"""The head: control plane of a ray_tpu "cluster".

The reference splits its control plane across three daemons — GCS (cluster
metadata, ``src/ray/gcs/gcs_server/gcs_server.cc:187``), per-node raylets
(scheduling + worker pools, ``src/ray/raylet/node_manager.cc``), and a plasma
store — talking gRPC. On a TPU pod the topology is static and every data-plane
byte that matters moves over ICI inside compiled XLA programs, so the
host-side control plane can be radically simpler: one Head object living in
the driver process, with worker processes attached over a unix socket.

It still implements the same *capabilities*, each tagged with its reference
counterpart:

* cluster membership + logical resources per node      (GcsNodeManager /
  ClusterResourceManager)
* hybrid pack/spread scheduling, spread + node-affinity + placement-group
  strategies                                           (cluster_task_manager.cc,
  scheduling/policy/*)
* worker pools with on-demand spawn + idle reuse       (worker_pool.h:152)
* dependency-gated dispatch                            (dependency_manager.h)
* object directory w/ inline + shm locations, waiters  (memory_store +
  plasma + ownership directory)
* task retries, worker-crash detection, actor restart
  state machine, named/detached actors                 (task_manager.cc,
  gcs_actor_manager.cc, gcs_health_check_manager.h)
* placement groups PACK/SPREAD/STRICT_*                (gcs_placement_group_*)
* function table, KV store                             (GCS internal KV)

Multi-"node" test clusters add virtual nodes to the same Head
(cluster_utils.Cluster mirrors the reference's ``cluster_utils.py:108``).
"""

from __future__ import annotations

import itertools
import os
import queue
import threading
import time
from collections import deque
from typing import Any, Optional

from ray_tpu import exceptions as rex
from ray_tpu._private import events
from ray_tpu._private import serialization as ser
from ray_tpu._private import config as _cfg
from ray_tpu._private.config import GLOBAL_CONFIG
from ray_tpu._private.proc_handles import ForkedProc, TemplateProc, spawn_template
from ray_tpu._private.ids import ActorID, NodeID, ObjectID, PlacementGroupID, TaskID
from ray_tpu._private.log_util import warn_throttled
from ray_tpu._private.shm_store import ShmLocation, ShmOwner
from ray_tpu.util import waterfall as _waterfall

#: raylint RL012 registry — batch-plane telemetry the head folds (ISSUE 14):
#: one observation per submit window / reply batch, documented in
#: OBSERVABILITY.md beside the waterfall legs they shrink; plus the
#: locality-aware scheduler (ISSUE 18): fraction of ref-arg task placements
#: that landed on a node already holding the args' bytes
METRIC_NAMES = (
    "core_submit_batch_size",
    "core_reply_batch_size",
    "core_sched_locality_hit_rate",
    # object-plane ledger (ISSUE 19): per-node arena/spill residency, the
    # leak-audit verdict, object lifetime distribution, and spill churn
    "core_arena_used_bytes",
    "core_arena_capacity_bytes",
    "core_arena_pinned_bytes",
    "core_arena_occupancy",
    "core_spill_bytes",
    "core_object_leaks",
    "core_object_age_s",
    "core_object_spills",
)

#: flight-recorder events this module emits (raylint RL012 registry) — the
#: directory half of the ``core.object.*`` lifecycle family (ISSUE 19):
#: a driver put landing in head shm, a locator entering the directory,
#: spill/restore transitions, a backing reaped by loss handling, and a
#: directory entry freed (forensic tail also kept in ``_freed_ring``).
EVENT_NAMES = (
    "core.object.put",
    "core.object.locator",
    "core.object.spill",
    "core.object.restore",
    "core.object.reap",
    "core.object.free",
)

#: raylint RL017 registry — DELIBERATE lock-free shared state, verified by
#: the linter (':atomic' = every write is one GIL-atomic operation; see
#: LINTING.md "thread/ownership model"). Each entry is a design decision:
#:
#: - _io_conns: conn -> (handle, remote) registered by conn threads with a
#:   plain dict store and reaped by the selector owner; readers take an
#:   atomic dict() snapshot and re-sync off the generation counter — a
#:   lock here would put every worker registration in the pump corridor.
#: - _outbox: deque of worker-bound sends, appended under the head lock,
#:   drained by the single _flush_lock holder; deque append/popleft are
#:   GIL-atomic, which is exactly why the outbox is a deque.
#: - ClientSession.refs/.actors: written only by the session's OWN conn
#:   thread while connected (one thread per client conn — _session_track
#:   docstring); the health loop's expiry sweep runs only after the grace
#:   window, when that conn thread is gone.
LOCKFREE = (
    "Head._io_conns: atomic",
    "Head._outbox: atomic",
    "ClientSession.refs: atomic",
    "ClientSession.actors: atomic",
)

#: Canonical lock order of the head IO-drain plane (ISSUE 14 / PR 14),
#: outermost first — RL010 checks every acquisition edge against it.
#: ``_pump_mutex`` sits outside everything: whoever owns the pump (the IO
#: thread or a pumping getter) dispatches worker messages that take the
#: head lock; the reverse never happens (getters PARK the pump request
#: counter, they do not acquire the pump mutex under the head lock, and
#: the IO thread's own acquire is bounded). ``_flush_lock`` serializes the
#: single outbox drainer, which then takes per-worker send locks; the
#: head lock is never held across a flush's socket writes (the round-2
#: tasks/s ceiling this architecture removed).
LOCK_ORDER = (
    "Head._pump_mutex",        # pump ownership (IO thread / pumping getter)
    "Head.lock",               # cluster state critical section
    "Head._flush_lock",        # single active outbox drainer
    "WorkerHandle.send_lock",  # one writer per worker conn
    "ShmOwner._lock",          # object-store ledger; never calls back up
)

_BATCH_BOUNDARIES = (1, 2, 4, 8, 16, 32, 64, 128, 256, 512)
_BATCH_METRICS = None
_BATCH_METRICS_LOCK = threading.Lock()


def _batch_metrics() -> dict:
    global _BATCH_METRICS
    if _BATCH_METRICS is not None:
        return _BATCH_METRICS
    with _BATCH_METRICS_LOCK:
        if _BATCH_METRICS is None:
            from ray_tpu.util.metrics import Histogram

            _BATCH_METRICS = {
                "submit": Histogram(
                    "core_submit_batch_size",
                    "tasks per pipelined submit window received by the head",
                    boundaries=_BATCH_BOUNDARIES,
                ),
                "reply": Histogram(
                    "core_reply_batch_size",
                    "completions per coalesced worker reply message",
                    boundaries=_BATCH_BOUNDARIES,
                ),
            }
    return _BATCH_METRICS


_LOCALITY_GAUGE = None


def _locality_gauge():
    # no init lock needed: only ever touched under the head lock (_pick_node)
    global _LOCALITY_GAUGE
    if _LOCALITY_GAUGE is None:
        from ray_tpu.util.metrics import Gauge

        _LOCALITY_GAUGE = Gauge(
            "core_sched_locality_hit_rate",
            "fraction of ref-arg task placements that landed on a node "
            "already holding the args' shm bytes",
        )
    return _LOCALITY_GAUGE


#: object age buckets: sub-minute churn through multi-hour residents
_OBJECT_AGE_BOUNDARIES = (1, 5, 15, 60, 300, 900, 3600, 14400)
_OBJECT_METRICS = None


def _object_metrics() -> dict:
    # no init lock needed: only ever touched under the head lock (health
    # loop tick, spill path, ledger/audit RPCs)
    global _OBJECT_METRICS
    if _OBJECT_METRICS is None:
        from ray_tpu.util.metrics import Counter, Gauge, Histogram

        _OBJECT_METRICS = {
            "arena_used": Gauge(
                "core_arena_used_bytes",
                "bytes allocated in a node's native object arena",
                tag_keys=("node",),
            ),
            "arena_capacity": Gauge(
                "core_arena_capacity_bytes",
                "a node's native object arena capacity",
                tag_keys=("node",),
            ),
            "arena_pinned": Gauge(
                "core_arena_pinned_bytes",
                "arena bytes currently pinned by live readers on a node",
                tag_keys=("node",),
            ),
            "arena_occupancy": Gauge(
                "core_arena_occupancy",
                "worst-node arena used/capacity ratio (the arena-pressure "
                "SLO gauge)",
            ),
            "spill_bytes": Gauge(
                "core_spill_bytes",
                "bytes of directory objects currently spilled to a node's "
                "disk",
                tag_keys=("node",),
            ),
            "leaks": Gauge(
                "core_object_leaks",
                "findings of the last object-plane leak audit (orphaned "
                "arena bytes / stale pins / dangling locators / orphaned "
                "spill files)",
            ),
            "age": Histogram(
                "core_object_age_s",
                "lifetime of directory objects at free/evict",
                boundaries=_OBJECT_AGE_BOUNDARIES,
            ),
            "spills": Counter(
                "core_object_spills",
                "directory objects spilled to disk under arena pressure "
                "(the spill-burn SLO counter)",
            ),
        }
    return _OBJECT_METRICS


# --------------------------------------------------------------------------
# Object directory


class ObjectEntry:
    __slots__ = (
        "small", "shm", "is_error", "refcount", "pins", "size",
        "spill_path", "last_access", "last_read", "borrow_nonces", "lineage",
        "created",
    )

    def __init__(self):
        self.small: Optional[bytes] = None
        self.shm: Optional[ShmLocation] = None
        self.is_error = False
        self.refcount = 0  # driver-side ObjectRef count
        self.pins = 0  # pending-task dependency pins
        self.size = 0
        self.created = time.time()  # wall time: ledger ages are user-facing
        self.spill_path: Optional[str] = None  # on-disk copy (spilled)
        self.last_access = 0.0
        self.last_read = 0.0  # read lease: guards just-handed-out locators
        # in-transit borrow nonces: a serialized ref holds one count until
        # the (first) deserializer claims it (reference: borrower registration
        # in core_worker/reference_count.h:61)
        self.borrow_nonces: Optional[set] = None
        # creating-task spec for lineage reconstruction (reference:
        # object_recovery_manager.h:41 rebuilds lost objects by resubmitting
        # the task; task_manager.cc lineage). None for ray.put objects.
        self.lineage: Optional[dict] = None

    @property
    def ready(self) -> bool:
        return self.small is not None or self.shm is not None or self.spill_path is not None

    def locator(self):
        if self.small is not None:
            return ("inline", self.small, self.is_error)
        return ("shm", self.shm, self.is_error)


# --------------------------------------------------------------------------
# Nodes / workers


class _WorkerProc:
    """Subprocess handle with the process API the head expects
    (pid / is_alive / terminate / join)."""

    __slots__ = ("popen", "pid")

    def __init__(self, popen):
        self.popen = popen
        self.pid = popen.pid

    def is_alive(self) -> bool:
        return self.popen.poll() is None

    def terminate(self):
        try:
            self.popen.terminate()
        except OSError:
            pass

    def join(self, timeout=None):
        try:
            self.popen.wait(timeout=timeout)
        except Exception:
            pass


# forkserver process handles (ForkedProc / TemplateProc / spawn_template)
# live in proc_handles.py — shared with node_agent for remote hosts


class WorkerHandle:
    """A connected worker process (reference: raylet's WorkerInterface)."""

    _ids = itertools.count()

    def __init__(self, node: "NodeState", proc, conn=None):
        self.wid = next(WorkerHandle._ids)
        self.node = node
        self.proc = proc  # _WorkerProc (None for remote workers)
        self.conn = conn  # set at registration
        self.alive = True
        self.current_task: Optional[dict] = None
        # FIFO of dispatched-but-not-done task recs (the worker executes in
        # order; current_task mirrors the head). More than one entry means
        # the worker is PIPELINED: followers ride the head task's resource
        # lease and the alloc transfers down the chain at each completion
        # (reference: lease-based pipelined submission,
        # max_tasks_in_flight_per_worker in the direct task submitter).
        self.queued_recs: deque = deque()
        # (signature, func_id) the current pipeline accepts; None = worker
        # not leaseable (mixed queue, strategy task, or empty)
        self.lease_sig: Optional[tuple] = None
        # in-flight blocking get/wait RPCs from this worker: a worker parked
        # in ray.get must not receive lease followers (nested-submit deadlock)
        self.blocked_gets = 0
        self.actor_id: Optional[bytes] = None
        self.idle_since = time.monotonic()
        self.created_at = time.monotonic()
        self.send_lock = threading.Lock()
        # startup token: matches a spawned process to its pre-created handle
        # at registration (reference: worker_pool.h startup_token) — the only
        # correlation that works for workers spawned on REMOTE hosts, where
        # the head never sees a pid
        self.token: Optional[str] = None
        # spawned via the node's forkserver template: the pid (unknown until
        # registration) becomes a ForkedProc so kill/join paths work
        self.forked = False
        # which attempt of a spawn chain this handle is (0 = first); bounds
        # registration-timeout respawns (reference: worker_register_timeout_seconds)
        self.spawn_attempts = 0
        # spec-header ids this worker already holds (cheaper per-task bytes:
        # flush_outbox ships a function's static spec fields once per
        # worker, steady-state run_task bodies reference them by id). Only
        # the single active flush_outbox drainer mutates this.
        self.sent_hdrs: set = set()
        # spec headers THIS worker's submit_batch messages defined (the
        # submitter side of the same split, keyed per connection)
        self.submit_hdrs: dict = {}

    def send(self, msg) -> bool:
        try:
            with self.send_lock:
                ser.conn_send(self.conn, msg)
            return True
        except (OSError, ValueError, BrokenPipeError):
            return False


class AgentHandle:
    """Connection to a remote node's agent daemon (spawns workers there)."""

    def __init__(self, conn):
        self.conn = conn
        self.send_lock = threading.Lock()

    def send(self, msg) -> bool:
        try:
            with self.send_lock:
                self.conn.send(msg)
            return True
        except (OSError, ValueError, BrokenPipeError):
            return False


def _close_listener(listener) -> None:
    """Close an mp.connection Listener so its PORT is actually released.

    ``Listener.close()`` alone leaves the socket listening while another
    thread is blocked in ``accept()`` (the in-flight syscall pins the
    socket), so a restarted head could never rebind the address. A
    ``shutdown(SHUT_RDWR)`` first wakes the accepter, then close releases
    the fd."""
    import socket as _socket

    try:
        sock = listener._listener._socket
        sock.shutdown(_socket.SHUT_RDWR)
    except (OSError, AttributeError):
        pass
    try:
        listener.close()
    except Exception:
        pass


class NodeState:
    def __init__(self, node_id: NodeID, resources: dict[str, float], labels=None):
        self.node_id = node_id
        self.created_at = time.monotonic()
        self.agent: Optional[AgentHandle] = None  # set for remote nodes
        self.resources_total = dict(resources)
        self.resources_avail = dict(resources)
        self.labels = labels or {}
        self.alive = True
        self.dispatching = 0  # spawns handed to a thread, handle not yet visible
        # (host, port) of the node's data-plane server (agent nodes only;
        # head-host nodes are served by the head's own DataServer)
        self.data_address: Optional[tuple] = None
        # latest /proc sample for this node's host (reporter.node_stats)
        self.stats: dict = {}
        self.idle_workers: list[WorkerHandle] = []
        self.all_workers: set[WorkerHandle] = set()
        self.spawning = 0
        # forkserver template for this node (head-host nodes only; agent
        # hosts run their own template) — see worker_template.py
        self.template: Optional[TemplateProc] = None
        self.assigned: deque = deque()  # tasks waiting for a worker on this node
        # placement-group reservations: pg_id -> bundle_index -> avail dict
        self.pg_reserved: dict[bytes, dict[int, dict[str, float]]] = {}

    def can_fit(self, res: dict[str, float]) -> bool:
        return all(self.resources_avail.get(k, 0.0) + 1e-9 >= v for k, v in res.items() if v > 0)

    def allocate(self, res: dict[str, float]) -> None:
        for k, v in res.items():
            self.resources_avail[k] = self.resources_avail.get(k, 0.0) - v

    def release(self, res: dict[str, float]) -> None:
        for k, v in res.items():
            self.resources_avail[k] = min(
                self.resources_avail.get(k, 0.0) + v, self.resources_total.get(k, 0.0)
            )

    def utilization(self, res: dict[str, float]) -> float:
        """Max utilization over the resources this task needs (reference:
        hybrid policy's critical-resource utilization)."""
        u = 0.0
        for k, v in res.items():
            if v <= 0:
                continue
            total = self.resources_total.get(k, 0.0)
            if total <= 0:
                return 1.0
            u = max(u, 1.0 - (self.resources_avail.get(k, 0.0) - v) / total)
        return u


# --------------------------------------------------------------------------
# Actors


ACTOR_PENDING, ACTOR_RESTARTING, ACTOR_ALIVE, ACTOR_DEAD = range(4)


class ActorState:
    def __init__(self, actor_id: bytes, create_spec: dict):
        self.actor_id = actor_id
        self.create_spec = create_spec
        self.state = ACTOR_PENDING
        self.worker: Optional[WorkerHandle] = None
        self.node_id: Optional[NodeID] = None
        self.restarts_left = create_spec.get("max_restarts", 0)
        self.max_task_retries = create_spec.get("max_task_retries", 0)
        self.name = create_spec.get("name")
        # named actors are NAMESPACE-scoped (reference: ray namespaces —
        # each ray:// client session gets an anonymous namespace unless it
        # asks for one, so concurrent clients don't see each other's names)
        self.namespace = create_spec.get("namespace") or "default"
        self.detached = create_spec.get("lifetime") == "detached"
        self.pending_calls: deque = deque()  # method specs queued while not ALIVE
        self.inflight: dict[bytes, dict] = {}  # task_id -> spec sent to worker
        self.num_handles = 1
        self.death_cause: Optional[str] = None
        self.alloc = None  # lifetime resource allocation (held until death)

    @property
    def named_key(self) -> Optional[str]:
        return None if not self.name else f"{self.namespace}:{self.name}"


class ClientSession:
    """One ``ray://`` client's server-side state (reference: the client
    proxier's per-client SpecificServer, ``util/client/server/proxier.py``).
    Tracks what the client owns so a disconnect without reconnect releases
    it: object refcounts taken on the client's behalf and actors it created.
    ``disconnected_at`` arms the grace timer; a reconnect presenting the
    session token disarms it and resumes with every ref intact."""

    def __init__(self, token: str, namespace: str):
        self.token = token
        self.namespace = namespace
        self.refs: dict[bytes, int] = {}
        self.actors: set[bytes] = set()
        self.conn = None
        self.disconnected_at: Optional[float] = None
        self.created_at = time.monotonic()
        # spec headers this client's submit_batch messages defined (survives
        # a reconnect-with-token: the client's header ids stay valid)
        self.submit_hdrs: dict = {}


# --------------------------------------------------------------------------
# Placement groups

PG_PENDING, PG_CREATED, PG_REMOVED = range(3)


class PlacementGroupState:
    def __init__(self, pg_id: bytes, bundles: list[dict], strategy: str, name: str = ""):
        self.pg_id = pg_id
        self.bundles = bundles
        self.strategy = strategy
        self.name = name
        self.state = PG_PENDING
        self.bundle_nodes: list[Optional[NodeID]] = [None] * len(bundles)
        self.ready_event = threading.Event()


# --------------------------------------------------------------------------


class _PendingQueue:
    """Dep-free tasks awaiting a node, grouped by scheduling signature.

    The earlier scheduler kept one deque and rescanned it IN FULL on every
    submit and every completion — O(queue) per event, O(n²) across a burst,
    and the direct reason async task submission benchmarked SLOWER than
    sync round-trips. Tasks with identical (resources, strategy, labels)
    are interchangeable for placement, so they share one FIFO bucket and a
    scheduling pass visits each DISTINCT signature once: a 10k-deep
    homogeneous backlog costs one placement attempt per event, not 10k
    (reference: raylet groups tasks into scheduling classes the same way —
    SchedulingClass, common/task/task_spec.h).

    FIFO order holds within a signature; across signatures dispatch is
    round-robin (the reference makes no global-FIFO promise either).
    """

    def __init__(self):
        self._buckets: dict[tuple, deque] = {}
        self._order: list[tuple] = []
        self._len = 0
        # sig -> scheduling generation at which placement last failed: a
        # pass skips sigs that already failed in the CURRENT generation
        # (nothing freed since, so the answer cannot have changed) — this
        # makes submit-into-a-saturated-cluster O(1) instead of one doomed
        # placement probe per submit
        self._blocked: dict[tuple, int] = {}

    @staticmethod
    def _sig(spec: dict) -> tuple:
        sig = spec.get("_sig0")
        if sig is not None:
            return sig  # template-cached (resources/strategy are static)
        res = spec.get("resources") or {}
        strat = spec.get("strategy")
        lbl = spec.get("label_selector")
        return (
            tuple(sorted((k, v) for k, v in res.items() if v != 0)),
            tuple(strat) if strat else None,
            tuple(sorted(lbl.items())) if lbl else None,
            spec.get("kind") == "actor_create",
        )

    @staticmethod
    def sig_of(rec: dict) -> tuple:
        sig = rec.get("_sig")
        if sig is None:
            sig = rec["_sig"] = _PendingQueue._sig(rec["spec"])
        return sig

    def append(self, rec: dict) -> None:
        sig = self.sig_of(rec)
        q = self._buckets.get(sig)
        if q is None:
            q = self._buckets[sig] = deque()
            self._order.append(sig)
        q.append(rec)
        self._len += 1

    def schedule_pass(self, try_place, gen: int = -1) -> None:
        """``try_place(rec) -> bool``: True consumes the head of a bucket
        (placed, or dropped as cancelled); False blocks that signature until
        the scheduling generation advances (resources freed / nodes
        changed)."""
        for sig in list(self._order):
            if self._blocked.get(sig) == gen:
                continue
            q = self._buckets.get(sig)
            blocked = False
            while q:
                if try_place(q[0]):
                    q.popleft()
                    self._len -= 1
                else:
                    self._blocked[sig] = gen
                    blocked = True
                    break
            if not blocked:
                self._blocked.pop(sig, None)
            if not q:
                del self._buckets[sig]
                self._order.remove(sig)
                self._blocked.pop(sig, None)

    def __len__(self) -> int:
        return self._len

    def __bool__(self) -> bool:
        return self._len > 0

    def __iter__(self):
        for sig in self._order:
            yield from self._buckets.get(sig, ())


class _DaemonPool:
    """Cached pool of DAEMON threads for blocking RPCs.

    ThreadPoolExecutor is unsuitable here: its non-daemon workers are joined
    at interpreter exit, so one ``get``/``wait``/``pg_ready`` parked forever
    (timeout=None on something never produced) would hang process exit —
    the per-call threads this replaces were daemons for exactly that reason.
    Threads spawn on demand up to ``max_workers``, reap after 30s idle, and
    print handler crashes (a submitted-and-forgotten Future would swallow
    them)."""

    _IDLE_REAP_S = 30.0

    def __init__(self, max_workers: int, name: str):
        self._q: "queue.SimpleQueue" = queue.SimpleQueue()
        self._lock = threading.Lock()
        self._threads = 0
        self._idle = 0
        # Items put but not yet claimed by a worker (claimed = the worker has
        # taken the lock after q.get returned). Spawning on
        # ``unclaimed > idle`` instead of ``idle == 0`` closes the window
        # where a worker has returned from q.get but not yet decremented
        # _idle: counting that item as still-unclaimed forces a spawn, so a
        # handler that then parks forever cannot strand the queued item.
        self._unclaimed = 0
        self._max = max_workers
        self._name = name

    def submit(self, fn, *args) -> None:
        with self._lock:
            self._unclaimed += 1
            self._q.put((fn, args))
            if self._unclaimed > self._idle and self._threads < self._max:
                self._threads += 1
                threading.Thread(target=self._run, name=self._name, daemon=True).start()

    def _run(self) -> None:
        import traceback as _tb

        while True:
            with self._lock:
                self._idle += 1
            try:
                item = self._q.get(timeout=self._IDLE_REAP_S)
            except queue.Empty:
                with self._lock:
                    self._idle -= 1
                    # a put may have raced the timeout: keep serving if work
                    # arrived (the lock orders this against submit's check).
                    # The loop top re-increments _idle — do NOT add it back
                    # here or the thread is counted idle twice forever.
                    if self._unclaimed > 0:
                        continue
                    self._threads -= 1
                return
            with self._lock:
                self._idle -= 1
                if item is not None:
                    self._unclaimed -= 1
                else:
                    self._threads -= 1
            if item is None:
                return
            fn, args = item
            try:
                fn(*args)
            except Exception:  # noqa: BLE001 - must never kill the pool thread
                _tb.print_exc()

    def shutdown(self) -> None:
        with self._lock:
            n = self._threads
        for _ in range(n):
            self._q.put(None)


class Head:
    def __init__(self, socket_path: str, authkey: bytes):
        self.lock = threading.RLock()
        self.cv = threading.Condition(self.lock)  # object readiness + pg + actor events
        self.socket_path = socket_path
        self.authkey = authkey
        self.shm_owner = ShmOwner()
        self._snapshot_path = GLOBAL_CONFIG.gcs_snapshot_path or None
        # Native object arena (plasma equivalent, ray_tpu/_native/arena.cc):
        # one shared segment for this host's small/medium objects. None when
        # disabled or the native build is unavailable (pure-Python fallback:
        # a dedicated segment per object).
        self.arena_name: Optional[str] = None
        if GLOBAL_CONFIG.object_store_arena_bytes > 0:
            from ray_tpu._private import shm_store as _shm

            self.arena_name = _shm.create_arena(GLOBAL_CONFIG.object_store_arena_bytes)

        self.objects: dict[bytes, ObjectEntry] = {}
        # forensic tail of the object ledger (ISSUE 19): the newest freed
        # entries — (oid hex, size, age_s, freed wall time, reason) — so
        # ``obs objects`` can show what JUST left the directory. Appended
        # under the head lock; bounded.
        self._freed_ring: deque = deque(maxlen=256)
        self.functions: dict[bytes, bytes] = {}  # func table (reference: GCS fn table)
        self.kv: dict[str, bytes] = {}
        # pubsub: channel -> sinks; a sink is ("conn", conn) for socket
        # clients or ("fn", callable) for in-process subscribers (reference:
        # src/ray/pubsub/ long-poll channels, GCS actor/node update feeds)
        self._subs: dict[str, list] = {}
        self._pub_locks: dict[int, threading.Lock] = {}
        self._pub_queue: "queue.Queue" = queue.Queue()
        # cap >> any realistic concurrent-blocking-RPC count; parked gets
        # hold a thread each, so the cap must stay generous (a too-small
        # pool would queue NEW gets behind parked ones)
        self._blocking_pool = _DaemonPool(4096, "head-rpc")
        # worker-spawn dispatch: Thread.start() must NEVER run under the head
        # lock — start() blocks until the child's bootstrap sets _started, and
        # a GC tick in that bootstrap window used to re-enter the head lock
        # via ObjectRef.__del__, wedging the whole head. Spawn requests are
        # queued here and started by a dedicated dispatcher thread instead.
        self._spawn_q: "queue.SimpleQueue" = queue.SimpleQueue()
        threading.Thread(
            target=self._spawn_dispatch_loop, name="spawn-dispatch", daemon=True
        ).start()
        self._snapshot_due = 0.0
        # detached actors restored from a snapshot, waiting for their old
        # worker to reconnect; past the grace window they re-create fresh
        self._restored_actors: set[bytes] = set()
        self._restore_time = time.monotonic()
        self._lineage_fifo: deque = deque()
        self._lineage_total = 0
        self.nodes: dict[bytes, NodeState] = {}
        self.node_order: list[bytes] = []
        self.actors: dict[bytes, ActorState] = {}
        # named actors, keyed "namespace:name" (see ActorState.named_key)
        self.named_actors: dict[str, bytes] = {}
        # cluster-wide named mutexes: name -> (owner_token, lease_expiry)
        self._named_mutexes: dict[str, tuple] = {}
        # ray:// client sessions by token (ClientSession); cleanup of a
        # disconnected session happens in the health loop after the grace
        self.client_sessions: dict[str, ClientSession] = {}
        self.placement_groups: dict[bytes, PlacementGroupState] = {}
        if self._snapshot_path:
            self._load_snapshot()  # after the tables above exist

        # tasks waiting on deps: obj_id -> set of task records
        self.dep_waiters: dict[bytes, set] = {}
        # dispatch outbox: worker-bound messages enqueued under the head
        # lock, flushed by the enqueuing caller right after it releases it
        # (see flush_outbox) — a socket write + spec pickle inside the
        # critical section would serialize every conn thread behind each
        # dispatch (the round-2 tasks/s ceiling)
        self._outbox: deque = deque()
        self._flush_lock = threading.Lock()
        self._flush_event = threading.Event()
        # selector-served worker connections: conn -> (WorkerHandle, remote)
        self._io_conns: dict = {}
        # bumped on every _io_conns mutation: drain callers re-sync their
        # selector only when this moved (the dict snapshot + key compare
        # were ~1.5us per pump — per sync task — with a stable conn set).
        # Bumps draw from an itertools.count and PUBLISH with a plain
        # store: two conns adopted/reaped concurrently (a registration
        # burst racing a reap) each land a DISTINCT generation, where the
        # old `+= 1` read-modify-write could collapse both bumps into one
        # value (found by raylint RL017)
        self._io_gen_src = itertools.count(1)
        self._io_conns_gen = 0
        # per-conn buffered framed readers (ser.ConnReader): one kernel
        # read per drain round instead of two syscalls per message; owned
        # by whoever holds _pump_mutex, reaped with the conn
        self._io_readers: dict = {}
        self._io_thread: Optional[threading.Thread] = None
        # worker-conn pump ownership (see _pump_or_wait): a blocked getter
        # may take over the IO thread's job so a completion wakes the getter
        # DIRECTLY instead of via IO-thread-handles-then-notifies — one
        # fewer thread handoff on the sync task round trip
        self._pump_mutex = threading.Lock()
        self._pump_count_lock = threading.Lock()
        self._pump_requests = 0
        self._last_pump = 0.0  # sticky grace: IO thread defers while fresh
        self._io_resume = threading.Event()
        self._io_wake_r, self._io_wake_w = os.pipe()
        os.set_blocking(self._io_wake_w, False)
        # progress signal TO pumpers: whoever processed worker messages
        # while getters were waiting writes here, so a pumper whose object
        # became ready in the handoff window doesn't sit out its select
        # timeout against conns that will stay silent
        self._io_prog_r, self._io_prog_w = os.pipe()
        os.set_blocking(self._io_prog_w, False)
        # persistent selector for pumpers (guarded by _pump_mutex):
        # multiprocessing.connection.wait builds+tears down a poll object
        # per call — real money at 1 call per sync task
        import selectors as _selectors

        self._pump_sel = _selectors.DefaultSelector()
        self._pump_sel.register(self._io_prog_r, _selectors.EVENT_READ)
        self._pump_registered: set = set()
        self._pump_reg_gen = [-1]  # _io_conns generation the pump last synced
        self.pending_sched = _PendingQueue()  # dep-free tasks awaiting node pick
        # bumped whenever placement capacity can have INCREASED (release,
        # node add, pg placement): lets _schedule skip signatures that
        # already failed in the current generation
        self._sched_gen = 0
        # locality-aware placement accounting (ISSUE 18): of the placements
        # whose ref args had bytes resident on some node, how many landed on
        # a byte-holding node (feeds core_sched_locality_hit_rate)
        self._loc_hits = 0
        self._loc_total = 0
        # actor_id -> actor_create rec awaiting its dedicated worker
        self._actor_create_recs: dict[bytes, dict] = {}
        self.tasks: dict[bytes, dict] = {}  # task_id -> record (pending/running)
        self.cancelled: set[bytes] = set()

        self._shutdown = False
        self._listener = None
        self._tcp_listener = None
        self.tcp_address: Optional[tuple] = None
        # data plane (peer-to-peer bulk object transfer, data_plane.py):
        # started alongside the TCP control plane; the head then acts as the
        # object DIRECTORY only — bytes move host-to-host directly
        # (reference: object_manager.h:117 + gcs object locations)
        self.data_server = None
        self.data_port: Optional[int] = None
        #: bytes the head itself shipped inline for remote readers — the
        #: legacy funnel path, kept as a fallback; the p2p test asserts this
        #: stays 0 when the data plane is healthy
        self.inline_bytes_served = 0
        self._threads: list[threading.Thread] = []
        self._conn_worker: dict[Any, WorkerHandle] = {}
        # startup tokens invalidated by a registration timeout: a late
        # registration bearing one is told to exit instead of joining the
        # pool (bounded; pruned oldest-first in _respawn_timed_out)
        self._revoked_tokens: dict[str, bool] = {}
        # agent worker-stack-dump rendezvous: req_id -> {pid: stacks}
        self._stacks_replies: dict[str, dict] = {}
        self._stacks_cv = threading.Condition()
        self.task_events: list[dict] = []  # observability feed (state API)
        # metric time-series store + SLO alert engine (both lazy: created on
        # first push/query so clusters that never look pay ~nothing)
        self._metric_series = None
        self._alerts = None
        self._infeasible_warned: dict[bytes, float] = {}
        # streaming-generator returns: task_id -> {"items": {index: obj_id},
        # "count": Optional[int] (set at completion), "next": next index a
        # consumer will ask for} (reference: task_manager.cc streaming
        # generator bookkeeping, _raylet.pyx:1230)
        self.streams: dict[bytes, dict] = {}
        # disposed stream ids (bounded): late stream_items/task_done from a
        # producer that had not yet seen the cancel must NOT resurrect the
        # stream entry (it would leak the items forever — nobody consumes a
        # disposed stream); their objects are freed on arrival instead
        self._disposed_streams: dict[bytes, bool] = {}

    # ---------------------------------------------------------------- wiring

    def start(self):
        from multiprocessing.connection import Listener

        self._listener = Listener(self.socket_path, family="AF_UNIX", authkey=self.authkey)
        t = threading.Thread(
            target=self._accept_loop, args=(self._listener, False),
            name="head-accept", daemon=True,
        )
        t.start()
        self._threads.append(t)
        h = threading.Thread(target=self._health_loop, name="head-health", daemon=True)
        h.start()
        self._threads.append(h)
        pub = threading.Thread(target=self._publisher_loop, name="head-pub", daemon=True)
        pub.start()
        self._threads.append(pub)
        fb = threading.Thread(
            target=self._flush_backstop_loop, name="head-flush-backstop", daemon=True
        )
        fb.start()
        self._threads.append(fb)
        if os.environ.get("RAY_TPU_ALERTS", "1").lower() not in ("0", "false", "off"):
            al = threading.Thread(
                target=self._alerts_loop, name="head-alerts", daemon=True
            )
            al.start()
            self._threads.append(al)
        if GLOBAL_CONFIG.memory_monitor_refresh_ms > 0:
            m = threading.Thread(
                target=self._memory_monitor_loop, name="head-memmon", daemon=True
            )
            m.start()
            self._threads.append(m)

    def listen_tcp(self, host: str = "0.0.0.0", port: int = 0) -> tuple[str, int]:
        """Open the TCP control plane beside the unix socket (same message
        protocol; reference: the gRPC ports every daemon exposes,
        ``services.py:1421``). Connections arriving here are REMOTE: object
        locators are converted to inline payloads for them (no cross-host
        shm)."""
        from multiprocessing.connection import Listener

        self._tcp_listener = Listener((host, port), authkey=self.authkey)
        self.tcp_address = self._tcp_listener.address
        if self.data_server is None:
            from ray_tpu._private.data_plane import DataServer

            self.data_server = DataServer(self.authkey, host)
            self.data_port = self.data_server.port
        t = threading.Thread(
            target=self._accept_loop, args=(self._tcp_listener, True),
            name="head-accept-tcp", daemon=True,
        )
        t.start()
        self._threads.append(t)
        return self.tcp_address

    def _accept_loop(self, listener, remote: bool):
        while not self._shutdown:
            try:
                conn = listener.accept()
            except (OSError, EOFError):
                return
            except Exception as e:
                # A client that died mid-handshake (AuthenticationError) or
                # sent garbage must not kill the accept loop — that would
                # silently stop ALL future worker registration. Drop the
                # connection and keep accepting.
                warn_throttled("head accept loop", e)
                continue
            t = threading.Thread(
                target=self._serve_conn, args=(conn, remote), daemon=True
            )
            t.start()

    def _serve_conn(self, conn, remote: bool = False):
        """Per-connection thread for drivers and agents. A WORKER conn is
        handed to the shared selector loop at registration (one IO thread
        for all workers, like the reference raylet's single io_service) —
        a thread per worker makes every one of them a GIL competitor and
        measurably caps task throughput."""
        worker: Optional[WorkerHandle] = None
        agent_node: Optional[NodeID] = None
        session: Optional[ClientSession] = None
        handover = False
        try:
            while not self._shutdown:
                try:
                    msg = conn.recv()
                except (EOFError, OSError):
                    break
                kind = msg[0]
                if kind == "register":
                    worker = self._on_register(conn, msg[1], remote=remote)
                    self.flush_outbox()
                    if worker is None:
                        break  # rejected (unknown node): close so it retries
                    self._adopt_worker_conn(conn, worker, remote)
                    worker = None  # selector owns disconnect handling now
                    handover = True
                    return
                elif kind == "register_agent":
                    agent_node = self._on_register_agent(conn, msg[1])
                elif kind == "register_driver":
                    session = self._on_register_driver(conn, msg[1])
                elif kind == "agent_stats":
                    if agent_node is not None:
                        with self.lock:
                            n = self.nodes.get(agent_node.binary())
                            if n is not None:
                                n.stats = msg[1]
                elif kind == "worker_stacks":
                    self._mailbox_post(msg[1]["req_id"], msg[1]["stacks"])
                elif kind == "submit_batch":
                    # pipelined submission from a ray:// driver session
                    self._on_submit_batch(
                        msg[1],
                        session.submit_hdrs if session is not None else {},
                        session=session,
                    )
                    self.flush_outbox()
                    with self._conn_lock(conn):
                        conn.send(("submit_ack", {"wid": msg[1]["wid"]}))
                elif kind == "req":
                    _, seq, method, payload = msg
                    if session is not None:
                        self._session_track(session, method, payload)
                    self._dispatch_request(conn, worker, seq, method, payload, remote=remote)
        finally:
            # close OUR side whatever ended the loop (rejection, peer EOF,
            # handler exception): a conn left open but unserved would park
            # the peer in recv forever instead of letting it retry
            if not handover:
                from ray_tpu._private.node_agent import shutdown_conn

                shutdown_conn(conn)
            if session is not None:
                self._on_client_disconnect(session, conn)
            if worker is not None:
                self._on_worker_disconnect(worker)
            if agent_node is not None:
                # agent death = node death (reference: raylet disconnect)
                try:
                    self.remove_node(agent_node)
                except Exception:
                    pass

    def _adopt_worker_conn(self, conn, wh: WorkerHandle, remote: bool) -> None:
        self._io_conns[conn] = (wh, remote)
        self._io_conns_gen = next(self._io_gen_src)
        try:
            os.write(self._io_wake_w, b"c")  # pick up the new conn now
        except OSError:
            pass
        with self.lock:
            if self._io_thread is None:
                self._io_thread = threading.Thread(
                    target=self._worker_io_loop, name="head-worker-io", daemon=True
                )
                self._io_thread.start()
                self._threads.append(self._io_thread)

    def _drain_io(
        self,
        sel,
        registered: set,
        special_fd: int,
        timeout: float,
        budget: int = 64,
        once: bool = False,
        reg_gen: Optional[list] = None,
    ) -> bool:
        """Shared selector-drain for the IO thread and pumping getters
        (caller must hold ``_pump_mutex``): sync ``registered`` with the
        live conn set on ``sel``, then drain ready messages — one recv per
        ready conn per select round, re-selecting at timeout 0 until quiet
        or ``budget`` messages (one chatty worker can't starve the rest). A
        readable ``special_fd`` (wake/progress pipe) is drained and ends
        the drain after the current event batch — the caller has a decision
        to make. Returns True when any worker message was handled."""
        # generation guard: with a stable conn set (every sync round trip)
        # the snapshot + key compare below are skipped entirely. A conn
        # adopted between the gen read and the snapshot is re-synced next
        # round (the stored gen is stale, and adopt writes the wake pipe so
        # the next select returns immediately).
        gen = self._io_conns_gen
        if reg_gen is not None and reg_gen[0] == gen:
            current = None
        else:
            # atomic C-level snapshot: _adopt_worker_conn inserts
            # concurrently, and iterating the live dict across threads can
            # raise "dictionary changed size during iteration" out of a
            # user's ray_tpu.get().
            current = dict(self._io_conns)
            if reg_gen is not None:
                reg_gen[0] = gen
        if current is not None and registered != current.keys():
            live = set(current)
            for c in registered - live:
                try:
                    sel.unregister(c)
                except (KeyError, ValueError, OSError):
                    pass
            for c in live - registered:
                try:
                    sel.register(c, 1)  # EVENT_READ
                except (ValueError, OSError):
                    self._reap_io_conn(c)
                    live.discard(c)
            registered.clear()
            registered.update(live)
        progressed = False
        while budget > 0:
            try:
                events = sel.select(timeout=timeout)
            except OSError:
                # a conn died mid-wait: find and reap it
                for c in list(registered):
                    if c.closed or c.fileno() < 0:
                        try:
                            sel.unregister(c)
                        except (KeyError, ValueError, OSError):
                            pass
                        registered.discard(c)
                        self._reap_io_conn(c)
                break
            if not events:
                break
            timeout = 0
            for key, _mask in events:
                conn = key.fileobj
                if conn == special_fd:
                    try:
                        os.read(special_fd, 4096)
                    except OSError:
                        pass
                    budget = 0
                    continue
                ent = self._io_conns.get(conn)
                if ent is None:
                    continue
                wh, remote = ent
                reader = self._io_readers.get(conn)
                if reader is None:
                    reader = self._io_readers[conn] = ser.ConnReader(conn)
                try:
                    # one kernel read, every complete frame parsed — a
                    # burst of coalesced replies costs one syscall, not
                    # two per message (Connection.recv's header+body)
                    msgs = reader.read_available()
                except (EOFError, OSError):
                    try:
                        sel.unregister(conn)
                    except (KeyError, ValueError, OSError):
                        pass
                    registered.discard(conn)
                    self._reap_io_conn(conn)
                    continue
                for msg in msgs:
                    progressed = True
                    budget -= 1
                    self._handle_worker_msg(conn, wh, remote, msg)
            if once and progressed:
                # pumping getter: its completion most likely just landed —
                # return to the readiness re-check instead of paying a
                # second (usually empty) selector round per sync get
                break
        return progressed

    def _worker_io_loop(self) -> None:
        """One selector thread serves EVERY worker connection.

        The selector is PERSISTENT (epoll): `multiprocessing.connection.wait`
        builds, registers, and tears down a fresh poll object per call —
        measurable per-message overhead once every completion wakes it. The
        conn set is re-synced only when `_io_conns` changes, and each ready
        conn is drained (bounded) before re-polling so a burst of
        completions costs one selector wakeup, not one per message."""
        import selectors

        sel = selectors.DefaultSelector()
        sel.register(self._io_wake_r, selectors.EVENT_READ)
        registered: set = set()
        reg_gen = [-1]
        while not self._shutdown:
            if self._pump_requests or (time.monotonic() - self._last_pump) < 0.003:
                # a getter owns the pump (it is doing this loop's job) or
                # pumped within the last few ms (a sync get loop: the next
                # pump is imminent) — park instead of ping-ponging the
                # mutex, which costs two context switches per task
                self._io_resume.wait(timeout=0.01)
                self._io_resume.clear()
                continue
            if not self._pump_mutex.acquire(timeout=0.1):
                continue
            try:
                progressed = self._drain_io(
                    sel, registered, self._io_wake_r, 0.1, reg_gen=reg_gen
                )
                if progressed:
                    self.flush_outbox()
                    if self._pump_requests:
                        try:
                            os.write(self._io_prog_w, b"g")
                        except OSError:
                            pass
            finally:
                self._pump_mutex.release()

    def _reap_io_conn(self, conn) -> None:
        self._io_readers.pop(conn, None)
        ent = self._io_conns.pop(conn, None)
        self._io_conns_gen = next(self._io_gen_src)
        if ent is not None:
            self._on_worker_disconnect(ent[0])
            self.flush_outbox()

    def _handle_worker_msg(self, conn, wh: WorkerHandle, remote: bool, msg) -> None:
        kind = msg[0]
        if kind == "task_done":  # hottest message first (one per task)
            self._on_task_done(wh, msg[1])
        elif kind == "req":
            _, seq, method, payload = msg
            self._dispatch_request(conn, wh, seq, method, payload, remote=remote)
        elif kind == "tasks_done_batch":
            self._on_task_done_batch(wh, msg[1])
        elif kind == "submit_batch":
            # pipelined nested submission from a worker: the whole window
            # lands in one critical section; the ack returns window credits
            # (per-window, never per-task)
            self._on_submit_batch(msg[1], wh.submit_hdrs)
            wh.send(("submit_ack", {"wid": msg[1]["wid"]}))
        elif kind == "stream_item":
            self._on_stream_item(wh, msg[1])
        elif kind == "actor_ready":
            self._on_actor_ready(wh, msg[1])
        elif kind == "profile_result":
            # shared reply mailbox with stack dumps; workers of one node
            # merge under their node's req_id
            self._mailbox_post(msg[1]["req_id"], {msg[1]["pid"]: msg[1]["profile"]})
        elif kind == "events_result":
            # flight-recorder drain replies ride the same mailbox
            self._mailbox_post(msg[1]["req_id"], {msg[1]["pid"]: msg[1]["events"]})
        elif kind == "object_report_result":
            # object-plane residency replies (ledger/audit rendezvous)
            self._mailbox_post(msg[1]["req_id"], {msg[1]["pid"]: msg[1]["report"]})

    def _mailbox_post(self, req_id: str, update: dict) -> None:
        """Merge a reply into the stacks/profile rendezvous mailbox. Bounded:
        replies landing after their caller timed out are never consumed —
        don't accumulate blobs (64 req_ids, not 64 workers: multiple workers
        of one node merge under one id)."""
        with self._stacks_cv:
            self._stacks_replies.setdefault(req_id, {}).update(update)
            while len(self._stacks_replies) > 64:
                self._stacks_replies.pop(next(iter(self._stacks_replies)))
            self._stacks_cv.notify_all()

    def _on_register_driver(self, conn, info: dict) -> ClientSession:
        """A ``ray://`` client attached (reference: the proxier's per-client
        server, ``util/client/server/proxier.py``). A presented session
        token RESUMES that session — same namespace, every ref intact; a
        fresh client gets a new token and an anonymous namespace unless it
        asked for one (reference namespace semantics)."""
        import uuid as _uuid

        token = (info or {}).get("session_token")
        with self.lock:
            session = self.client_sessions.get(token) if token else None
            if session is None:
                token = _uuid.uuid4().hex
                namespace = (info or {}).get("namespace") or f"anon-{token[:12]}"
                session = ClientSession(token, namespace)
                self.client_sessions[token] = session
            session.conn = conn
            session.disconnected_at = None  # reconnect disarms cleanup
        conn.send(
            (
                "driver_ack",
                {
                    "node_id": self._any_node_id(),
                    "session_token": session.token,
                    "namespace": session.namespace,
                },
            )
        )
        return session

    def _session_track(self, session: ClientSession, method: str, payload) -> None:
        """Attribute ref/actor ownership to the client session so a dirty
        disconnect can release exactly what the client held. Mirrors the
        refcounts the handlers themselves will take — kept in the conn
        thread, racing nothing (one thread per client conn)."""
        try:
            if method in ("submit_task", "submit_actor_task", "create_actor"):
                spec = payload["spec"]
                for rid in spec.get("return_ids", ()):
                    session.refs[rid] = session.refs.get(rid, 0) + 1
                if method == "create_actor":
                    session.actors.add(spec["actor_id"])
                    if not spec.get("namespace"):
                        spec["namespace"] = (
                            "default"
                            if spec.get("lifetime") == "detached"
                            else session.namespace
                        )
            elif method == "put" and payload.get("take_ref"):
                session.refs[payload["obj_id"]] = (
                    session.refs.get(payload["obj_id"], 0) + 1
                )
            elif method in ("add_ref",):
                session.refs[payload["obj_id"]] = (
                    session.refs.get(payload["obj_id"], 0) + 1
                )
            elif method in ("free_ref", "free_ref_async"):
                oid = payload["obj_id"]
                n = session.refs.get(oid, 0) - 1
                if n <= 0:
                    session.refs.pop(oid, None)
                else:
                    session.refs[oid] = n
            elif method in ("free_refs", "free_refs_async"):
                # the gc drain's COALESCED free (ISSUE 14): mirror the
                # batched decrement or session expiry double-frees refs
                # the client already dropped
                for oid in payload["obj_ids"]:
                    n = session.refs.get(oid, 0) - 1
                    if n <= 0:
                        session.refs.pop(oid, None)
                    else:
                        session.refs[oid] = n
            elif method == "get_actor_named" and payload.get("namespace") is None:
                # safety net: clients normally send their namespace, but a
                # None (pre-handshake or legacy caller) defaults to the
                # session's, not the cluster-wide "default"
                payload["namespace"] = session.namespace
        except Exception:
            pass  # bookkeeping must never break the request path

    def _on_client_disconnect(self, session: ClientSession, conn) -> None:
        with self.lock:
            if session.conn is conn:  # a reconnect may already own the session
                session.conn = None
                session.disconnected_at = time.monotonic()

    def _reap_client_sessions(self) -> None:
        """Health-loop tick: release what clients that never came back held
        (reference: proxier cleanup when a client's channel dies)."""
        grace = GLOBAL_CONFIG.client_reconnect_grace_s
        now = time.monotonic()
        with self.lock:
            expired = [
                s
                for s in self.client_sessions.values()
                if s.disconnected_at is not None and now - s.disconnected_at > grace
            ]
            for s in expired:
                self.client_sessions.pop(s.token, None)
        for s in expired:
            for oid, count in s.refs.items():
                for _ in range(count):
                    self.remove_ref(oid)
            s.refs.clear()
            for aid in s.actors:
                with self.lock:
                    actor = self.actors.get(aid)
                    leaked = (
                        actor is not None
                        and not actor.detached
                        and actor.state != ACTOR_DEAD
                    )
                if leaked:
                    self.kill_actor(aid, no_restart=True)
            s.actors.clear()
            self.flush_outbox()

    def _any_node_id(self) -> bytes:
        with self.lock:
            for n in self.nodes.values():
                if n.alive:
                    return n.node_id.binary()
        raise rex.RayError("cluster has no alive nodes")

    def _on_register_agent(self, conn, info) -> NodeID:
        """A remote host's node agent attached: register its node; workers
        for it will be spawned THERE via spawn requests over this conn. An
        agent reattaching after a head restart presents its previous node
        id and keeps it (dead or unknown here — a LIVE id means a rogue
        duplicate and gets a fresh one)."""
        want = info.get("node_id")
        keep = None
        if want:
            with self.lock:
                old = self.nodes.get(want)
                if old is None or not old.alive:
                    keep = NodeID(want)
        node_id = self.add_node(
            info.get("resources") or {}, labels=info.get("labels"), node_id=keep
        )
        with self.lock:
            node = self.nodes[node_id.binary()]
            node.agent = AgentHandle(conn)
            if info.get("data_address"):
                node.data_address = tuple(info["data_address"])
        conn.send(("agent_ack", {
            "node_id": node_id.binary(),
            # ship the head's non-default config so the _system_config tier
            # reaches remote agent/worker processes too (reference: GCS
            # serves system_config to joining raylets), not just this host
            "config": _cfg.config_overrides(),
        }))
        with self.lock:
            self._schedule()  # queued-infeasible work may now fit
        return node_id

    def _dispatch_request(self, conn, worker, seq, method, payload, remote: bool = False):
        if method in ("subscribe", "unsubscribe"):
            import functools

            handler = functools.partial(getattr(self, "_rpc_" + method), conn)
        else:
            handler = getattr(self, "rpc_" + method)
        if remote and method == "get":
            handler = self._rpc_get_remote
        blocking = method in (
            "get", "wait", "pg_ready", "get_actor_named", "stream_next",
            "worker_stacks", "worker_profile", "mutex_acquire",
            "collect_events",
        )
        if blocking:
            # blocking RPCs park until objects/actors materialize; run them
            # on a cached high-cap pool so the hot path reuses threads
            # instead of spawning one per call (reference: the event-loop
            # pipelining in grpc_server.h — many-task workloads would
            # otherwise hit thread-spawn overhead and exhaustion)
            if worker is not None and method in ("get", "wait"):
                # the submitter is about to park in ray.get/wait: it must
                # not be handed lease followers meanwhile (_try_lease_dispatch)
                with self.lock:
                    worker.blocked_gets += 1
                wh0 = worker

                def handler(h=handler, wh0=wh0, **kw):  # noqa: B008
                    try:
                        return h(**kw)
                    finally:
                        with self.lock:
                            wh0.blocked_gets = max(0, wh0.blocked_gets - 1)

            self._blocking_pool.submit(
                self._run_request, conn, worker, seq, handler, payload
            )
        else:
            self._run_request(conn, worker, seq, handler, payload)

    def _rpc_get_remote(self, obj_ids, timeout=None):
        """get for TCP clients. With the data plane up, hand out the shm
        locators untouched — the client pulls the bytes straight from the
        owning host's data server (head = directory only; reference:
        object_manager.h peer-to-peer chunked transfer). Without it, fall
        back to the round-2 behavior of shipping bytes inline."""
        if self.data_server is not None:
            return self.get_locators(obj_ids, timeout)
        return self.rpc_get_inline(obj_ids, timeout)

    def rpc_get_inline(self, obj_ids, timeout=None):
        """Head-mediated object fetch: read the bytes (locally, or pulled
        from the owning agent's data server) and ship them inline on the
        control socket. Fallback for clients that cannot reach a host's
        data server; ``inline_bytes_served`` counts this traffic so tests
        can assert the p2p path leaves it at zero."""
        from ray_tpu._private import data_plane
        from ray_tpu._private.shm_store import ShmReader

        out = []
        for loc in self.get_locators(obj_ids, timeout):
            kind, payload, is_err = loc
            if kind != "shm":
                out.append(loc)
                continue
            data = None
            try:
                reader = ShmReader(payload)
                try:
                    data = reader.read_serialized_bytes()
                finally:
                    reader.close()
            except FileNotFoundError:
                with self.lock:
                    node = self.nodes.get(payload.node) if payload.node else None
                addr = node.data_address if node is not None else None
                if addr is not None:
                    from ray_tpu._private.shm_store import layout_views

                    mv = data_plane.fetch(addr, self.authkey, payload)
                    header, bufs = layout_views(
                        mv, payload.header_len, payload.buffer_lens
                    )
                    data = ser.SerializedValue(bytes(header), bufs).to_bytes()
            if data is None:
                raise FileNotFoundError("object backing unavailable")
            self.inline_bytes_served += len(data)
            out.append(("inline", data, is_err))
        return out

    def rpc_data_address(self, node_id=None):
        """Data-plane address for a node's host. Agent nodes advertise their
        own server; anything else (head host, simulated local nodes,
        unknown) maps to the head's server. host=None means "the host you
        already reach the control plane on"."""
        with self.lock:
            n = self.nodes.get(node_id) if node_id else None
            if n is not None and n.data_address is not None:
                return tuple(n.data_address)
        return (None, self.data_port) if self.data_port else None

    def _run_request(self, conn, worker, seq, handler, payload):
        if seq == 0:
            # fire-and-forget request (free_ref, pipelined put): client
            # seqs start at 1, so nobody waits on seq 0 — skip the dead
            # resp write (one fewer socket frame per put/free in a burst)
            try:
                handler(**payload)
            except BaseException as e:  # noqa: BLE001
                warn_throttled(f"fire-and-forget {getattr(handler, '__name__', '?')}", e)
            self.flush_outbox()
            return
        try:
            result = handler(**payload)
            out = ("resp", seq, True, result)
        except BaseException as e:  # noqa: BLE001 - errors cross the socket
            out = ("resp", seq, False, e if _picklable(e) else rex.RayError(repr(e)))
        self.flush_outbox()
        try:
            if worker is not None:
                with worker.send_lock:
                    ser.conn_send(conn, out)
            else:
                ser.conn_send(conn, out)
        except (OSError, ValueError, BrokenPipeError):
            pass

    # -------------------------------------------------------------- workers

    def _spawn_worker(
        self,
        node: NodeState,
        actor_id: Optional[bytes] = None,
        attempts: int = 0,
        container: Optional[dict] = None,
    ) -> None:
        # Workers are fresh interpreter processes running a dedicated entry
        # point (`python -m ray_tpu._private.worker_main`), like the
        # reference's worker pool (worker_pool.h:152) execing default_worker.py
        # — NOT multiprocessing children, which would re-import the user's
        # __main__ module (fatal for unguarded driver scripts). Remote nodes
        # delegate the spawn to their agent daemon over TCP.
        import uuid as _uuid

        if actor_id is not None and container is None:
            # every actor spawn path (first spawn, registration-timeout
            # retry, restart FSM) funnels here; resolve the container spec
            # from the create rec so no caller can drop it
            with self.lock:
                rec = self._actor_create_recs.get(actor_id)
                if rec is not None:
                    container = (rec["spec"].get("runtime_env") or {}).get("container")
        token = _uuid.uuid4().hex
        if node.agent is not None:
            wh = WorkerHandle(node, None)
            wh.actor_id = actor_id
            wh.token = token
            wh.spawn_attempts = attempts
            with self.lock:
                node.all_workers.add(wh)
            msg: dict = {"token": token}
            if container:
                msg["container"] = container
            if not node.agent.send(("spawn_worker", msg)):
                self._on_worker_dead(wh)
            return

        if container is None and GLOBAL_CONFIG.worker_forkserver_enabled:
            # fast path: fork from the node's warm template (~5-10ms) instead
            # of a cold interpreter boot (reference: pre-started worker pool,
            # worker_pool.h:152 — same goal, one warm process instead of N).
            # The handle goes into all_workers BEFORE the fork request: the
            # template's token->pid report races the fork and must find the
            # handle, or a pre-registration wedge could never be killed.
            tmpl = self._ensure_template(node)
            if tmpl is not None:
                wh = WorkerHandle(node, None)
                wh.forked = True
                wh.actor_id = actor_id
                wh.token = token
                wh.spawn_attempts = attempts
                with self.lock:
                    node.all_workers.add(wh)
                if tmpl.fork(token):
                    return
                with self.lock:  # template died mid-request: cold-spawn
                    node.all_workers.discard(wh)

        import subprocess
        import sys

        pkg_root = self._pkg_root()
        env = self._worker_env(pkg_root)
        argv = [
            sys.executable,
            "-m",
            "ray_tpu._private.worker_main",
            self.socket_path,
            self.authkey.hex(),
            node.node_id.binary().hex(),
            token,
        ]
        if container:
            from ray_tpu._private import runtime_env as _renv

            argv, env = _renv.container_wrap(argv, env, pkg_root, container)
        popen = subprocess.Popen(argv, env=env, start_new_session=False)
        proc = _WorkerProc(popen)
        wh = WorkerHandle(node, proc)
        wh.actor_id = actor_id
        wh.token = token
        wh.spawn_attempts = attempts
        with self.lock:
            node.all_workers.add(wh)
        # registration arrives on its own connection; matched in _on_register

    def _pkg_root(self) -> str:
        import ray_tpu

        return os.path.dirname(os.path.dirname(os.path.abspath(ray_tpu.__file__)))

    def _worker_env(self, pkg_root: str) -> dict:
        env = dict(os.environ)
        env["PYTHONPATH"] = pkg_root + os.pathsep + env.get("PYTHONPATH", "")
        if self.arena_name:
            env["RAY_TPU_ARENA"] = self.arena_name
        if self.tcp_address is not None:
            # detached-actor workers reconnect here after a head restart —
            # the unix socket dies with the old head process, the TCP
            # address is what a restarted head rebinds
            env["RAY_TPU_HEAD_TCP"] = f"{self.tcp_address[0]}:{self.tcp_address[1]}"
        return env

    def _ensure_template(self, node: NodeState) -> Optional[TemplateProc]:
        """Get (spawning if needed) the node's forkserver template. Returns
        None when templates are unusable on this platform (no fork) — the
        caller cold-spawns. A dead template (OOM-killed, crashed) is
        replaced; spawn requests buffered in its stdin pipe die with it, but
        those workers' registration timeouts already cover lost spawns."""
        tmpl = node.template
        if tmpl is not None and tmpl.alive():
            return tmpl
        # Popen OUTSIDE the head lock (it is multi-ms and the lock guards
        # the scheduling hot path); the re-check under the lock keeps one
        # template per node when two spawn threads race here.
        ours = spawn_template(
            self.socket_path,
            self.authkey,
            node.node_id.binary(),
            self._worker_env(self._pkg_root()),
            on_spawn=lambda token, proc: self._bind_forked_proc(node, token, proc),
        )
        if ours is None:
            return None
        with self.lock:
            cur = node.template
            if cur is not None and cur.alive():
                loser = ours
            else:
                node.template, loser = ours, cur
        if loser is not None:
            loser.shutdown()
        return node.template

    def _bind_forked_proc(self, node: NodeState, token: str, proc: ForkedProc) -> None:
        """Template reported a fork: give the pre-created handle a process
        object NOW so registration-timeout kills work before the worker
        ever connects (_on_register also binds, for the race where it wins)."""
        with self.lock:
            for wh in node.all_workers:
                if wh.token == token and wh.proc is None:
                    wh.proc = proc
                    return
            revoked = token in self._revoked_tokens
        if revoked:
            # the head already gave up on this spawn (_respawn_timed_out ran
            # before the pid report arrived, so it had nothing to kill) —
            # this report IS the kill opportunity for the wedged interpreter
            proc.terminate()

    def _on_register(self, conn, info, remote: bool = False) -> Optional[WorkerHandle]:
        node_id = info["node_id"]
        pid = info["pid"]
        token = info.get("token")
        with self.lock:
            node = self.nodes.get(node_id)
            if node is None:
                # e.g. a detached-actor worker reconnecting after a head
                # restart BEFORE its node's agent has reattached: reject by
                # closing the conn (caller) — the worker's reconnect loop
                # retries until the node exists again
                return None
            wh = None
            if token:
                for cand in node.all_workers:
                    if cand.conn is None and cand.token == token:
                        wh = cand
                        break
            if wh is None:
                for cand in node.all_workers:
                    if cand.conn is None and cand.proc is not None and cand.proc.pid == pid:
                        wh = cand
                        break
            if wh is None and token and token in self._revoked_tokens:
                # timed out and already replaced: exit, don't join the pool
                self._revoked_tokens.pop(token, None)
                wh = WorkerHandle(node, None)
                wh.conn = conn
                wh.alive = False
                self._conn_worker[conn] = wh
                wh.send(("exit", None))
                return wh
            if wh is None:  # race-safe fallback
                wh = WorkerHandle(node, None)
                node.all_workers.add(wh)
            wh.conn = conn
            if wh.forked and wh.proc is None and not remote:
                # template-forked worker: first time we learn its pid —
                # kill/join paths need a process handle (head-host only;
                # a remote host's pid is meaningless here)
                wh.proc = ForkedProc(pid)
            claim = info.get("actor_id")
            if wh.actor_id is None and claim is None:
                # not a reconnect claim: this registration consumes a spawn
                # slot (a reconnecting worker never occupied one)
                node.spawning = max(0, node.spawning - 1)
            self._conn_worker[conn] = wh
            if claim is not None:
                # a detached actor's worker reconnecting after a head
                # restart: rebind it to the restored ActorState (its
                # actor_ready message completes the transition to ALIVE
                # through _on_actor_ready). Reject if the actor is gone OR
                # already re-bound/re-creating — two workers bound to one
                # actor id would split its state.
                actor = self.actors.get(claim)
                if (
                    actor is None
                    or actor.state == ACTOR_DEAD
                    or actor.worker is not None
                    or actor.create_spec["task_id"] in self.tasks
                ):
                    wh.alive = False
                    wh.send(("exit", None))
                    return wh
                wh.actor_id = claim
                actor.node_id = node.node_id
                self._restored_actors.discard(claim)
                return wh
            if wh.actor_id is not None:
                rec = self._actor_create_recs.pop(wh.actor_id, None)
                if rec is not None and rec["task_id"] in self.cancelled:
                    # creation cancelled while the worker was coming up:
                    # resolve the creation refs and mark the actor dead
                    self._finish_cancelled(rec)
                    actor = self.actors.get(wh.actor_id)
                    if actor is not None and actor.state != ACTOR_DEAD:
                        actor.restarts_left = 0
                        self._kill_actor_locked(actor, "creation cancelled", restart=False)
                    rec = None
                if rec is None:
                    # actor died/was cancelled before its worker came up
                    wh.alive = False
                    wh.send(("exit", None))
                else:
                    self._dispatch_to_worker(wh, rec)
            else:
                self._worker_idle(wh)
        return wh

    def _worker_idle(self, wh: WorkerHandle):
        """Called with lock held: worker drained its queue / just registered."""
        node = wh.node
        wh.current_task = None
        wh.lease_sig = None
        wh.idle_since = time.monotonic()
        if wh.actor_id is not None:
            # Dedicated actor worker (reference: actors own their worker
            # process for life) — it must never join the general pool, or a
            # blocking normal task could wedge the actor's serial queue.
            return
        while node.assigned and node.alive:
            rec = node.assigned.popleft()
            if rec["task_id"] in self.cancelled:
                self._finish_cancelled(rec)
                continue
            self._dispatch_to_worker(wh, rec)
            return
        if wh not in node.idle_workers:
            node.idle_workers.append(wh)

    def _dispatch_to_worker(self, wh: WorkerHandle, rec: dict) -> None:
        spec = rec["spec"]
        wh.queued_recs.append(rec)
        wh.current_task = wh.queued_recs[0]
        leaseable = not spec.get("strategy") and spec["kind"] == "task"
        # the lease key includes func_id on top of the scheduling signature:
        # queueing a DIFFERENT function behind a running task deadlocks when
        # the running task is its submitter blocked in ray.get on it (the
        # nested fan-out pattern: parent and leaf share {CPU: 1})
        sig = (
            (_PendingQueue.sig_of(rec), spec.get("func_id")) if leaseable else None
        )
        if len(wh.queued_recs) == 1:
            wh.lease_sig = sig
        elif wh.lease_sig != sig:
            wh.lease_sig = None  # mixed queue: stop leasing until it drains
        if wh in wh.node.idle_workers:
            wh.node.idle_workers.remove(wh)
        rec["worker"] = wh
        rec["state"] = "RUNNING"
        rec["started_at"] = time.monotonic()  # OOM policy: newest-first victim
        wf = spec.get("wf")
        if wf is not None:
            _waterfall.stamp(wf)  # head_dispatch: about to queue the send
        self._event(rec, "RUNNING")
        # send OUTSIDE the head lock (flush_outbox); a dead conn surfaces
        # there as worker death, which requeues the whole dispatch FIFO —
        # dispatch itself can no longer fail synchronously
        self._enqueue_send(wh, ("run_task", spec))

    def _enqueue_send(self, wh: WorkerHandle, msg) -> None:
        """Lock held: queue a worker-bound message. The socket write (plus
        its pickle) happens in flush_outbox AFTER the caller releases the
        head lock — a write inside the critical section serializes every
        conn thread behind each dispatch. The backstop thread catches any
        path that queued a send but parks before flushing (e.g. a driver
        get whose lineage reconstruction dispatched a rebuild, then blocked
        on the very result).

        Deliberately does NOT wake the backstop: Event.set with a waiter is
        a futex wake (~50us measured on a busy 1-core box, paid on EVERY
        dispatch), while every normal entry point already flushes in its
        own finally — the backstop only exists for the rare parked-enqueuer
        path, which its poll interval bounds."""
        self._outbox.append((wh, msg))

    def _wire_spec(self, wh: WorkerHandle, spec: dict) -> dict:
        """Header-split a dispatch (cheaper per-task bytes, ISSUE 14): a
        spec carrying ``_hdr`` (header id + the static per-function fields
        its submitter computed once) ships only its per-call body
        (ser.split_spec_body — the shared elision rule) plus a header
        reference; the first dispatch of a header to a worker inlines the
        definition (``_hdr_def``), so a worker never misses — the conn is
        FIFO and ``sent_hdrs`` is per-handle, so respawned or reassigned
        workers start from a fresh set."""
        hdr = spec.get("_hdr")
        if hdr is None:
            return spec
        hid, fields = hdr
        body = ser.split_spec_body(spec, fields)
        if hid in wh.sent_hdrs:
            body["_hdr_ref"] = hid
        else:
            wh.sent_hdrs.add(hid)
            body["_hdr_def"] = hdr
        return body

    def _flush_backstop_loop(self) -> None:
        while not self._shutdown:
            self._flush_event.wait(timeout=GLOBAL_CONFIG.outbox_flush_backstop_s)
            self._flush_event.clear()
            self.flush_outbox()

    def flush_outbox(self) -> None:
        """Drain queued worker sends. Called by every entry point right
        after it drops the head lock (RPC dispatch, conn message handlers,
        driver direct calls, the health loop). Exactly ONE thread drains at
        a time — per-worker message order is the dispatch order workers'
        FIFO execution depends on; the outer re-check catches items
        appended while the active drainer was releasing.

        run_task dispatches coalesce PER WORKER across the whole drain into
        one run_task_batch message (one pickle + one socket write for a
        burst of pipelined leases or a deferred submit storm). Only
        cross-worker order is relaxed — no ordering contract spans workers;
        each worker's own FIFO (including non-dispatch messages like exit,
        which flush that worker's pending batch first) is preserved. Each
        spec is header-split per worker at write time (_wire_spec): static
        per-function fields ship once, steady-state bodies reference them."""
        while self._outbox:
            if not self._flush_lock.acquire(blocking=False):
                return  # active drainer will pick ours up (or we re-enter)
            try:
                if len(self._outbox) == 1:
                    # sync round-trip fast path: one queued message, no
                    # batching machinery — pop, wire, write
                    try:
                        wh, msg = self._outbox.popleft()
                    except IndexError:
                        continue
                    if msg[0] == "run_task":
                        msg = ("run_task", self._wire_spec(wh, msg[1]))
                    if wh.alive and not wh.send(msg):
                        self._on_worker_dead(wh)
                    continue
                batches: dict = {}  # wh -> [spec, ...] in dispatch order

                def _flush_batch(wh0):
                    specs = batches.pop(wh0, None)
                    if not specs:
                        return
                    if not wh0.alive:
                        return
                    wire = [self._wire_spec(wh0, s) for s in specs]
                    out = ("run_task", wire[0]) if len(wire) == 1 else (
                        "run_task_batch", wire
                    )
                    if not wh0.send(out):
                        self._on_worker_dead(wh0)

                while True:
                    try:
                        wh, msg = self._outbox.popleft()
                    except IndexError:
                        break
                    if msg[0] == "run_task":
                        batches.setdefault(wh, []).append(msg[1])
                        continue
                    _flush_batch(wh)  # non-dispatch msg: keep per-wh FIFO
                    if wh.alive and not wh.send(msg):
                        self._on_worker_dead(wh)
                for wh in list(batches):
                    _flush_batch(wh)
            finally:
                self._flush_lock.release()

    def _try_lease_dispatch(self, rec: dict) -> bool:
        """No node has free capacity — pipeline the task onto a worker
        already running the same scheduling signature. The follower holds no
        allocation of its own; it inherits the chain head's at completion
        time (_on_task_done alloc transfer), so concurrent resource usage
        stays exact while the worker never idles waiting for a round-trip.
        """
        depth = GLOBAL_CONFIG.max_tasks_in_flight_per_worker
        if depth <= 1:
            return False
        spec = rec["spec"]
        if spec.get("strategy") or spec["kind"] != "task":
            return False
        sig = (_PendingQueue.sig_of(rec), spec.get("func_id"))
        for nid in self.node_order:
            node = self.nodes[nid]
            if not node.alive:
                continue
            for wh in node.all_workers:
                if (
                    wh.alive
                    and wh.conn is not None
                    and wh.actor_id is None
                    and wh.lease_sig == sig
                    and wh.blocked_gets == 0
                    and len(wh.queued_recs) < depth
                ):
                    rec["node"] = node.node_id
                    rec["state"] = "ASSIGNED"
                    self._dispatch_to_worker(wh, rec)
                    return True
        return False

    # ------------------------------------------------------------ node admin

    def add_node(self, resources: dict[str, float], labels=None, node_id=None) -> NodeID:
        """``node_id`` lets a reattaching agent keep its identity across a
        head restart, so restored object locators (loc.node) stay routable
        (reference: raylet re-registration after GCS failover)."""
        node_id = node_id or NodeID.from_random()
        with self.lock:
            self.nodes[node_id.binary()] = NodeState(node_id, resources, labels)
            if node_id.binary() not in self.node_order:
                self.node_order.append(node_id.binary())
            self._sched_gen += 1
            self._retry_pending_pgs()
            self._schedule()
        self.publish("nodes", {"event": "added", "node_id": node_id.hex(), "resources": dict(resources)})
        return node_id

    def remove_node(self, node_id: NodeID, graceful: bool = False) -> None:
        """Simulated node failure (reference: NodeKillerActor / node death in
        GCS). Kills all workers, fails or retries their tasks, restarts their
        actors elsewhere."""
        # One critical section for mark-dead + requeue: releasing the lock
        # mid-removal would let rpc_task_done/_schedule observe a dead node
        # whose tasks are not yet requeued. publish() is a non-blocking
        # Queue.put and terminate() just sends a signal, so neither can
        # block the lock.
        with self.lock:
            node = self.nodes.get(node_id.binary())
            if node is None or not node.alive:
                return
            node.alive = False
            workers = list(node.all_workers)
            self.publish("nodes", {"event": "removed", "node_id": node_id.hex()})
            assigned = list(node.assigned)
            node.assigned.clear()
            node.idle_workers.clear()
            for wh in workers:
                wh.alive = False
                if wh.proc is not None and wh.proc.is_alive():
                    wh.proc.terminate()
            if node.template is not None:
                node.template.shutdown()
                node.template = None
            for rec in assigned:
                self._requeue_or_fail(rec, rex.WorkerCrashedError("node removed"))
            for wh in workers:
                self._handle_worker_death_locked(wh)
            for pg in self.placement_groups.values():
                if any(n == node_id for n in pg.bundle_nodes):
                    for i, n in enumerate(pg.bundle_nodes):
                        if n == node_id:
                            pg.bundle_nodes[i] = None
                    pg.state = PG_PENDING
                    pg.ready_event.clear()
                    self._try_place_pg(pg)
            # objects whose bytes lived on the dead host are gone: rebuild
            # via lineage or mark LOST now, so readers fail fast instead of
            # timing out against an unreachable data server (reference:
            # object directory location removal on node death). Skipped
            # during shutdown — resubmitting tasks into a dying cluster is
            # pure noise.
            nid = node_id.binary()
            if not self._shutdown:
                for oid, ent in list(self.objects.items()):
                    if ent.shm is not None and ent.shm.node == nid:
                        events.emit(
                            "core.object.reap",
                            obj_id=oid,
                            size=ent.size,
                            node=nid,
                            reason="node-removed",
                        )
                        self._reconstruct(oid, ent)
            self._schedule()
            self.cv.notify_all()

    # ----------------------------------------------------------- scheduling

    def _on_submit_batch(self, payload: dict, hdr_cache: dict, session=None) -> None:
        """Rehydrate one pipelined submit window — items are ``(kind,
        body)`` with bodies header-split against this connection's cache —
        and run it through ``submit_task_batch``. Submit-time failures
        (missing header after a protocol loss, oversized inline args)
        surface asynchronously on that task's return refs; the window
        always completes and always gets acked, so client credits can
        never wedge on a poison task."""
        hdrs = payload.get("hdrs")
        if hdrs:
            hdr_cache.update(hdrs)
        cap = GLOBAL_CONFIG.core_max_spec_inline_bytes
        items = []
        for kind, body in payload["items"]:
            if kind == "put":
                # pipelined ray.put riding the submit window (ISSUE 18):
                # process AT its window position — a later item in this
                # same window may consume the ref as a task argument.
                # rpc_put never raises (store failures land on the id),
                # so the window always completes and always acks.
                body.pop("return_ids", None)
                # rpc_put returns False only for an ignored replay
                # duplicate — tracking the session ref then would
                # double-count the take_ref applied by the original
                stored = self.rpc_put(**body)
                if stored and session is not None:
                    self._session_track(session, "put", body)
                continue
            hid = body.pop("_hdr_ref", None)
            if hid is None:
                spec = body
            else:
                fields = hdr_cache.get(hid)
                if fields is None:
                    with self.lock:
                        for rid in body.get("return_ids", ()):
                            self._store_error(
                                rid,
                                rex.RayError(
                                    "submit window referenced an unknown spec "
                                    "header (connection state lost); retry the task"
                                ),
                            )
                    continue
                spec = {**fields, **body}
                spec["_hdr"] = (hid, fields)
            size = 0
            for a in spec.get("args", ()):
                if a[0] == "v":
                    size += len(a[1])
            for a in spec.get("kwargs", {}).values():
                if a[0] == "v":
                    size += len(a[1])
            if size > cap:
                with self.lock:
                    for rid in spec.get("return_ids", ()):
                        self._store_error(
                            rid,
                            ValueError(
                                f"task {spec.get('name')!r} carries {size} inline "
                                f"argument bytes (cap {cap}); ray_tpu.put() large "
                                f"arguments and pass the refs"
                            ),
                        )
                continue
            if session is not None:
                self._session_track(
                    session,
                    "submit_task" if kind == "task" else "submit_actor_task",
                    {"spec": spec},
                )
            items.append((kind, spec))
        if items:
            self.submit_task_batch(items)

    def submit_task(self, spec: dict) -> None:
        with self.lock:
            if self._submit_task_locked(spec):
                self._schedule()

    def submit_task_batch(self, items: list) -> None:
        """Pipelined-submission entry (ISSUE 14): a whole burst of specs —
        ``("task" | "actor_method", spec)`` in submission order — lands in
        ONE critical section with ONE scheduling pass, instead of a lock
        acquisition + schedule pass per ``.remote()``. Per-item failures
        surface asynchronously on that item's return refs (the submitter
        already holds them; there is no reply to raise into)."""
        _batch_metrics()["submit"].observe(len(items))
        with self.lock:
            need_sched = False
            for kind, spec in items:
                try:
                    if kind == "task":
                        need_sched = self._submit_task_locked(spec) or need_sched
                    else:
                        self._submit_actor_task_locked(spec)
                except Exception as e:  # noqa: BLE001 - surfaces on the refs
                    for rid in spec.get("return_ids", ()):
                        self._store_error(rid, e)
            if need_sched:
                self._schedule()

    def _submit_task_locked(self, spec: dict) -> bool:
        """Lock held. Returns True when the task joined ``pending_sched``
        (the caller owes a scheduling pass)."""
        rec = {
            "task_id": spec["task_id"],
            "spec": spec,
            "deps": set(),
            "state": "PENDING",
            "worker": None,
            "node": None,
            "retries_left": spec.get("max_retries", GLOBAL_CONFIG.default_max_retries),
        }
        # the submitter's refs on the return objects are taken HERE — at
        # receive time, before any dispatch — not by per-id add_ref RPCs
        # before the submit: for a worker submitting nested tasks that is
        # one control round trip instead of 1 + num_returns, and for a
        # batched window it means ownership exists the moment the head has
        # the bytes (reference: task returns are born owned by the
        # submitter, reference_count.h)
        for rid in spec["return_ids"]:
            ent = self.objects.get(rid)
            if ent is None:
                ent = self.objects[rid] = ObjectEntry()
            ent.refcount += 1
        strategy = spec.get("strategy")
        if strategy and strategy[0] == "pg":
            # Fail fast if the task can never fit its designated bundle
            # (reference: ValueError on infeasible bundle resources).
            _, pg_id, bundle_idx, _ = strategy
            pg = self.placement_groups.get(pg_id)
            if pg is None:
                for rid in spec["return_ids"]:
                    self._store_error(rid, ValueError("placement group removed"))
                return False
            res = self._effective_resources(spec)
            bundles = [pg.bundles[bundle_idx]] if bundle_idx >= 0 else pg.bundles
            if not any(
                all(b.get(k, 0.0) >= v for k, v in res.items()) for b in bundles
            ):
                for rid in spec["return_ids"]:
                    self._store_error(
                        rid,
                        ValueError(
                            f"Task {spec.get('name')} requires {res} which can never fit "
                            f"in placement group bundle(s) {bundles}; pass num_cpus=0 for "
                            f"tasks in accelerator-only bundles"
                        ),
                    )
                return False
        self.tasks[spec["task_id"]] = rec
        self._event(rec, "PENDING_ARGS_AVAIL")
        if spec.get("args") or spec.get("kwargs"):
            for kind, payload in _iter_arg_refs(spec):
                ent = self.objects.get(payload)
                if ent is None:
                    ent = self.objects[payload] = ObjectEntry()
                ent.pins += 1
                if not ent.ready:
                    rec["deps"].add(payload)
                    self.dep_waiters.setdefault(payload, set()).add(rec["task_id"])
        if rec["deps"]:
            rec["state"] = "WAITING_DEPS"
            return False
        if not self.pending_sched and self._try_place(rec):
            # direct placement: with nothing queued ahead policy order is
            # unchanged, and the _PendingQueue signature machinery
            # (append + schedule_pass) drops off the per-submit hot path
            return False
        self.pending_sched.append(rec)
        return True

    def _deps_ready(self, obj_id: bytes):
        """Lock held. An object became available; activate waiting tasks."""
        activated = False
        for tid in self.dep_waiters.pop(obj_id, ()):  # noqa: B020
            rec = self.tasks.get(tid)
            if rec is None:
                continue
            rec["deps"].discard(obj_id)
            if not rec["deps"] and rec["state"] == "WAITING_DEPS":
                rec["state"] = "PENDING"
                self.pending_sched.append(rec)
                activated = True
        if activated:
            self._schedule()

    def _try_place(self, rec: dict) -> bool:
        """Lock held. One placement attempt for a dep-free task record —
        the policy body shared by the scheduling pass and the direct
        fast path (_submit_task_locked)."""
        if self.cancelled and rec["task_id"] in self.cancelled:
            self._finish_cancelled(rec)
            return True
        res = self._effective_resources(rec["spec"])
        node = self._pick_node(rec["spec"], res)
        if node is None:
            if self._try_lease_dispatch(rec):
                return True
            self._warn_infeasible(rec)
            return False
        self._allocate_for(rec, node, res)
        rec["node"] = node.node_id
        rec["state"] = "ASSIGNED"
        if rec["spec"]["kind"] == "actor_create":
            self._start_actor_on(rec, node)
        elif node.idle_workers:
            wh = node.idle_workers.pop()
            self._dispatch_to_worker(wh, rec)
        else:
            node.assigned.append(rec)
            self._maybe_spawn(node)
        return True

    def _schedule(self):
        """Lock held. Hybrid policy (reference hybrid_scheduling_policy.cc):
        prefer the first feasible node whose critical-resource utilization
        stays under the spread threshold (pack); otherwise the least-utilized
        feasible node (spread). Honors strategies: SPREAD, node affinity,
        placement-group bundles. One pass visits each distinct scheduling
        signature once (see _PendingQueue) — O(signatures), not O(tasks)."""
        if not self.pending_sched:
            return  # hot path: every completion triggers a pass
        self.pending_sched.schedule_pass(self._try_place, self._sched_gen)

    def _warn_infeasible(self, rec):
        now = time.monotonic()
        tid = rec["task_id"]
        if now - self._infeasible_warned.get(tid, 0.0) > GLOBAL_CONFIG.infeasible_warn_interval_s:
            self._infeasible_warned[tid] = now
            res = self._effective_resources(rec["spec"])
            total = {}
            for n in self.nodes.values():
                if n.alive:
                    for k, v in n.resources_total.items():
                        total[k] = max(total.get(k, 0.0), v)
            if any(total.get(k, 0.0) < v for k, v in res.items() if v > 0):
                print(
                    f"[ray_tpu] WARNING: task {rec['spec'].get('name')} requires {res} "
                    f"which no node can ever satisfy (per-node max {total})."
                )

    def _effective_resources(self, spec: dict) -> dict[str, float]:
        eres = spec.get("_eres")
        if eres is not None:
            return eres  # template-cached (read-only by contract)
        return {k: v for k, v in spec.get("resources", {}).items() if v != 0}

    def _locality_bytes(self, spec: dict) -> Optional[dict]:
        """Lock held. Bytes of this spec's ref args resident per owning node
        (ISSUE 18): the object directory already knows where every shm
        locator lives (``ent.shm.node``), so placement can move the task to
        its data instead of pulling bytes to an arbitrary worker. Head-host
        bytes (``node is None``) are reachable from every same-host node and
        carry no preference. Returns None when the spec has no args at all —
        the no-arg hot path stays allocation-free."""
        if not spec.get("args") and not spec.get("kwargs"):
            return None
        by_node = None
        for _kind, oid in _iter_arg_refs(spec):
            ent = self.objects.get(oid)
            if ent is None or ent.shm is None or ent.shm.node is None:
                continue
            if by_node is None:
                by_node = {}
            nid = ent.shm.node
            by_node[nid] = by_node.get(nid, 0) + (ent.size or 0)
        return by_node

    def _pick_node(self, spec: dict, res: Optional[dict] = None) -> Optional[NodeState]:
        if res is None:
            res = self._effective_resources(spec)
        strategy = spec.get("strategy")
        if strategy is None:
            # locality first (ISSUE 18): a task whose args' bytes already
            # sit on some node runs where its data lives — most bytes wins,
            # load breaks ties, infeasible byte-holders fall through to the
            # hybrid policy below
            loc_bytes = self._locality_bytes(spec)
            if loc_bytes:
                best = None
                best_key = None
                for nid, nbytes in loc_bytes.items():
                    n = self.nodes.get(nid)
                    if n is None or not n.alive or not n.can_fit(res):
                        continue
                    key = (-nbytes, n.utilization(res))
                    if best_key is None or key < best_key:
                        best, best_key = n, key
                self._loc_total += 1
                if best is not None:
                    self._loc_hits += 1
                _locality_gauge().set(self._loc_hits / self._loc_total)
                if best is not None:
                    return best
            # hot path (plain tasks, no placement constraint): first node in
            # stable order under the spread threshold — no alive-list or
            # feasible-list allocation, the common single/few-node case
            # resolves in one scan
            thr = GLOBAL_CONFIG.scheduler_spread_threshold
            best = None
            best_u = None
            for nid in self.node_order:
                n = self.nodes[nid]
                if not n.alive or not n.can_fit(res):
                    continue
                u = n.utilization(res)
                if u <= thr:
                    return n
                if best_u is None or u < best_u:
                    best, best_u = n, u
            return best
        alive = [self.nodes[nid] for nid in self.node_order if self.nodes[nid].alive]
        if not alive:
            return None
        if strategy and strategy[0] == "pg":
            _, pg_id, bundle_idx, _ = strategy
            pg = self.placement_groups.get(pg_id)
            if pg is None or pg.state != PG_CREATED:
                return None
            indices = [bundle_idx] if bundle_idx >= 0 else range(len(pg.bundles))
            for bi in indices:
                nid = pg.bundle_nodes[bi]
                if nid is None:
                    continue
                node = self.nodes[nid.binary()]
                avail = node.pg_reserved.get(pg_id, {}).get(bi, {})
                if node.alive and all(avail.get(k, 0.0) + 1e-9 >= v for k, v in res.items()):
                    spec["_pg_bundle"] = (pg_id, bi)
                    return node
            return None
        if strategy and strategy[0] == "node":
            _, node_hex, soft = strategy
            node = self.nodes.get(bytes.fromhex(node_hex))
            if node is not None and node.alive and node.can_fit(res):
                return node
            if not soft:
                return None
            # soft affinity falls through to default policy
        feasible = [n for n in alive if n.can_fit(res)]
        if not feasible:
            return None
        if strategy and strategy[0] == "labels":
            # node-label policy (reference: scheduling/policy node-label):
            # hard labels filter; soft labels prefer best-matching nodes
            _, hard, soft = strategy
            feasible = [
                n for n in feasible
                if all(n.labels.get(k) == v for k, v in hard)
            ]
            if not feasible:
                return None
            if soft:
                best = max(
                    sum(1 for k, v in soft if n.labels.get(k) == v) for n in feasible
                )
                feasible = [
                    n for n in feasible
                    if sum(1 for k, v in soft if n.labels.get(k) == v) == best
                ]
        if strategy and strategy[0] == "spread":
            return min(feasible, key=lambda n: (n.utilization(res), self.node_order.index(n.node_id.binary())))
        # hybrid: first node (stable order) under threshold, else least utilized
        thr = GLOBAL_CONFIG.scheduler_spread_threshold
        for n in feasible:
            if n.utilization(res) <= thr:
                return n
        return min(feasible, key=lambda n: n.utilization(res))

    def _allocate_for(self, rec, node: NodeState, res):
        bundle = rec["spec"].get("_pg_bundle")
        if bundle is not None:
            pg_id, bi = bundle
            avail = node.pg_reserved[pg_id][bi]
            for k, v in res.items():
                avail[k] = avail.get(k, 0.0) - v
        else:
            node.allocate(res)
        rec["alloc"] = (node.node_id.binary(), res, bundle)

    def _release_alloc(self, rec):
        alloc = rec.pop("alloc", None)
        if alloc is None:
            return
        self._sched_gen += 1  # capacity freed: blocked signatures may now fit
        nid, res, bundle = alloc
        node = self.nodes.get(nid)
        if node is None:
            return
        if bundle is not None:
            pg_id, bi = bundle
            reserved = node.pg_reserved.get(pg_id, {}).get(bi)
            if reserved is not None:
                for k, v in res.items():
                    reserved[k] = reserved.get(k, 0.0) + v
        else:
            node.release(res)
            self._retry_pending_pgs()

    def _startup_cap(self, node: NodeState) -> int:
        cap = GLOBAL_CONFIG.worker_startup_concurrency
        if cap > 0:
            return cap
        return max(int(node.resources_total.get("CPU", 1)), 2)

    def _booting_count(self, node: NodeState) -> int:
        """Workers booting on this node: handed to a spawn thread but not
        yet visible in all_workers (``node.dispatching``, counted
        SYNCHRONOUSLY by the dispatcher — the handle only appears after the
        multi-ms Popen, far too late to throttle a storm) plus spawned-but-
        unregistered handles."""
        with self.lock:
            return node.dispatching + len(
                [w for w in node.all_workers if w.alive and w.conn is None]
            )

    def _spawn_dispatch_loop(self):
        """Runs spawn thunks on fresh threads from OUTSIDE any lock (see
        _spawn_q comment in __init__). Throttles per-node startup
        concurrency: interpreter boot is CPU-bound, and an unbounded storm
        (100 actor creations at once) pushes every boot past the
        registration timeout (reference: maximum_startup_concurrency).
        Must never die: if the OS refuses a new thread, degrade to running
        the spawn inline (serialized but alive) rather than silently
        disabling all future spawning."""
        import traceback as _tb

        deferred: list = []
        while True:
            try:
                item = self._spawn_q.get(timeout=0.05 if deferred else None)
            except queue.Empty:
                item = False  # tick: only re-examine deferred spawns
            if item is None:
                return
            pending = deferred + ([item] if item is not False else [])
            deferred = []
            for fn, args, kwargs in pending:
                node = args[0]
                if not node.alive:
                    # node died while the spawn was queued: a dropped ACTOR
                    # spawn must still feed the actor FSM (its create rec is
                    # keyed in _actor_create_recs, invisible to node-death
                    # cleanup) or the actor's waiters hang forever
                    # NB: compare unbound functions — `fn is self._spawn_actor_worker`
                    # is always False (each attribute access builds a fresh
                    # bound-method object)
                    if getattr(fn, "__func__", None) is Head._spawn_actor_worker:
                        with self.lock:
                            self._on_actor_worker_death(args[1])
                            self._schedule()
                    else:
                        with self.lock:
                            node.spawning = max(0, node.spawning - 1)
                    continue
                if self._booting_count(node) >= self._startup_cap(node):
                    deferred.append((fn, args, kwargs))
                    continue
                with self.lock:
                    node.dispatching += 1  # released in _run_spawn_item
                try:
                    threading.Thread(
                        target=self._run_spawn_item,
                        args=(fn, node, args, kwargs),
                        daemon=True,
                    ).start()
                except RuntimeError:  # can't start new thread
                    try:
                        self._run_spawn_item(fn, node, args, kwargs)
                    except Exception:  # noqa: BLE001 - keep the dispatcher alive
                        _tb.print_exc()

    def _run_spawn_item(self, fn, node, args, kwargs):
        try:
            fn(*args, **kwargs)
        finally:
            # _spawn_worker returns right after the handle lands in
            # all_workers, so from here _booting_count sees the handle
            # instead of this counter
            with self.lock:
                node.dispatching = max(0, node.dispatching - 1)

    def _maybe_spawn(self, node: NodeState):
        cap = max(int(node.resources_total.get("CPU", 1)), 1)
        pool = (
            len([w for w in node.all_workers if w.alive and w.actor_id is None and w.conn is not None])
            + node.spawning
        )
        if node.assigned and pool < cap:
            node.spawning += 1
            self._spawn_q.put((self._spawn_worker, (node,), {}))

    # ------------------------------------------------------------ completion

    # ------------------------------------------------- streaming generators

    def _on_stream_item(self, wh: WorkerHandle, payload: dict):
        """A streaming task yielded one item: store its object and publish
        the index so blocked ``stream_next`` calls wake (reference:
        ReportGeneratorItemReturns, task_manager.cc)."""
        task_id = payload["task_id"]
        locator = self._normalize_locator(payload["locator"])
        with self.lock:
            self._store_locator(payload["obj_id"], locator)
            ent = self.objects.get(payload["obj_id"])
            if task_id in self._disposed_streams:
                # consumer walked away; the producer raced the cancel —
                # free the stored bytes immediately instead of leaking them
                if ent is not None:
                    self._maybe_evict(payload["obj_id"], ent)
                return
            st = self.streams.setdefault(
                task_id, {"items": {}, "count": None, "next": 0}
            )
            if ent is not None:
                ent.refcount += 1  # held by the stream until handed out/disposed
            st["items"][payload["index"]] = payload["obj_id"]
            self.cv.notify_all()

    def rpc_stream_next(self, task_id, index, timeout=None):
        """Blocking: ('item', obj_id) when the index exists; ('end', count)
        past the final item; ('error', completion_obj_id) when the task
        failed (the completion object holds the exception). Acks the
        consumed index to the producing worker for backpressure."""
        deadline = None if timeout is None else time.monotonic() + timeout
        with self.lock:
            while True:
                if task_id in self._disposed_streams:
                    return ("end", 0)
                st = self.streams.get(task_id)
                if st is not None:
                    if index in st["items"]:
                        oid = st["items"][index]
                        st["next"] = max(st["next"], index + 1)
                        rec = self.tasks.get(task_id)
                        wh = rec.get("worker") if rec is not None else None
                        break
                    if st["count"] is not None and index >= st["count"]:
                        comp = st.get("completion")
                        if comp is not None:
                            ent = self.objects.get(comp)
                            if ent is not None and ent.is_error:
                                return ("error", comp)
                        return ("end", st["count"])
                remaining = None if deadline is None else deadline - time.monotonic()
                if remaining is not None and remaining <= 0:
                    raise rex.GetTimeoutError(f"stream_next timed out on {TaskID(task_id)}")
                if self._shutdown:
                    raise rex.RayError("shutting down")
                self.cv.wait(timeout=min(remaining, 1.0) if remaining else 1.0)
        if wh is not None and wh.alive:
            wh.send(("stream_ack", {"task_id": task_id, "consumed": index + 1}))
        return ("item", oid)

    def rpc_stream_dispose(self, task_id):
        """Consumer dropped its generator: cancel the producer if it is
        still running and release items never handed out (reference:
        streaming generator cancellation + unconsumed-return GC)."""
        with self.lock:
            st = self.streams.pop(task_id, None)
            self._disposed_streams[task_id] = True
            while len(self._disposed_streams) > 4096:
                self._disposed_streams.pop(next(iter(self._disposed_streams)))
            running = task_id in self.tasks
            if st is not None:
                for idx, oid in st["items"].items():
                    if idx >= st["next"]:
                        ent = self.objects.get(oid)
                        if ent is not None:
                            ent.refcount -= 1
                            self._maybe_evict(oid, ent)
        if running:
            self.cancel_task(task_id, force=False)
        return True

    def _fail_stream_locked(self, spec: dict) -> None:
        """Lock held. A streaming task's producer died: cap the stream at
        what was produced and point completion at the stored error, so
        consumers drain then raise instead of blocking forever."""
        if spec.get("num_returns") != "streaming":
            return
        if spec["task_id"] in self._disposed_streams:
            return
        st = self.streams.setdefault(
            spec["task_id"], {"items": {}, "count": None, "next": 0}
        )
        if st["count"] is None:
            st["count"] = len(st["items"])
            st["completion"] = spec["return_ids"][0]

    def _finish_stream_locked(self, task_id: bytes, payload: dict):
        """task_done of a streaming task: record the final item count and
        where the completion object (error carrier) lives."""
        if task_id in self._disposed_streams:
            return
        st = self.streams.setdefault(task_id, {"items": {}, "count": None, "next": 0})
        st["count"] = payload.get("stream_count", len(st["items"]))
        results = payload.get("results") or []
        if results:
            st["completion"] = results[0][0]
        self.cv.notify_all()

    def _on_task_done(self, wh: WorkerHandle, payload: dict):
        # singular fast lane (the sync round trip): same receipt contract
        # as the batch path — reply_recv stamped before metrics/re-lay/
        # lock — without the list wrap and second scan
        wf = payload.get("wf")
        if wf is not None and len(wf) == len(_waterfall.PHASES) - 1:
            wf.append(time.time())
        _batch_metrics()["reply"].observe(1)
        results = payload.get("results")
        if results:
            for i, (rid, loc) in enumerate(results):
                nloc = self._normalize_locator(loc)
                if nloc is not loc:
                    results[i] = (rid, nloc)
        with self.lock:
            self._task_done_locked(wh, payload)
            self.cv.notify_all()
            self._schedule()

    def _on_task_done_batch(self, wh: WorkerHandle, payloads: list[dict]):
        """Workers batch completions when they have more queued work
        (worker_main _emit_done): one lock region, one wakeup, one
        scheduling pass per batch instead of per task."""
        now = None
        for payload in payloads:
            wf = payload.get("wf")
            if wf is not None and len(wf) == len(_waterfall.PHASES) - 1:
                # reply_recv stamps at RECEIPT — before metrics, the re-lay
                # scan, and the head lock — so the reply leg measures the
                # worker→head hop, not head-internal bookkeeping (fold
                # detects the already-closed list)
                if now is None:
                    now = time.time()
                wf.append(now)
        _batch_metrics()["reply"].observe(len(payloads))
        for payload in payloads:
            results = payload.get("results")
            if results:
                # big inline results re-lay into shm BEFORE taking the
                # lock; small locators pass through untouched (in-place —
                # no per-task list rebuild)
                for i, (rid, loc) in enumerate(results):
                    nloc = self._normalize_locator(loc)
                    if nloc is not loc:
                        results[i] = (rid, nloc)
        with self.lock:
            for payload in payloads:
                self._task_done_locked(wh, payload)
            self.cv.notify_all()
            self._schedule()

    def _task_done_locked(self, wh: WorkerHandle, payload: dict) -> None:
        task_id = payload["task_id"]
        if "stream_count" in payload:
            self._finish_stream_locked(task_id, payload)
        rec = self.tasks.pop(task_id, None)
        wf = payload.get("wf")
        if wf is not None:
            # reply_recv closes the waterfall: fold the sampled task's
            # stamps into the per-phase histograms + recent ring
            _waterfall.fold(wf, rec["spec"] if rec is not None else None)
        if wh is not None:
            self._worker_pop_done(wh, task_id)
        if rec is None:
            if wh is not None and not wh.queued_recs:
                self._worker_idle(wh)
            return
        # pipelined chain: the completed head's allocation passes to the
        # next leased follower instead of being released (it is now the
        # one running) — exact concurrent accounting, zero idle gap
        nxt = wh.queued_recs[0] if (wh is not None and wh.queued_recs) else None
        if nxt is not None and nxt.get("alloc") is None and rec.get("alloc") is not None:
            nxt["alloc"] = rec.pop("alloc")
            # a pipeline slot freed even though no resources released:
            # same-signature pending tasks can lease-dispatch now
            self._sched_gen += 1
        else:
            self._release_alloc(rec)
        self._unpin_deps(rec["spec"])
        for obj_id, locator in payload.get("results", []):
            self._store_locator(obj_id, locator)
            # remember how to recompute a lost copy (normal tasks only:
            # actor-method replay needs the actor's state at call time)
            if (
                not payload.get("results_error")
                and rec["spec"]["kind"] == "task"
                and GLOBAL_CONFIG.enable_lineage_reconstruction
            ):
                ent = self.objects.get(obj_id)
                if ent is not None:
                    ent.lineage = rec["spec"]
                    self._lineage_track(obj_id, rec["spec"])
        self._event(rec, "FINISHED" if not payload.get("results_error") else "FAILED")
        spec = rec["spec"]
        if spec.get("num_returns") == "streaming" and "stream_count" not in payload:
            # the task function itself failed before yielding anything:
            # close the stream so consumers surface the error
            self._finish_stream_locked(task_id, payload)
        if spec["kind"] == "actor_method":
            actor = self.actors.get(spec["actor_id"])
            if actor is not None:
                actor.inflight.pop(task_id, None)
        if wh is not None and wh.alive and not wh.queued_recs:
            self._worker_idle(wh)

    def _worker_pop_done(self, wh: WorkerHandle, task_id: bytes) -> None:
        """Lock held. Remove a completed task from the worker's dispatch
        FIFO (normally the head; out-of-order only after cancels)."""
        if wh.queued_recs and wh.queued_recs[0]["task_id"] == task_id:
            wh.queued_recs.popleft()
        elif wh.queued_recs:
            wh.queued_recs = deque(
                r for r in wh.queued_recs if r["task_id"] != task_id
            )
        wh.current_task = wh.queued_recs[0] if wh.queued_recs else None

    def _loc_is_local(self, loc) -> bool:
        """Does this shm locator live on the head's own host? (Simulated
        local nodes share the host; only agent nodes are truly remote.)"""
        if loc.node is None:
            return True
        n = self.nodes.get(loc.node)
        return n is None or n.agent is None

    def _release_loc(self, loc) -> None:
        """Free an object's backing wherever it lives: locally via the
        owner registry, or by routing a free_shm to the owning node's agent
        (reference: object directory + raylet-local frees)."""
        if self._loc_is_local(loc):
            self.shm_owner.unlink(loc)
            return
        node = self.nodes.get(loc.node)
        if node is not None and node.agent is not None and node.alive:
            node.agent.send(("free_shm", loc))

    def _store_locator(self, obj_id: bytes, locator, notify: bool = True):
        ent = self.objects.get(obj_id)
        if ent is None:
            ent = self.objects[obj_id] = ObjectEntry()
        kind, payload, is_err = locator
        if kind == "inline":
            ent.small = payload
            ent.size = len(payload)
        else:
            ent.shm = payload
            ent.size = payload.total_size
            events.emit(
                "core.object.locator",
                obj_id=obj_id,
                size=payload.total_size,
                node=payload.node,
                seg=payload.name,
            )
            if self._loc_is_local(payload):
                # only head-host bytes count toward this host's spill
                # watermark; agent-host objects live in THEIR arenas
                self._ensure_capacity(payload.total_size)
                self.shm_owner.register(payload)
        ent.last_access = time.monotonic()
        ent.is_error = is_err
        if notify:
            self._deps_ready(obj_id)
            self.cv.notify_all()

    def _unpin_deps(self, spec: dict):
        if not spec.get("args") and not spec.get("kwargs"):
            return
        for kind, obj_id in _iter_arg_refs(spec):
            ent = self.objects.get(obj_id)
            if ent is not None:
                ent.pins -= 1
                self._maybe_evict(obj_id, ent)

    def _store_error(self, obj_id: bytes, exc: Exception):
        sv = ser.serialize(exc)
        self._store_locator(obj_id, ("inline", sv.to_bytes(), True))

    def _finish_cancelled(self, rec):
        self._release_alloc(rec)
        self.tasks.pop(rec["task_id"], None)
        self._unpin_deps(rec["spec"])
        for rid in rec["spec"]["return_ids"]:
            self._store_error(rid, rex.TaskCancelledError())
        self.cv.notify_all()

    # --------------------------------------------------------------- failure

    def _health_loop(self):
        while not self._shutdown:
            time.sleep(GLOBAL_CONFIG.health_check_interval_s)
            if self._snapshot_path and time.monotonic() >= self._snapshot_due:
                self._snapshot_due = time.monotonic() + GLOBAL_CONFIG.gcs_snapshot_interval_s
                self._snapshot()
            try:
                self._reap_client_sessions()
            except Exception as e:
                # session cleanup must never kill the health loop
                warn_throttled("health loop: client session reap", e)
            with self.lock:
                # prune expired named-mutex leases (crashed holders whose
                # release never arrived) — unbounded growth otherwise
                now_m = time.monotonic()
                for mname in [
                    n for n, (_o, exp) in self._named_mutexes.items() if exp <= now_m
                ]:
                    del self._named_mutexes[mname]
            dead, reap, timed_out = [], [], []
            keep = GLOBAL_CONFIG.idle_worker_keep_alive_s
            reg_timeout = GLOBAL_CONFIG.worker_register_timeout_s
            now = time.monotonic()
            with self.lock:
                for node in self.nodes.values():
                    for wh in list(node.all_workers):
                        if (
                            wh.alive
                            and wh.proc is not None
                            and not wh.proc.is_alive()
                            and wh.conn is not None
                        ):
                            dead.append(wh)
                        elif (
                            wh.alive
                            and wh.conn is None
                            and reg_timeout > 0
                            and now - wh.created_at > reg_timeout
                        ):
                            # spawned but never registered: a process that
                            # wedged at interpreter start (or an agent-side
                            # spawn that crashed where we hold no handle).
                            # Kill + respawn instead of hanging its waiters
                            # forever (reference: worker_register_timeout_seconds,
                            # ray_config_def.h; worker_pool.h startup tokens).
                            timed_out.append(wh)
                        elif (
                            wh.alive
                            and wh.proc is not None
                            and not wh.proc.is_alive()
                            and wh.conn is None
                        ):
                            # local spawn died before registering: no point
                            # waiting out the registration deadline
                            timed_out.append(wh)
                        elif (
                            wh.alive
                            and wh.proc is None
                            and wh.conn is None
                            and reg_timeout <= 0
                            and now - wh.created_at > 60.0
                        ):
                            # registration timeout disabled: keep the legacy
                            # reap of agent-side spawns that crashed before
                            # connecting (no proc handle to poll)
                            dead.append(wh)
                    # Reap workers idle beyond the keep-alive (reference:
                    # worker_pool idle worker killing), but never while work
                    # is queued for the node.
                    if keep > 0 and not self.pending_sched and not node.assigned:
                        for wh in list(node.idle_workers):
                            if wh.actor_id is None and now - wh.idle_since > keep:
                                node.idle_workers.remove(wh)
                                node.all_workers.discard(wh)
                                wh.alive = False
                                reap.append(wh)
            for wh in reap:
                wh.send(("exit", None))
            for wh in dead:
                self._on_worker_dead(wh)
            for wh in timed_out:
                self._respawn_timed_out(wh)
            # refresh this host's /proc stats onto its (non-agent) nodes
            try:
                from ray_tpu._private.reporter import node_stats

                stats = node_stats()
                with self.lock:
                    for n in self.nodes.values():
                        if n.agent is None:
                            n.stats = stats
            except Exception as e:
                warn_throttled("health loop: /proc stats refresh", e)
            # object-plane residency gauges (ISSUE 19): this host's arena /
            # spill bytes every tick; agent-node gauges refresh when a
            # ledger/audit rendezvous actually gathers their reports
            try:
                self._publish_object_gauges()
            except Exception as e:
                warn_throttled("health loop: object-plane gauges", e)
            # restored detached actors whose old workers never reconnected:
            # past the grace window, re-create them fresh (reference:
            # gcs_actor_manager restart of registered actors on failover)
            if (
                self._restored_actors
                and now - self._restore_time > GLOBAL_CONFIG.head_reconnect_grace_s
            ):
                with self.lock:
                    for aid in list(self._restored_actors):
                        self._restored_actors.discard(aid)
                        actor = self.actors.get(aid)
                        if (
                            actor is not None
                            and actor.state == ACTOR_RESTARTING
                            and actor.worker is None
                        ):
                            self._recreate_actor_locked(actor)
                    self._schedule()
            self.flush_outbox()

    def _respawn_timed_out(self, wh: WorkerHandle) -> None:
        """A spawned worker missed its registration deadline: kill it and
        retry the spawn (bounded), without charging the actor-restart budget
        — a wedge at interpreter start is an environment hiccup, not an
        application failure. On exhaustion an actor creation fails through
        the actor FSM; a pool slot's queued work goes back to the scheduler.
        Reference: worker_register_timeout_seconds (ray_config_def.h)
        + worker_pool.h startup-token accounting."""
        with self.lock:
            if wh.conn is not None or not wh.alive:
                return  # registered (or was reaped) before we acted
            wh.alive = False
            node = wh.node
            node.all_workers.discard(wh)
            if wh.token:
                # a racing late registration must match nothing and be told
                # to exit, not fall back to a fresh pool handle
                self._revoked_tokens[wh.token] = True
                while len(self._revoked_tokens) > 1024:
                    self._revoked_tokens.pop(next(iter(self._revoked_tokens)))
            actor_id = wh.actor_id
            attempts = wh.spawn_attempts + 1
            retry = node.alive and attempts <= GLOBAL_CONFIG.worker_spawn_retries
            if actor_id is None:
                # return the spawn slot; a retry re-claims it immediately so
                # _maybe_spawn doesn't double-spawn for the same queued work
                node.spawning = max(0, node.spawning - 1)
                if retry:
                    node.spawning += 1
        # kill only after the handle is dead and its token revoked (above):
        # registration can no longer win the race and then be shot
        if wh.proc is not None and wh.proc.is_alive():
            wh.proc.terminate()
        elif wh.proc is None and node.agent is not None and wh.token:
            node.agent.send(("kill_worker", {"token": wh.token}))
        print(
            f"[ray_tpu] worker (attempt {attempts}) on node "
            f"{node.node_id.hex()[:8]} did not register within "
            f"{GLOBAL_CONFIG.worker_register_timeout_s}s; "
            + ("respawning" if retry else "giving up")
        )
        if retry:
            threading.Thread(
                target=self._spawn_worker,
                args=(node, actor_id),
                kwargs={"attempts": attempts},
                daemon=True,
            ).start()
        elif actor_id is not None:
            # exhausted: let the actor FSM decide (restart budget / fail refs)
            with self.lock:
                self._on_actor_worker_death(actor_id)
                self._schedule()
        else:
            # exhausted: hand this node's queued work back to the scheduler
            # so it can land on another node — or start a fresh spawn chain
            # here if this is the only one (never strand it in node.assigned,
            # which nothing re-examines)
            with self.lock:
                while node.assigned:
                    rec = node.assigned.popleft()
                    self._release_alloc(rec)
                    rec["state"] = "PENDING"
                    rec["node"] = None
                    self.pending_sched.append(rec)
                self._schedule()

    # ------------------------------------------------------- memory monitor

    def memory_usage_fraction(self) -> float:
        """Host memory usage in [0, 1]. Tests inject ``_memory_sampler``
        (reference: memory_monitor.h reads cgroup/proc the same way)."""
        sampler = getattr(self, "_memory_sampler", None)
        if sampler is not None:
            return sampler()
        try:
            with open("/proc/meminfo") as f:
                info = {}
                for line in f:
                    parts = line.split()
                    info[parts[0].rstrip(":")] = int(parts[1])
            total = info.get("MemTotal", 1)
            avail = info.get("MemAvailable", total)
            return 1.0 - avail / total
        except Exception:
            return 0.0

    def _memory_monitor_loop(self):
        """Kill a victim worker when host memory crosses the threshold
        (reference: ``memory_monitor.h:52`` + retriable-FIFO policy in
        ``worker_killing_policy_retriable_fifo.h:31``)."""
        interval = GLOBAL_CONFIG.memory_monitor_refresh_ms / 1000.0
        while not self._shutdown:
            time.sleep(interval)
            try:
                if self.memory_usage_fraction() < GLOBAL_CONFIG.memory_usage_threshold:
                    continue
                self._kill_for_memory()
                self.flush_outbox()  # requeued victims' redispatches
            except Exception as e:
                warn_throttled("memory monitor loop", e)

    def _kill_for_memory(self):
        with self.lock:
            candidates = [
                (wh, rec)
                for node in self.nodes.values()
                for wh in node.all_workers
                if wh.alive
                and (rec := wh.current_task) is not None
                and rec["spec"]["kind"] == "task"
            ]
            if not candidates:
                return
            # retriable-FIFO: prefer a victim whose task can retry; among
            # those, the most recently started (preserve older progress)
            def key(item):
                wh, rec = item
                retriable = rec.get("retries_left", 0) != 0
                return (retriable, rec.get("started_at", 0.0))

            wh, rec = max(candidates, key=key)
            rec["oom_killed"] = True
            self._event(rec, "OOM_KILLED")
        if wh.proc is not None:
            try:
                wh.proc.terminate()
            except Exception:
                pass
        else:
            wh.send(("exit", None))
        self._on_worker_dead(wh)

    def _on_worker_disconnect(self, wh: WorkerHandle):
        if wh.proc is not None and wh.proc.is_alive():
            # Graceful exit or crash; health loop would catch it, but react now.
            wh.proc.join(timeout=0.5)
        self._on_worker_dead(wh)

    def _on_worker_dead(self, wh: WorkerHandle):
        with self.lock:
            self._handle_worker_death_locked(wh)
            self._schedule()

    def _handle_worker_death_locked(self, wh: WorkerHandle):
        if not wh.alive:
            return
        wh.alive = False
        node = wh.node
        if wh.actor_id is None and wh.conn is None:
            # died before registering: return the spawn slot, or _maybe_spawn
            # under-counts the pool forever (worst case: node stops spawning)
            node.spawning = max(0, node.spawning - 1)
        node.all_workers.discard(wh)
        if wh in node.idle_workers:
            node.idle_workers.remove(wh)
        if wh.proc is not None:
            from ray_tpu._private.reporter import reap_stack_file

            reap_stack_file(wh.proc.pid)
        # the whole dispatch FIFO dies with the worker. Only the HEAD of the
        # queue was executing — it is charged a retry (or failed). Pipelined
        # followers never ran an instruction: they requeue to the scheduler
        # free of charge (the reference likewise only charges attempts that
        # actually started).
        first = True
        for rec in list(wh.queued_recs):
            if rec["task_id"] in self.tasks and rec["spec"]["kind"] == "task":
                if first:
                    self.tasks.pop(rec["task_id"], None)
                    cause = (
                        rex.OutOfMemoryError(
                            f"Task {rec['spec'].get('name')} was killed by the memory "
                            f"monitor to relieve host memory pressure"
                        )
                        if rec.get("oom_killed")
                        else rex.WorkerCrashedError()
                    )
                    self._requeue_or_fail(rec, cause)
                else:
                    self._release_alloc(rec)
                    rec["state"] = "PENDING"
                    rec["worker"] = None
                    rec["spec"].pop("_pg_bundle", None)
                    self.pending_sched.append(rec)
            first = False
        wh.queued_recs.clear()
        wh.current_task = None
        if wh.actor_id is not None:
            self._on_actor_worker_death(wh.actor_id)

    def _requeue_or_fail(self, rec, error: Exception):
        """Lock held. Task retry semantics (reference task_manager.cc:
        ``max_retries`` for normal tasks; actor methods obey the actor's
        ``max_task_retries``)."""
        self._release_alloc(rec)
        spec = rec["spec"]
        if rec["task_id"] in self.cancelled:
            self._finish_cancelled(rec)
            return
        if spec["kind"] == "actor_method":
            # handled by the actor restart machinery
            return
        if rec["retries_left"] != 0:  # -1 = unlimited (reference max_retries)
            if rec["retries_left"] > 0:
                rec["retries_left"] -= 1
            rec["state"] = "PENDING"
            rec["worker"] = None
            rec.pop("oom_killed", None)  # fresh attempt, fresh failure cause
            spec.pop("_pg_bundle", None)
            self._event(rec, "RETRY")
            self.tasks[rec["task_id"]] = rec
            self.pending_sched.append(rec)
        else:
            self.tasks.pop(rec["task_id"], None)
            self._unpin_deps(spec)
            for rid in spec["return_ids"]:
                self._store_error(rid, error)
            self._fail_stream_locked(spec)
            self.cv.notify_all()

    # ---------------------------------------------------------------- actors

    def create_actor(self, spec: dict) -> None:
        with self.lock:
            actor = ActorState(spec["actor_id"], spec)
            key = actor.named_key
            if key and key in self.named_actors:
                # check BEFORE registering, so a duplicate name leaves no
                # orphan PENDING actor behind
                raise ValueError(
                    f"Actor name {actor.name!r} already taken in namespace "
                    f"{actor.namespace!r}"
                )
            self.actors[spec["actor_id"]] = actor
            if key:
                self.named_actors[key] = spec["actor_id"]
        self.submit_task(spec)

    def _start_actor_on(self, rec, node: NodeState):
        """Lock held. Actor creation got a node: adopt an idle pool worker
        when the env allows it, else spawn a dedicated worker.

        Adoption (reference: the raylet hands actor-creation leases to
        already-started pool workers — workers are generic processes there
        too) skips the whole spawn pipeline: the actor starts in one
        dispatch instead of interpreter boot + registration. Only a
        container env forces a dedicated cold spawn (the pool worker runs
        outside the requested image); conda/pip/env_vars apply in-worker at
        create time exactly as they would in a fresh process."""
        spec = rec["spec"]
        actor = self.actors[spec["actor_id"]]
        actor.node_id = node.node_id
        rec["state"] = "RUNNING"
        if not (spec.get("runtime_env") or {}).get("container"):
            while node.idle_workers:
                wh = node.idle_workers.pop()
                if (
                    wh.alive
                    and wh.conn is not None
                    and wh.actor_id is None
                    and not wh.queued_recs
                ):
                    wh.actor_id = spec["actor_id"]
                    self._dispatch_to_worker(wh, rec)
                    return
        # Keyed by actor id, NOT queued on node.assigned: only the dedicated
        # worker spawned for this actor may pick it up.
        self._actor_create_recs[spec["actor_id"]] = rec
        self._spawn_q.put((self._spawn_actor_worker, (node, spec["actor_id"]), {}))

    def _spawn_actor_worker(self, node: NodeState, actor_id: bytes):
        self._spawn_worker(node, actor_id=actor_id)

    def _on_actor_ready(self, wh: WorkerHandle, payload):
        actor_id = payload["actor_id"]
        with self.lock:
            actor = self.actors.get(actor_id)
            if actor is None:
                return
            if actor.state == ACTOR_DEAD:
                # killed while this spawn was in flight: NEVER resurrect —
                # the fallback re-reserve below would allocate resources no
                # kill will ever release. Tell the orphan worker to exit.
                self._enqueue_send(wh, ("exit",))
                return
            if payload.get("error") is not None:
                # __init__ raised: actor is DEAD, creation error propagates to
                # the creation "ready" object and all queued calls.
                self._kill_actor_locked(actor, payload["error"], restart=False)
                return
            actor.state = ACTOR_ALIVE
            self.publish("actors", {"event": "ALIVE", "actor_id": actor.actor_id.hex(), "name": actor.name})
            actor.worker = wh
            wh.actor_id = actor_id
            rec = self.tasks.pop(actor.create_spec["task_id"], None)
            if rec is not None:
                actor.alloc = rec.pop("alloc", None)
                self._event(rec, "FINISHED")
            elif actor.alloc is None:
                # reconnected after head restart: no create task carried an
                # allocation — re-reserve the actor's lifetime resources on
                # its node (may briefly oversubscribe right after failover)
                res = self._effective_resources(actor.create_spec)
                wh.node.allocate(res)
                actor.alloc = (wh.node.node_id.binary(), res, None)
            for rid in actor.create_spec["return_ids"]:
                sv = ser.serialize(None)
                self._store_locator(rid, ("inline", sv.to_bytes(), False))
            while actor.pending_calls:
                mspec = actor.pending_calls.popleft()
                self._send_actor_task(actor, mspec)
            self.cv.notify_all()

    def submit_actor_task(self, spec: dict) -> None:
        with self.lock:
            self._submit_actor_task_locked(spec)

    def _submit_actor_task_locked(self, spec: dict) -> None:
        for rid in spec["return_ids"]:  # submitter's refs (see submit_task)
            ent = self.objects.get(rid)
            if ent is None:
                ent = self.objects[rid] = ObjectEntry()
            ent.refcount += 1
        actor = self.actors.get(spec["actor_id"])
        if actor is None or actor.state == ACTOR_DEAD:
            cause = actor.death_cause if actor else "actor not found"
            for rid in spec["return_ids"]:
                self._store_error(rid, rex.ActorDiedError(msg=f"Actor is dead: {cause}"))
            return
        rec = {"task_id": spec["task_id"], "spec": spec, "state": "PENDING", "worker": None, "retries_left": actor.max_task_retries}
        self.tasks[spec["task_id"]] = rec
        # Pin ObjectRef args until completion (mirrors submit_task); the
        # actor worker fetches them at execution time.
        for _kind, payload in _iter_arg_refs(spec):
            ent = self.objects.get(payload)
            if ent is None:
                ent = self.objects[payload] = ObjectEntry()
            ent.pins += 1
        if actor.state == ACTOR_ALIVE:
            self._send_actor_task(actor, spec)
        else:
            actor.pending_calls.append(spec)

    def _send_actor_task(self, actor: ActorState, spec: dict):
        """Lock held. Actor calls reach the actor's worker in submission
        order: the outbox is per-worker FIFO and flush_outbox preserves it,
        so coalesced actor-call bursts ride one ``run_task_batch`` write
        (socket FIFO = the reference's sequential actor submit queue). A
        dead conn surfaces at flush as worker death, which runs the actor
        restart machinery — dispatch can no longer fail synchronously."""
        actor.inflight[spec["task_id"]] = spec
        rec = self.tasks.get(spec["task_id"])
        if rec is not None:
            rec["state"] = "RUNNING"
            rec["worker"] = actor.worker
        wf = spec.get("wf")
        if wf is not None:
            _waterfall.stamp(wf)  # head_dispatch: about to queue the send
        self._enqueue_send(actor.worker, ("run_task", spec))

    def _on_actor_worker_death(self, actor_id: bytes):
        """Lock held. Actor restart state machine (reference
        gcs_actor_manager.cc: restart if restarts remain, else mark DEAD and
        fail inflight + queued calls)."""
        actor = self.actors.get(actor_id)
        if actor is None or actor.state == ACTOR_DEAD:
            return
        inflight = list(actor.inflight.values())
        actor.inflight.clear()
        actor.worker = None
        self._actor_create_recs.pop(actor_id, None)
        self._release_alloc({"alloc": actor.alloc} if actor.alloc else {})
        actor.alloc = None
        if actor.restarts_left != 0:
            if actor.restarts_left > 0:
                actor.restarts_left -= 1
            actor.state = ACTOR_RESTARTING
            self.publish("actors", {"event": "RESTARTING", "actor_id": actor.actor_id.hex(), "name": actor.name})
            # inflight calls with retry budget left are re-queued ahead of new
            # calls; the rest fail (reference: max_task_retries per call,
            # -1 = unlimited)
            retry = []
            for s in inflight:
                rec = self.tasks.get(s["task_id"])
                left = rec["retries_left"] if rec is not None else 0
                if s.get("num_returns") == "streaming":
                    # never replay a stream: the consumer may have consumed
                    # items of the dead run already (same rule as tasks)
                    left = 0
                if left != 0:
                    if rec is not None and left > 0:
                        rec["retries_left"] -= 1
                    retry.append(s)
                else:
                    self.tasks.pop(s["task_id"], None)
                    self._unpin_deps(s)
                    for rid in s["return_ids"]:
                        self._store_error(rid, rex.RayActorError(msg="actor died; restarting"))
                    self._fail_stream_locked(s)
            for s in reversed(retry):
                actor.pending_calls.appendleft(s)
            self._recreate_actor_locked(actor)
        else:
            self._kill_actor_locked(actor, "worker died", restart=False, inflight=inflight)
        self.cv.notify_all()

    def _recreate_actor_locked(self, actor: ActorState) -> None:
        """Lock held. Queue a fresh creation task for a RESTARTING actor.

        If the worker died mid-creation, reap the in-flight create task:
        release its allocation and carry its return ids into the retry so
        they eventually resolve."""
        old_rec = self.tasks.pop(actor.create_spec["task_id"], None)
        if old_rec is not None:
            self._release_alloc(old_rec)
        cspec = dict(actor.create_spec)
        cspec["task_id"] = TaskID.from_random().binary()
        cspec["return_ids"] = actor.create_spec["return_ids"] if old_rec is not None else []
        # Future lookups (ready/kill) must see the re-creation task's id,
        # or its record + resource allocation leak forever.
        actor.create_spec = cspec
        rec = {"task_id": cspec["task_id"], "spec": cspec, "deps": set(), "state": "PENDING", "worker": None, "retries_left": 0}
        self.tasks[cspec["task_id"]] = rec
        self.pending_sched.append(rec)

    def _kill_actor_locked(self, actor: ActorState, cause, restart: bool, inflight=None):
        actor.state = ACTOR_DEAD
        self.publish("actors", {"event": "DEAD", "actor_id": actor.actor_id.hex(), "name": actor.name})
        actor.death_cause = str(cause)
        err = cause if isinstance(cause, Exception) else rex.ActorDiedError(msg=str(cause))
        for s in (inflight or []) + list(actor.inflight.values()) + list(actor.pending_calls):
            self.tasks.pop(s["task_id"], None)
            self._unpin_deps(s)
            for rid in s["return_ids"]:
                self._store_error(rid, err)
            self._fail_stream_locked(s)
        actor.inflight.clear()
        actor.pending_calls.clear()
        self._actor_create_recs.pop(actor.actor_id, None)
        self._release_alloc({"alloc": actor.alloc} if actor.alloc else {})
        actor.alloc = None
        rec = self.tasks.pop(actor.create_spec["task_id"], None)
        if rec is not None:
            self._release_alloc(rec)
            for rid in actor.create_spec["return_ids"]:
                self._store_error(rid, err)
        if actor.named_key and self.named_actors.get(actor.named_key) == actor.actor_id:
            del self.named_actors[actor.named_key]
        wh = actor.worker
        if wh is not None:
            wh.actor_id = None
            wh.alive = False
            if wh.proc is not None and wh.proc.is_alive():
                wh.proc.terminate()
        self.cv.notify_all()

    def kill_actor(self, actor_id: bytes, no_restart: bool = True):
        with self.lock:
            actor = self.actors.get(actor_id)
            if actor is None:
                return
            if no_restart:
                actor.restarts_left = 0
                self._kill_actor_locked(actor, "ray.kill", restart=False)
            else:
                wh = actor.worker
                if wh is not None and wh.proc is not None:
                    wh.proc.terminate()

    def remove_actor_handle(self, actor_id: bytes):
        """Driver-side handle count dropped; non-detached actors exit when the
        last handle dies (reference: actor GC via reference counting)."""
        with self.lock:
            actor = self.actors.get(actor_id)
            if actor is None:
                return
            actor.num_handles -= 1
            if actor.num_handles <= 0 and not actor.detached and actor.state != ACTOR_DEAD:
                actor.restarts_left = 0
                self._kill_actor_locked(actor, "all handles out of scope", restart=False)

    # -------------------------------------------------------------- objects

    def put_serialized(
        self, sv: ser.SerializedValue, is_error=False, take_ref=False
    ) -> bytes:
        obj_id = ObjectID.for_put().binary()
        self.put_at(obj_id, sv, is_error, take_ref=take_ref)
        return obj_id

    def put_at(
        self, obj_id: bytes, sv: ser.SerializedValue, is_error=False, take_ref=False
    ):
        # same zero-copy cutoff as runtime.store_value (ISSUE 18): with the
        # native arena up, driver puts above core_shm_inline_threshold go
        # straight to shm — consumers map them instead of copying them off
        # the control socket. Without the arena the old 100KB cutoff stands
        # (a dedicated segment per mid-size object costs more than inlining).
        threshold = (
            GLOBAL_CONFIG.core_shm_inline_threshold
            if self.arena_name is not None
            else GLOBAL_CONFIG.max_direct_call_object_size
        )
        if sv.total_size <= threshold:
            locator = ("inline", sv.to_bytes(), is_error)
        else:
            from ray_tpu._private.runtime import _data_counters
            from ray_tpu._private.shm_store import write_shm

            locator = ("shm", write_shm(sv), is_error)
            _data_counters()[0].inc(sv.total_size)
            events.emit(
                "core.object.put",
                obj_id=obj_id,
                size=sv.total_size,
                seg=locator[1].name,
            )
        with self.lock:
            # fresh put ids have no waiters (see rpc_put): skip the wakeup
            fresh = obj_id not in self.objects
            self._store_locator(obj_id, locator, notify=not fresh)
            if take_ref:
                self.objects[obj_id].refcount += 1

    def _pump_or_wait(self, t: float) -> None:
        """A getter with nothing to do yet either takes over the worker-IO
        pump (processing completions on ITS thread — the message that makes
        its object ready wakes no one else first) or, when another thread
        already pumps, parks on the condition variable. Single pump at a
        time via _pump_mutex; the IO thread defers while _pump_requests>0.
        Never called with the head lock held."""
        if self._outbox:
            # deferred dispatches (coalesced submits, lineage rebuilds) must
            # ride out BEFORE this thread parks waiting on their results
            self.flush_outbox()
        with self._pump_count_lock:
            self._pump_requests += 1
            self._last_pump = time.monotonic()
        try:
            # fast path: mutex free (IO thread parked in its sticky-grace
            # window) — no kick, no handoff, straight to the select
            acquired = self._pump_mutex.acquire(blocking=False)
            if not acquired:
                try:
                    os.write(self._io_wake_w, b"p")  # kick IO out of its select
                except OSError:
                    pass
                acquired = self._pump_mutex.acquire(timeout=min(t, 0.005))
            if not acquired:
                with self.lock:
                    self.cv.wait(timeout=t)
                return
            try:
                if self._shutdown:
                    return
                if not self._io_conns:
                    with self.lock:
                        self.cv.wait(timeout=min(t, 0.01))
                    return
                progressed = self._drain_io(
                    self._pump_sel, self._pump_registered, self._io_prog_r, t,
                    once=True, reg_gen=self._pump_reg_gen,
                )
                if progressed:
                    self.flush_outbox()
                    if self._pump_requests > 1:
                        # other getters wait behind the mutex/cv: what we
                        # just handled may be THEIR completion
                        try:
                            os.write(self._io_prog_w, b"g")
                        except OSError:
                            pass
            finally:
                self._pump_mutex.release()
        finally:
            with self._pump_count_lock:
                self._pump_requests -= 1
            # No _io_resume.set() here: waking the IO thread's waiter is a
            # futex wake (~50us) paid once per get. The IO thread self-wakes
            # from its 10ms park (_worker_io_loop), so the pump hand-back is
            # bounded-latency instead of immediate — a sync get loop pumps
            # its own completions and never needs the IO thread anyway.

    def get_locators(self, obj_ids: list[bytes], timeout: Optional[float]) -> list:
        if len(obj_ids) == 1:
            # single-ref get (the sync round-trip pattern): no index
            # machinery, one dict probe per readiness check
            oid = obj_ids[0]
            deadline = None if timeout is None else time.monotonic() + timeout
            objects = self.objects
            while True:
                with self.lock:
                    ent = objects.get(oid)
                    if ent is not None and ent.ready:
                        if ent.small is None and ent.shm is None:
                            self._restore_spilled(oid, ent)
                        if ent.ready:  # restore may fail INTO lineage rebuild
                            ent.last_access = ent.last_read = time.monotonic()
                            return [ent.locator()]
                    if self._shutdown:
                        raise rex.RayError("shutting down")
                remaining = None if deadline is None else deadline - time.monotonic()
                if remaining is not None and remaining <= 0:
                    raise rex.GetTimeoutError(f"Get timed out on {ObjectID(oid)}")
                self._pump_or_wait(min(remaining, 0.05) if remaining else 0.05)
        deadline = None if timeout is None else time.monotonic() + timeout
        out = []
        i = 0
        while True:
            with self.lock:
                while i < len(obj_ids):
                    oid = obj_ids[i]
                    ent = self.objects.get(oid)
                    if ent is not None and ent.ready:
                        if ent.small is None and ent.shm is None:
                            self._restore_spilled(oid, ent)  # transparent
                        if ent.ready:  # restore may fail INTO lineage
                            # reconstruction, which empties the entry — then
                            # keep waiting for the recomputed value instead
                            ent.last_access = ent.last_read = time.monotonic()
                            out.append(ent.locator())
                            i += 1
                            continue
                    break
                if i >= len(obj_ids):
                    return out
                if self._shutdown:
                    raise rex.RayError("shutting down")
            remaining = None if deadline is None else deadline - time.monotonic()
            if remaining is not None and remaining <= 0:
                raise rex.GetTimeoutError(f"Get timed out on {ObjectID(obj_ids[i])}")
            self._pump_or_wait(min(remaining, 0.05) if remaining else 0.05)

    def wait_objects(self, obj_ids: list[bytes], num_returns: int, timeout: Optional[float]):
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            with self.lock:
                ready = [oid for oid in obj_ids if (e := self.objects.get(oid)) and e.ready]
                if len(ready) >= num_returns:
                    return ready
            remaining = None if deadline is None else deadline - time.monotonic()
            if remaining is not None and remaining <= 0:
                return ready
            self._pump_or_wait(min(remaining, 0.05) if remaining else 0.05)

    def add_ref(self, obj_id: bytes):
        with self.lock:
            ent = self.objects.get(obj_id)
            if ent is None:
                ent = self.objects[obj_id] = ObjectEntry()
            ent.refcount += 1

    def remove_ref(self, obj_id: bytes):
        with self.lock:
            ent = self.objects.get(obj_id)
            if ent is None:
                return
            ent.refcount -= 1
            self._maybe_evict(obj_id, ent)

    def remove_refs(self, obj_ids: list) -> None:
        """Batched decrement (GC drains coalesce ref drops): one lock
        region for a whole burst of ``ObjectRef.__del__`` frees instead of
        a head round trip per dead ref."""
        with self.lock:
            for obj_id in obj_ids:
                ent = self.objects.get(obj_id)
                if ent is not None:
                    ent.refcount -= 1
                    self._maybe_evict(obj_id, ent)

    def _note_freed(self, obj_id: bytes, ent: ObjectEntry, reason: str) -> None:
        """Lock held. Forensic trail for an entry leaving the directory:
        the ``core.object.free`` event, the lifetime histogram observation,
        and the bounded freed ring ``obs objects`` shows."""
        age = max(0.0, time.time() - ent.created)
        _object_metrics()["age"].observe(age)
        self._freed_ring.append(
            (ObjectID(obj_id).hex(), ent.size, age, time.time(), reason)
        )
        events.emit(
            "core.object.free",
            obj_id=obj_id,
            size=ent.size,
            reason=reason,
        )

    def _maybe_evict(self, obj_id: bytes, ent: ObjectEntry):
        if ent.refcount <= 0 and ent.pins <= 0 and ent.ready:
            self.objects.pop(obj_id, None)
            self._note_freed(obj_id, ent, "refcount")
            if ent.shm is not None:
                self._release_loc(ent.shm)
            if ent.spill_path is not None:
                try:
                    os.unlink(ent.spill_path)
                except OSError:
                    pass

    # ------------------------------------------------------------- spilling

    def _spill_threshold(self) -> int:
        t = GLOBAL_CONFIG.object_spilling_threshold_bytes
        if t:
            return t
        return GLOBAL_CONFIG.object_store_memory or (2 << 30)

    def _spill_dir(self) -> str:
        d = os.path.join(os.path.dirname(self.socket_path), "spill")
        os.makedirs(d, exist_ok=True)
        return d

    def _ensure_capacity(self, incoming: int) -> None:
        """Lock held. Spill LRU shm objects to disk until ``incoming`` more
        bytes fit under the watermark (reference:
        ``raylet/local_object_manager.h:41-76`` spill-to-external-storage).
        Pinned objects (in-flight task args) are exempt; existing reader
        mappings survive the unlink — restore creates a fresh segment."""
        limit = self._spill_threshold()
        if self.shm_owner.bytes_used + incoming <= limit:
            return
        now = time.monotonic()
        victims = sorted(
            (
                (oid, e)
                for oid, e in self.objects.items()
                # grace window: a locator handed out moments ago may not be
                # attached yet — unlinking it would FileNotFoundError the
                # reader (clients also re-fetch on that error as a backstop)
                if e.shm is not None
                and e.pins <= 0
                and now - e.last_read > 5.0
                # agent-host objects can't be spilled from here (their bytes
                # live in another host's arena)
                and self._loc_is_local(e.shm)
            ),
            key=lambda kv: kv[1].last_access,
        )
        for oid, ent in victims:
            if self.shm_owner.bytes_used + incoming <= limit:
                break
            self._spill_one(oid, ent)

    def _spill_one(self, obj_id: bytes, ent: ObjectEntry) -> None:
        from ray_tpu._private.shm_store import ShmReader

        try:
            reader = ShmReader(ent.shm)
            try:
                data = reader.read_serialized_bytes()
            finally:
                reader.close()
            path = os.path.join(self._spill_dir(), ObjectID(obj_id).hex())
            with open(path, "wb") as f:
                f.write(data)
        except Exception:
            return  # spill is best-effort; the object stays in shm
        events.emit(
            "core.object.spill", obj_id=obj_id, size=ent.size, path=path
        )
        _object_metrics()["spills"].inc()
        self.shm_owner.unlink(ent.shm)
        ent.shm = None
        ent.spill_path = path

    def _restore_spilled(self, obj_id: bytes, ent: ObjectEntry) -> None:
        """Lock held. Transparent restore on access (reference:
        ``local_object_manager`` restore path). A lost/corrupt spill file
        marks the object LOST (callers get ObjectLostError) instead of
        raising an opaque I/O error on every get forever."""
        from ray_tpu._private.shm_store import write_shm

        try:
            with open(ent.spill_path, "rb") as f:
                data = f.read()
            sv = ser.SerializedValue.from_bytes(data)
        except Exception:
            ent.spill_path = None
            # rebuild via lineage; failure stores ObjectLostError on the entry
            self._reconstruct(obj_id, ent)
            return
        self._ensure_capacity(sv.total_size)
        ent.shm = write_shm(sv)
        self.shm_owner.register(ent.shm)
        events.emit(
            "core.object.restore",
            obj_id=obj_id,
            size=sv.total_size,
            seg=ent.shm.name,
        )
        try:
            os.unlink(ent.spill_path)
        except OSError:
            pass
        ent.spill_path = None

    def _lineage_spec_size(self, spec: dict) -> int:
        n = 512
        args = spec.get("args")
        kwargs = spec.get("kwargs")
        if not args and not kwargs:
            return n
        for a in list(args or ()) + list(kwargs.values() if kwargs else ()):
            if a[0] != "r":
                n += len(a[1])
        return n

    def _lineage_track(self, obj_id: bytes, spec: dict) -> None:
        """Lock held. Bound total retained lineage (reference: lineage
        total-size eviction, reference_count.h lineage pinning budget):
        over the cap, the oldest objects silently lose reconstructability."""
        size = self._lineage_spec_size(spec)
        self._lineage_fifo.append((obj_id, size))
        self._lineage_total += size
        cap = GLOBAL_CONFIG.max_lineage_bytes
        while self._lineage_total > cap and self._lineage_fifo:
            old_id, old_size = self._lineage_fifo.popleft()
            self._lineage_total -= old_size
            old = self.objects.get(old_id)
            if old is not None:
                old.lineage = None

    def _reconstruct(self, obj_id: bytes, ent: ObjectEntry) -> bool:
        """Lock held. Resubmit the creating task to rebuild a lost object
        (reference: ObjectRecoveryManager::RecoverObject,
        core_worker/object_recovery_manager.h:41). Returns True when a
        resubmission is queued/running — getters then block until the task
        stores fresh results. Fails (False) when an input of the creating
        task is itself gone without lineage."""
        spec = ent.lineage
        ent.small = None
        ent.shm = None
        ent.spill_path = None
        if spec is not None and spec["task_id"] in self.tasks:
            return True  # already being recomputed (another lost return)
        pinned: list = []
        failed = spec is None  # e.g. ray.put objects: no creating task
        for _kind, arg_id in (() if spec is None else _iter_arg_refs(spec)):
            arg = self.objects.get(arg_id)
            if arg is None:
                failed = True  # input gone without a record: unrecoverable
                break
            in_flight = any(
                arg_id in t["spec"]["return_ids"] for t in self.tasks.values()
            )
            if not arg.ready and not in_flight and not self._reconstruct(arg_id, arg):
                # recursive rebuild impossible (marked LOST below): this
                # task would wait on its arg forever — fail instead of hang
                failed = True
                break
            arg.pins += 1
            pinned.append(arg)
        if failed:
            for arg in pinned:  # no task queued: release this loop's pins
                arg.pins -= 1
            err = ser.serialize(
                rex.ObjectLostError(
                    ObjectID(obj_id).hex(), "object lost and not reconstructable"
                )
            )
            ent.small = err.to_bytes()
            ent.is_error = True
            ent.lineage = None
            return False
        rec = {
            "task_id": spec["task_id"],
            "spec": spec,
            "state": "PENDING",
            "worker": None,
            "retries_left": 0,
            "reconstruction": True,
        }
        self.tasks[spec["task_id"]] = rec
        self.pending_sched.append(rec)
        self._event(rec, "PENDING_ARGS_AVAIL")
        self._schedule()
        return True

    def rpc_report_lost(self, obj_ids):
        """A reader found an object's shm backing gone (segment unlinked /
        arena block recycled): verify, then reconstruct via lineage or mark
        LOST. The caller re-issues its get, which blocks until ready."""
        from ray_tpu._private.shm_store import ShmReader

        # Verify before destroying anything: a report can also mean the
        # CALLER had a transient problem (unreachable data server, auth,
        # network) — freeing a healthy object on hearsay would turn a
        # blip into permanent loss for no-lineage (ray.put) objects.
        from ray_tpu._private import data_plane

        foreign: list[tuple] = []
        with self.lock:
            lost: list[bytes] = []
            for oid in obj_ids:
                ent = self.objects.get(oid)
                if ent is None or ent.small is not None or ent.shm is None:
                    continue  # inline data or already being handled
                if self._loc_is_local(ent.shm):
                    try:
                        ShmReader(ent.shm).close()
                        continue  # backing is actually fine (caller raced)
                    except FileNotFoundError:
                        lost.append(oid)
                else:
                    node = self.nodes.get(ent.shm.node)
                    addr = node.data_address if node is not None else None
                    foreign.append((oid, addr, ent.shm))
            for oid in lost:
                ent = self.objects.get(oid)
                if ent is not None and ent.shm is not None:
                    events.emit(
                        "core.object.reap",
                        obj_id=oid,
                        size=ent.size,
                        node=ent.shm.node,
                        reason="backing-lost",
                    )
                    self._release_loc(ent.shm)
                    self._reconstruct(oid, ent)  # failure stores ObjectLostError
            self.cv.notify_all()
        if not foreign:
            return
        # probe owners OUTSIDE the lock (network), then act
        verdicts = []
        for oid, addr, loc in foreign:
            gone = (
                data_plane.stat(addr, self.authkey, loc) is False
                if addr is not None
                else False
            )
            # unreachable (None) or no address: leave it — if the node is
            # actually dead the health loop's remove_node purges its objects
            verdicts.append((oid, gone))
        with self.lock:
            for oid, gone in verdicts:
                if not gone:
                    continue
                ent = self.objects.get(oid)
                if ent is not None and ent.shm is not None:
                    events.emit(
                        "core.object.reap",
                        obj_id=oid,
                        size=ent.size,
                        node=ent.shm.node,
                        reason="owner-dropped",
                    )
                    self._release_loc(ent.shm)
                    self._reconstruct(oid, ent)
            self.cv.notify_all()

    def free_objects(self, obj_ids: list[bytes]):
        with self.lock:
            for oid in obj_ids:
                ent = self.objects.pop(oid, None)
                if ent is not None:
                    self._note_freed(oid, ent, "explicit-free")
                    if ent.shm is not None:
                        self._release_loc(ent.shm)

    # -------------------------------------------------------- task cancel

    def cancel_task(self, task_id: bytes, force: bool):
        with self.lock:
            rec = self.tasks.get(task_id)
            if rec is None:
                return
            self.cancelled.add(task_id)
            if rec["state"] in ("PENDING", "WAITING_DEPS"):
                self.tasks.pop(task_id, None)
                self._finish_cancelled(rec)
            elif rec["state"] in ("RUNNING", "ASSIGNED") and rec.get("worker") is not None:
                wh = rec["worker"]
                if force and wh.proc is not None:
                    wh.proc.terminate()
                else:
                    wh.send(("cancel", task_id))

    # ------------------------------------------------------------- functions

    def put_function(self, func_id: bytes, blob: bytes):
        with self.lock:
            self.functions[func_id] = blob

    def get_function(self, func_id: bytes) -> bytes:
        with self.lock:
            return self.functions[func_id]

    # ------------------------------------------------------- placement groups

    def create_pg(self, bundles: list[dict], strategy: str, name: str = "") -> bytes:
        pg_id = PlacementGroupID.from_random().binary()
        pg = PlacementGroupState(pg_id, bundles, strategy, name)
        with self.lock:
            self.placement_groups[pg_id] = pg
            self._try_place_pg(pg)
        return pg_id

    def _try_place_pg(self, pg: PlacementGroupState):
        """Lock held. Bundle placement (reference
        bundle_scheduling_policy.cc): STRICT_PACK = all bundles on one node;
        PACK = minimize nodes (greedy best-fit); SPREAD = prefer distinct
        nodes; STRICT_SPREAD = require distinct nodes. Placement is
        incremental: bundles still placed on alive nodes (after a partial node
        failure) keep their existing allocation; only unplaced bundles are
        assigned, all-or-nothing."""
        # bundles whose node is gone are unplaced; the rest keep their commit
        todo = [i for i, nid in enumerate(pg.bundle_nodes) if nid is None]
        if not todo:
            if pg.state != PG_CREATED:
                pg.state = PG_CREATED
                pg.ready_event.set()
                self._sched_gen += 1  # pg-strategy tasks may now place
                self.cv.notify_all()
            return
        alive = [self.nodes[nid] for nid in self.node_order if self.nodes[nid].alive]
        if not alive:
            return
        shadow = {n.node_id.binary(): dict(n.resources_avail) for n in alive}
        placed_nodes = {pg.bundle_nodes[i].binary() for i in range(len(pg.bundles)) if pg.bundle_nodes[i] is not None}

        def fits(nid, bundle):
            return all(shadow[nid].get(k, 0.0) + 1e-9 >= v for k, v in bundle.items() if v > 0)

        def take(nid, bundle):
            for k, v in bundle.items():
                shadow[nid][k] = shadow[nid].get(k, 0.0) - v

        assign: dict[int, bytes] = {}
        strategy = pg.strategy
        if strategy == "STRICT_PACK":
            # all bundles must share one node; surviving bundles pin it
            cands = (
                [n for n in alive if n.node_id.binary() in placed_nodes]
                if placed_nodes
                else alive
            )
            for n in cands:
                nid = n.node_id.binary()
                snap = dict(shadow[nid])
                ok = True
                for i in todo:
                    if fits(nid, pg.bundles[i]):
                        take(nid, pg.bundles[i])
                    else:
                        ok = False
                        break
                if ok:
                    assign = {i: nid for i in todo}
                    break
                shadow[nid] = snap
        else:
            used_nodes: set[bytes] = set(placed_nodes)
            order = sorted(todo, key=lambda i: -sum(pg.bundles[i].values()))
            for i in order:
                b = pg.bundles[i]
                cands = [n.node_id.binary() for n in alive if fits(n.node_id.binary(), b)]
                if strategy == "STRICT_SPREAD":
                    cands = [c for c in cands if c not in used_nodes]
                elif strategy == "SPREAD":
                    fresh = [c for c in cands if c not in used_nodes]
                    cands = fresh or cands
                elif strategy == "PACK":
                    packed = [c for c in cands if c in used_nodes]
                    cands = packed or cands
                if not cands:
                    assign = {}
                    break
                nid = cands[0]
                take(nid, b)
                used_nodes.add(nid)
                assign[i] = nid
        if len(assign) != len(todo):
            return  # stays PENDING; retried on node add / resource release
        # commit only the newly placed bundles
        for i in todo:
            node = self.nodes[assign[i]]
            b = pg.bundles[i]
            node.allocate(b)
            node.pg_reserved.setdefault(pg.pg_id, {})[i] = dict(b)
            pg.bundle_nodes[i] = node.node_id
        pg.state = PG_CREATED
        pg.ready_event.set()
        self.cv.notify_all()

    def _retry_pending_pgs(self):
        """Lock held. Re-attempt placement of PENDING groups when capacity
        appears (node added, resources released)."""
        for pg in self.placement_groups.values():
            if pg.state == PG_PENDING:
                self._try_place_pg(pg)

    def remove_pg(self, pg_id: bytes):
        with self.lock:
            pg = self.placement_groups.pop(pg_id, None)
            if pg is None:
                return
            pg.state = PG_REMOVED
            for i, nid in enumerate(pg.bundle_nodes):
                if nid is None:
                    continue
                node = self.nodes.get(nid.binary())
                if node is None:
                    continue
                node.pg_reserved.get(pg_id, {}).pop(i, None)
                if not node.pg_reserved.get(pg_id):
                    node.pg_reserved.pop(pg_id, None)
                node.release(pg.bundles[i])
            self._sched_gen += 1
            self._retry_pending_pgs()
            self._schedule()

    def pg_ready_wait(self, pg_id: bytes, timeout: Optional[float]) -> bool:
        with self.lock:
            pg = self.placement_groups.get(pg_id)
        if pg is None:
            raise ValueError("placement group removed")
        return pg.ready_event.wait(timeout)

    # ------------------------------------------------------------------ rpcs
    # Thin adapters so worker processes hit the same logic over the socket.

    def _normalize_locator(self, locator):
        """Big inline payloads (remote worker puts/results over the socket)
        re-lay into this node's shm so local readers stay zero-copy and the
        head's heap doesn't hold object data. Runs OUTSIDE the head lock —
        it's a full memcpy of the object."""
        kind, payload, is_err = locator
        if kind == "inline" and len(payload) > GLOBAL_CONFIG.max_direct_call_object_size:
            from ray_tpu._private.shm_store import write_shm

            sv = ser.SerializedValue.from_bytes(payload)
            return ("shm", write_shm(sv), is_err)
        return locator

    # ---------------------------------------------------------------- pubsub

    def _conn_lock(self, conn) -> threading.Lock:
        wh = self._conn_worker.get(conn)
        if wh is not None:
            return wh.send_lock
        lock = self._pub_locks.get(id(conn))
        if lock is None:
            lock = self._pub_locks.setdefault(id(conn), threading.Lock())
        return lock

    def _rpc_subscribe(self, conn, channel):
        with self.lock:
            self._subs.setdefault(channel, []).append(("conn", conn))

    def _rpc_unsubscribe(self, conn, channel):
        with self.lock:
            sinks = self._subs.get(channel, [])
            self._subs[channel] = [s for s in sinks if s != ("conn", conn)]

    def subscribe_local(self, channel: str, fn) -> None:
        """In-process subscription (the driver shares this process)."""
        with self.lock:
            self._subs.setdefault(channel, []).append(("fn", fn))

    def unsubscribe_local(self, channel: str, fn) -> None:
        with self.lock:
            sinks = self._subs.get(channel, [])
            self._subs[channel] = [s for s in sinks if s != ("fn", fn)]

    def publish(self, channel: str, payload) -> None:
        """Queue a message for every subscriber of ``channel`` (reference:
        src/ray/pubsub/publisher.h — GCS-push counterpart). Delivery happens
        on a dedicated publisher thread: callers frequently hold the head
        lock, and a blocking send to one slow subscriber must never stall
        the control plane."""
        self._pub_queue.put((channel, payload))

    rpc_publish = publish

    def _publisher_loop(self) -> None:
        while True:
            item = self._pub_queue.get()
            if item is None:
                return
            channel, payload = item
            with self.lock:
                sinks = list(self._subs.get(channel, ()))
            dead = []
            for kind, sink in sinks:
                if kind == "fn":
                    try:
                        sink(channel, payload)
                    except Exception as e:
                        warn_throttled(f"publisher loop: subscriber on {channel}", e)
                    continue
                try:
                    with self._conn_lock(sink):
                        sink.send(("pub", channel, payload))
                except Exception:
                    dead.append((kind, sink))
            if dead:
                with self.lock:
                    self._subs[channel] = [
                        s for s in self._subs.get(channel, []) if s not in dead
                    ]

    # ------------------------------------------------------------- snapshot

    def _snapshot(self) -> None:
        """Persist restartable head state (reference: GCS table storage —
        gcs_table_storage.cc + gcs_init_data.cc reloading every table on
        failover). Scope:

        * KV (carries the job table) + function table,
        * DETACHED actors (create spec + restart budget — their workers
          outlive the head and reconnect; non-detached actors die with
          their driver anyway),
        * placement groups (re-placed as nodes reattach),
        * the object directory for entries whose BYTES survive a head
          crash: spilled files, agent-host objects, and head-host shm
          (/dev/shm persists across a head process crash; only a clean
          shutdown unlinks it) plus the arena name for re-attach.
        """
        path = self._snapshot_path
        if not path:
            return
        import pickle as _pickle

        with self.lock:
            actors = {
                aid: {
                    "create_spec": a.create_spec,
                    "restarts_left": a.restarts_left,
                    "max_task_retries": a.max_task_retries,
                    "num_handles": a.num_handles,
                }
                for aid, a in self.actors.items()
                if a.detached and a.state != ACTOR_DEAD
            }
            pgs = {
                pg_id: {"bundles": pg.bundles, "strategy": pg.strategy, "name": pg.name}
                for pg_id, pg in self.placement_groups.items()
                if pg.state != PG_REMOVED
            }
            objects = {}
            for oid, e in self.objects.items():
                if not e.ready:
                    continue
                rec = {"refcount": e.refcount, "size": e.size, "is_error": e.is_error}
                if e.spill_path is not None:
                    rec["spill_path"] = e.spill_path
                elif e.shm is not None:
                    rec["shm"] = e.shm
                elif e.small is not None and len(e.small) <= 65536:
                    rec["small"] = e.small
                else:
                    continue
                objects[oid] = rec
            blob = _pickle.dumps(
                {
                    "version": 2,
                    "kv": dict(self.kv),
                    "functions": dict(self.functions),
                    "actors": actors,
                    "placement_groups": pgs,
                    "objects": objects,
                    "arena_name": self.arena_name,
                }
            )
        tmp = f"{path}.tmp.{os.getpid()}"
        try:
            with open(tmp, "wb") as f:
                f.write(blob)
            os.replace(tmp, path)
        except OSError:
            try:
                os.unlink(tmp)
            except OSError:
                pass

    def _load_snapshot(self) -> None:
        path = self._snapshot_path
        if not path or not os.path.exists(path):
            return
        import pickle as _pickle

        try:
            with open(path, "rb") as f:
                data = _pickle.loads(f.read())
        except Exception:
            return  # a torn snapshot must not block cluster start
        try:
            self.kv.update(data.get("kv", {}))
            self.functions.update(data.get("functions", {}))
            # detached actors come back RESTARTING: a surviving worker
            # reconnects and rebinds (state preserved); otherwise the next
            # node registration triggers a fresh create (state lost, like a
            # reference actor restart)
            for aid, rec in data.get("actors", {}).items():
                actor = ActorState(aid, rec["create_spec"])
                actor.restarts_left = rec.get("restarts_left", 0)
                actor.max_task_retries = rec.get("max_task_retries", 0)
                actor.num_handles = rec.get("num_handles", 1)
                actor.state = ACTOR_RESTARTING
                self.actors[aid] = actor
                if actor.named_key:
                    self.named_actors[actor.named_key] = aid
                self._restored_actors.add(aid)
            for pg_id, rec in data.get("placement_groups", {}).items():
                pg = PlacementGroupState(
                    pg_id, rec["bundles"], rec["strategy"], rec["name"]
                )
                pg.bundle_nodes = [None] * len(rec["bundles"])
                self.placement_groups[pg_id] = pg
            from ray_tpu._private.shm_store import ShmReader as _ShmReader

            for oid, rec in data.get("objects", {}).items():
                ent = ObjectEntry()
                ent.refcount = max(rec.get("refcount", 0), 1)
                ent.size = rec.get("size", 0)
                ent.is_error = rec.get("is_error", False)
                ent.spill_path = rec.get("spill_path")
                ent.shm = rec.get("shm")
                ent.small = rec.get("small")
                if ent.spill_path or ent.shm is not None or ent.small is not None:
                    self.objects[oid] = ent
                    if ent.shm is not None:
                        # node table is empty at restore time, so locality
                        # can't be judged from loc.node — probe instead:
                        # only segments attachable on THIS host count
                        # toward its spill accounting
                        try:
                            _ShmReader(ent.shm).close()
                            self.shm_owner.register(ent.shm)
                        except FileNotFoundError:
                            pass  # foreign host's bytes (or gone)
            prev_arena = data.get("arena_name")
            if prev_arena and self.arena_name is None:
                from ray_tpu._private import shm_store as _shm

                if _shm.attach_arena(prev_arena) is not None:
                    self.arena_name = prev_arena
                    _shm.set_write_arena(prev_arena)
        except Exception:
            import traceback as _tb

            _tb.print_exc()  # partial restore is better than none

    def rpc_put(self, obj_id, small, shm, is_error=False, take_ref=False, replay=False):
        """Store a put. Returns True when the delivery was APPLIED (stored,
        or its failure stored as an error on the id) and False when a
        replay-flagged redelivery was ignored as a duplicate — callers use
        that to track side effects (session refs) exactly once."""
        try:
            if replay:
                # redelivery after a client reconnect: the original window
                # may have been processed before the conn dropped (only the
                # ack was lost). Put ids are minted once per op, so a value
                # already on the id means THIS put landed — applying again
                # would double-count take_ref.
                with self.lock:
                    ent0 = self.objects.get(obj_id)
                    if ent0 is not None and (
                        ent0.small is not None or ent0.shm is not None or ent0.spill_path
                    ):
                        return False
            locator = ("inline", small, is_error) if small is not None else ("shm", shm, is_error)
            locator = self._normalize_locator(locator)  # big memcpy outside lock
            with self.lock:
                # a FIRST-time put id can have no waiters or queued deps: the
                # head reads each conn in order, so no other party can have
                # learned the id before the put itself landed — skip the
                # notify_all, which otherwise wakes every parked get once
                # per put in a burst (1-core ping-pong). Re-puts (lineage
                # restore, retry) keep the wakeup.
                fresh = obj_id not in self.objects
                self._store_locator(obj_id, locator, notify=not fresh)
                if take_ref:
                    # the caller's ObjectRef refcount, folded into the put
                    # itself: one head round trip per ray.put, not two
                    self.objects[obj_id].refcount += 1
            return True
        except Exception as e:  # noqa: BLE001
            # never raise: async (fire-and-forget) putters have no reply to
            # carry the error, and a raise would strand their get() in the
            # not-yet-arrived wait — the failure lands ON the object id
            with self.lock:
                self._store_error(obj_id, e)
                if take_ref:
                    self.objects[obj_id].refcount += 1
            return True

    def rpc_get(self, obj_ids, timeout=None):
        return self.get_locators(obj_ids, timeout)

    def rpc_wait(self, obj_ids, num_returns, timeout=None):
        return self.wait_objects(obj_ids, num_returns, timeout)

    def rpc_submit_task(self, spec):
        self.submit_task(spec)
        return True

    def rpc_create_actor(self, spec):
        self.create_actor(spec)
        return True

    def rpc_submit_actor_task(self, spec):
        self.submit_actor_task(spec)
        return True

    def rpc_kill_actor(self, actor_id, no_restart=True):
        self.kill_actor(actor_id, no_restart)
        return True

    def rpc_cancel_task(self, task_id, force=False):
        self.cancel_task(task_id, force)
        return True

    def rpc_put_function(self, func_id, blob):
        self.put_function(func_id, blob)
        return True

    def rpc_get_function(self, func_id):
        return self.get_function(func_id)

    def rpc_get_actor_named(self, name, timeout=0.0, namespace=None):
        """Namespace-scoped lookup. Falls back to the "default" namespace
        ONLY for detached actors: detached = cluster-scoped services (serve
        controller, job supervisors, collective stores) that every client
        session must find, while regular named actors stay invisible across
        session namespaces (reference: namespaces + detached lifetimes)."""
        ns = namespace or "default"
        deadline = time.monotonic() + (timeout or 0.0)
        with self.lock:
            while True:
                aid = self.named_actors.get(f"{ns}:{name}")
                if aid is None and ns != "default":
                    cand = self.named_actors.get(f"default:{name}")
                    if cand is not None and self.actors[cand].detached:
                        aid = cand
                if aid is not None:
                    return aid, self.actors[aid].create_spec.get("methods", {})
                if time.monotonic() >= deadline:
                    raise ValueError(
                        f"Failed to look up actor with name '{name}'"
                    )
                self.cv.wait(timeout=0.1)

    def rpc_actor_state(self, actor_id):
        with self.lock:
            a = self.actors.get(actor_id)
            return None if a is None else a.state

    def rpc_actor_inc_handle(self, actor_id):
        with self.lock:
            a = self.actors.get(actor_id)
            if a is not None:
                a.num_handles += 1
        return True

    def rpc_actor_dec_handle(self, actor_id):
        self.remove_actor_handle(actor_id)
        return True

    def rpc_mutex_acquire(self, name, owner, timeout=None, lease_s=300.0):
        """Cluster-wide named mutex with a LEASE (reference capability:
        workflow storage coordination; here the primitive virtual actors
        serialize their read-modify-write transactions on, replacing the
        fcntl file lock that silently degrades on NFS/cloud storage).
        A crashed holder's lease expires instead of wedging the name
        forever; re-acquiring with the same owner token renews."""
        deadline = None if timeout is None else time.monotonic() + timeout
        with self.lock:
            while True:
                now = time.monotonic()
                cur = self._named_mutexes.get(name)
                if cur is None or cur[1] <= now or cur[0] == owner:
                    self._named_mutexes[name] = (owner, now + float(lease_s))
                    return True
                if deadline is not None and now >= deadline:
                    return False
                # wait until the holder's lease would expire (release
                # notifies sooner) — a fixed poll would wake every waiter
                # 20x/s on the head's global lock for nothing
                bound = cur[1] - now
                if deadline is not None:
                    bound = min(bound, deadline - now)
                self.cv.wait(timeout=max(bound, 0.01))

    def rpc_mutex_release(self, name, owner):
        with self.lock:
            cur = self._named_mutexes.get(name)
            if cur is not None and cur[0] == owner:
                del self._named_mutexes[name]
                self.cv.notify_all()
                return True
            return False

    # -- metric time series + SLO alerts (observability plane) -------------

    def _series_store(self):
        """Lazy SeriesStore: bounded per-process metric history, fed by
        every process's metrics flusher (``series_push``) alongside the KV
        snapshot mailbox. Guarded by its own lock — the hot scheduling path
        must never contend with observability pushes."""
        store = self._metric_series
        if store is None:
            from ray_tpu.util.metrics import SeriesStore

            with self.lock:
                if self._metric_series is None:
                    self._metric_series = SeriesStore()
                store = self._metric_series
        return store

    def _alert_manager(self):
        mgr = self._alerts
        if mgr is None:
            from ray_tpu._private.alerts import AlertManager

            with self.lock:
                if self._alerts is None:
                    self._alerts = AlertManager()
                mgr = self._alerts
        return mgr

    def rpc_series_push(self, proc, interval, series):
        self._series_store().push(proc, interval, series)
        return True

    def rpc_series_get(self, name=None):
        """Raw per-process series (the drain format);
        ``util.metrics.collect_series`` merges client-side with the same
        function the head's own alert evaluator uses."""
        return self._series_store().raw(name)

    def rpc_alerts(self, eval_now=False):
        """The SLO rule engine's current state. ``eval_now`` forces one
        evaluation pass against the freshly merged series (obs alerts
        --eval-once; tests) instead of waiting for the evaluator tick."""
        mgr = self._alert_manager()
        if eval_now:
            mgr.evaluate(self._series_store().merged())
        return mgr.state()

    def _alerts_loop(self):
        import os as _os

        try:
            interval = max(
                1.0, float(_os.environ.get("RAY_TPU_ALERTS_INTERVAL_S", "15"))
            )
        except ValueError:
            interval = 15.0
        while not self._shutdown:
            time.sleep(interval)
            try:
                self._alert_manager().evaluate(self._series_store().merged())
            except Exception as e:
                # the evaluator must never die with the cluster still up —
                # a broken rule would otherwise silently end all alerting
                warn_throttled("head alert evaluator", e)

    def rpc_kv_put(self, key, value):
        with self.lock:
            self.kv[key] = value
        return True

    def rpc_kv_get(self, key):
        with self.lock:
            return self.kv.get(key)

    def rpc_kv_del(self, key):
        with self.lock:
            return self.kv.pop(key, None) is not None

    def rpc_kv_keys(self, prefix=""):
        with self.lock:
            return [k for k in self.kv if k.startswith(prefix)]

    def rpc_create_pg(self, bundles, strategy, name=""):
        return self.create_pg(bundles, strategy, name)

    def rpc_remove_pg(self, pg_id):
        self.remove_pg(pg_id)
        return True

    def rpc_pg_ready(self, pg_id, timeout=None):
        return self.pg_ready_wait(pg_id, timeout)

    def rpc_add_ref(self, obj_id):
        self.add_ref(obj_id)
        return True

    def rpc_free_ref(self, obj_id):
        self.remove_ref(obj_id)
        return True

    def rpc_free_refs(self, obj_ids):
        self.remove_refs(obj_ids)
        return True

    def rpc_tcp_address(self):
        return self.tcp_address

    def rpc_auth_info(self):
        """Authkey (hex) for attach-back flows (job entrypoints). Callers of
        this RPC already authenticated with the same key — no escalation."""
        return self.authkey.hex()

    def rpc_borrow_begin(self, obj_id, nonce):
        """A ref is being serialized: hold one count for the transit window,
        tagged so the deserializer can claim (not double-count) it
        (reference: borrower bookkeeping, ``reference_count.h:61-115``)."""
        with self.lock:
            ent = self.objects.get(obj_id)
            if ent is None:
                ent = self.objects[obj_id] = ObjectEntry()
            ent.refcount += 1
            if ent.borrow_nonces is None:
                ent.borrow_nonces = set()
            ent.borrow_nonces.add(nonce)
        return True

    def rpc_borrow_claim(self, obj_id, nonce):
        """A deserialized ref came alive. First claim of a nonce inherits
        the transit count; later claims of the same nonce (the same pickle
        deserialized again, e.g. a retried task's args) each add their own
        count. Every claimed holder releases via free_ref on GC."""
        with self.lock:
            ent = self.objects.get(obj_id)
            if ent is None:
                ent = self.objects[obj_id] = ObjectEntry()
            if ent.borrow_nonces and nonce in ent.borrow_nonces:
                ent.borrow_nonces.discard(nonce)  # transit count transfers
            else:
                ent.refcount += 1
        return True

    def rpc_free(self, obj_ids):
        self.free_objects(obj_ids)
        return True

    def rpc_cluster_resources(self):
        with self.lock:
            out: dict[str, float] = {}
            for n in self.nodes.values():
                if n.alive:
                    for k, v in n.resources_total.items():
                        out[k] = out.get(k, 0.0) + v
            return out

    def rpc_available_resources(self):
        with self.lock:
            out = {}
            for n in self.nodes.values():
                if n.alive:
                    for k, v in n.resources_avail.items():
                        out[k] = out.get(k, 0.0) + v
            return out

    def rpc_nodes(self):
        with self.lock:
            return [
                {
                    "NodeID": n.node_id.hex(),
                    "Alive": n.alive,
                    "Resources": dict(n.resources_total),
                    "Available": dict(n.resources_avail),
                    "Labels": dict(n.labels),
                }
                for n in self.nodes.values()
            ]

    def rpc_list_tasks(self):
        with self.lock:
            return [
                {"task_id": ObjectID(r["task_id"]).hex() if len(r["task_id"]) == 16 else r["task_id"].hex(), "name": r["spec"].get("name"), "state": r["state"]}
                for r in self.tasks.values()
            ]

    def rpc_list_actors(self):
        with self.lock:
            names = {0: "PENDING", 1: "RESTARTING", 2: "ALIVE", 3: "DEAD"}
            return [
                {
                    "actor_id": ActorID(a.actor_id).hex(),
                    "state": names[a.state],
                    "name": a.name,
                    "class_name": a.create_spec.get("class_name"),
                    "node_id": a.node_id.hex() if a.node_id else None,
                }
                for a in self.actors.values()
            ]

    def rpc_list_objects(self):
        def where(e):
            if e.small is not None:
                return "inline"
            if e.shm is not None:
                return "shm"
            if e.spill_path is not None:
                return "spilled"
            return "pending"

        with self.lock:
            return [
                {
                    "object_id": ObjectID(oid).hex(),
                    "size": e.size,
                    "ready": e.ready,
                    "where": where(e),
                    "refcount": e.refcount,
                    "pins": e.pins,
                }
                for oid, e in self.objects.items()
            ]

    def rpc_node_stats(self):
        """Per-node /proc stats (reporter.node_stats samples — the head's
        health loop covers its host; agents push theirs)."""
        with self.lock:
            return {
                n.node_id.hex(): dict(n.stats) for n in self.nodes.values() if n.alive
            }

    def rpc_worker_stacks(self, timeout: float = 5.0):
        """All-thread stack dumps of every worker in the cluster (SIGUSR1 →
        faulthandler; reference: the dashboard's py-spy stack dumps). Works
        on wedged workers — the handler is C-level and needs no GIL."""
        import uuid as _uuid

        from ray_tpu._private.reporter import dump_pids

        deadline = time.monotonic() + timeout
        local_pids: list[int] = []
        agents = []
        with self.lock:
            for node in self.nodes.values():
                if not node.alive:
                    continue
                if node.agent is not None:
                    agents.append((node.node_id.hex(), node.agent))
                else:
                    local_pids.extend(
                        wh.proc.pid
                        for wh in node.all_workers
                        # registered only: pre-registration processes may not
                        # have armed the handler yet (dump_pids also refuses
                        # to signal unarmed pids as a second guard)
                        if wh.proc is not None and wh.proc.is_alive() and wh.conn is not None
                    )
        out: dict[str, dict] = {}
        req_ids = {}
        for node_hex, agent in agents:
            rid = _uuid.uuid4().hex
            if agent.send(("dump_workers", {"req_id": rid})):
                req_ids[rid] = node_hex
            else:
                out[node_hex] = {"error": "agent unreachable"}
        local = dump_pids(
            sorted(set(local_pids)),
            timeout=max(min(3.0, deadline - time.monotonic()), 0.1),
        )
        out["local"] = {str(pid): text for pid, text in local.items()}
        with self._stacks_cv:
            while req_ids and time.monotonic() < deadline:
                done = [r for r in req_ids if r in self._stacks_replies]
                for rid in done:
                    node_hex = req_ids.pop(rid)
                    out[node_hex] = {
                        str(p): t for p, t in self._stacks_replies.pop(rid).items()
                    }
                if req_ids:
                    self._stacks_cv.wait(timeout=0.2)
        for rid, node_hex in req_ids.items():
            out[node_hex] = {"error": "no reply within timeout"}
        return out

    def _broadcast_rendezvous(self, msg_kind: str, payload: dict,
                              deadline: float) -> dict:
        """Fan ``(msg_kind, payload + req_id)`` out to every live
        registered worker and gather the replies posted to the stacks
        mailbox until ``deadline``.  One req_id per NODE (its workers
        merge into one mailbox entry), which keeps the 64-entry mailbox
        bound a per-node bound, not per-worker.  Returns ``{node_hex:
        {pid: reply}}``; nodes with missing workers additionally carry an
        ``_errors`` list (a distinct key shape from pids, so callers
        iterating pids never trip on it) — partial coverage is reported,
        never silently assumed total.  Shared by ``rpc_worker_profile``
        and ``rpc_collect_events``."""
        import uuid as _uuid

        req_ids: dict[str, tuple[str, int]] = {}  # rid -> (node_hex, expected)
        with self.lock:
            for node in self.nodes.values():
                if not node.alive:
                    continue
                whs = [wh for wh in node.all_workers if wh.conn is not None]
                if not whs:
                    continue
                rid = _uuid.uuid4().hex
                for wh in whs:
                    self._enqueue_send(wh, (msg_kind, dict(payload, req_id=rid)))
                req_ids[rid] = (node.node_id.hex(), len(whs))
        self.flush_outbox()
        out: dict[str, dict] = {}

        def _take(rid: str, node_hex: str, expected: int) -> None:
            got = self._stacks_replies.pop(rid, None) or {}
            dest = out.setdefault(node_hex, {})
            dest.update({str(p): v for p, v in got.items()})
            if len(got) < expected:
                dest["_errors"] = [
                    f"{expected - len(got)} worker(s) did not reply within timeout"
                ]

        with self._stacks_cv:
            while req_ids and time.monotonic() < deadline:
                for rid in list(req_ids):
                    node_hex, expected = req_ids[rid]
                    if len(self._stacks_replies.get(rid) or {}) >= expected:
                        _take(rid, node_hex, expected)
                        req_ids.pop(rid)
                if req_ids:
                    self._stacks_cv.wait(timeout=0.2)
            for rid, (node_hex, expected) in req_ids.items():
                _take(rid, node_hex, expected)  # deadline: keep partials
        return out

    def rpc_worker_profile(self, duration_s: float = 2.0, interval_ms: float = 10.0,
                           timeout: float = 0.0):
        """Sampling CPU profile of every live worker (reference: the
        dashboard's py-spy ``cpu_profile`` endpoint). Each worker samples
        itself (``reporter.sample_profile``) and posts collapsed stacks
        back; returns ``{node_hex: {pid: collapsed_text}}`` — feed a value
        straight to flamegraph.pl or speedscope."""
        duration_s = min(max(float(duration_s), 0.05), 60.0)  # bound GIL cost
        timeout = timeout or duration_s + 5.0
        req = {"duration_s": duration_s, "interval_s": interval_ms / 1000.0}
        return self._broadcast_rendezvous(
            "profile", req, time.monotonic() + timeout
        )

    def rpc_collect_events(self, timeout: float = 5.0):
        """Drain every live worker's flight-recorder ring (plus this
        process's own) — ``{node_hex: {pid: [event, ...]}}``. Same
        broadcast/mailbox rendezvous as ``rpc_worker_profile``; workers
        that miss the deadline are reported under ``_errors`` so callers
        see partial coverage instead of assuming it was total."""
        from ray_tpu._private import events as _ev

        timeout = min(max(float(timeout), 0.2), 30.0)
        out = self._broadcast_rendezvous(
            "events_drain", {}, time.monotonic() + timeout
        )
        # the head process's own ring (the in-process driver's, usually)
        out.setdefault("head", {})[str(os.getpid())] = _ev.snapshot()
        return out

    # ------------------------------------------------- object-plane ledger

    @staticmethod
    def _object_state(ent: ObjectEntry) -> str:
        """A directory entry's position in the object state machine
        (inline → arena/segment → spilled; ``poisoned`` lives client-side
        and is folded into the ledger from worker reports)."""
        if ent.shm is not None:
            return "arena" if ent.shm.offset is not None else "segment"
        if ent.spill_path is not None:
            return "spilled"
        if ent.small is not None:
            return "inline"
        return "pending"

    def _node_object_stats(self) -> dict:
        """Lock held. This host's object-plane residency: arena occupancy
        (owner-registry bytes when no native arena), this process's live
        pins, and directory bytes spilled to this host's disk."""
        from ray_tpu._private import shm_store as _shm

        spill = sum(
            ent.size for ent in self.objects.values()
            if ent.spill_path is not None
        )
        arena = _shm.attach_arena(self.arena_name) if self.arena_name else None
        pins = _shm.pin_stats()
        return {
            "arena": self.arena_name,
            "used": (
                arena.used if arena is not None else self.shm_owner.bytes_used
            ),
            "capacity": (
                arena.capacity if arena is not None else self._spill_threshold()
            ),
            "n_objects": (
                arena.n_objects if arena is not None
                else len(self.shm_owner.snapshot())
            ),
            "pinned_bytes": pins["pinned_bytes"],
            "pins": pins["count"],
            "oldest_pin_age_s": pins["oldest_age_s"],
            "spill_bytes": spill,
            "owner_bytes": self.shm_owner.bytes_used,
        }

    def _publish_object_gauges(self, node_stats: Optional[dict] = None) -> None:
        """Publish the per-node residency gauges. ``node_stats`` maps a
        node tag to a ``_node_object_stats``-shaped dict (agent nodes,
        from a ledger/audit rendezvous); None = just this host, the
        health-loop tick. The untagged occupancy gauge carries the WORST
        node's used/capacity ratio so the arena-pressure SLO rule watches
        cluster-wide pressure in one series."""
        m = _object_metrics()
        stats = dict(node_stats or {})
        with self.lock:
            stats["head"] = self._node_object_stats()
        worst = 0.0
        for tag, s in stats.items():
            used = s.get("used") or 0
            cap = s.get("capacity") or 0
            m["arena_used"].set(used, tags={"node": tag})
            m["arena_capacity"].set(cap, tags={"node": tag})
            m["arena_pinned"].set(s.get("pinned_bytes") or 0, tags={"node": tag})
            m["spill_bytes"].set(s.get("spill_bytes") or 0, tags={"node": tag})
            if cap:
                worst = max(worst, used / cap)
        m["arena_occupancy"].set(worst)

    def _gather_object_reports(self, timeout: float) -> dict:
        """Cluster object-plane residency — ``{node_hex: {pid: report}}``:
        every live worker's arena pins / locally-poisoned ids / arena
        occupancy (``object_report`` rendezvous, same broadcast/mailbox as
        stacks and events), plus this process's own report."""
        from ray_tpu._private import runtime as _rt
        from ray_tpu._private import shm_store as _shm

        out: dict = {}
        if timeout > 0:
            out = self._broadcast_rendezvous(
                "object_report", {}, time.monotonic() + timeout
            )
        report = _shm.pin_stats()
        ctx = _rt._ctx  # the in-process driver, when this head is local
        report["poisoned"] = [
            oid.hex() for oid in list(getattr(ctx, "_poisoned", None) or {})
        ]
        arena = _shm.attach_arena(self.arena_name) if self.arena_name else None
        if arena is not None:
            report["arena"] = {
                "name": arena.name,
                "used": arena.used,
                "capacity": arena.capacity,
                "n_objects": arena.n_objects,
            }
        out.setdefault("head", {})[str(os.getpid())] = report
        return out

    @staticmethod
    def _fold_node_reports(reports: dict) -> tuple[dict, list]:
        """Fold per-pid object reports into per-node residency stats and
        the cluster poisoned-ref list. Simulated local nodes share the
        head host's arena, so their entries mirror its occupancy."""
        node_stats: dict[str, dict] = {}
        poisoned: list[dict] = []
        for node_hex, pids in reports.items():
            agg = {
                "pinned_bytes": 0, "pins": 0,
                "oldest_pin_age_s": 0.0, "spill_bytes": 0,
            }
            for pid, rep in pids.items():
                if pid == "_errors" or not isinstance(rep, dict):
                    continue
                agg["pinned_bytes"] += rep.get("pinned_bytes") or 0
                agg["pins"] += rep.get("count") or 0
                agg["oldest_pin_age_s"] = max(
                    agg["oldest_pin_age_s"], rep.get("oldest_age_s") or 0.0
                )
                for oh in rep.get("poisoned", ()):
                    poisoned.append(
                        {"object_id": oh, "state": "poisoned",
                         "node": node_hex, "pid": pid}
                    )
                ar = rep.get("arena")
                if ar:
                    agg["arena"] = ar.get("name")
                    agg["used"] = ar.get("used")
                    agg["capacity"] = ar.get("capacity")
                    agg["n_objects"] = ar.get("n_objects")
            node_stats[node_hex] = agg
        return node_stats, poisoned

    def rpc_object_ledger(self, top_n: int = 20, node: Optional[str] = None,
                          state: Optional[str] = None, timeout: float = 2.0):
        """The object ledger (ISSUE 19): every directory entry's state,
        owner node, size, ref/pin counts, and age; client-side poisoned
        refs folded in from the ``object_report`` rendezvous; the freed
        forensics tail; and per-node arena/spill residency. ``top_n``
        bounds the object rows (largest first; 0 = all) AFTER the
        ``node``/``state`` filters. Also refreshes the per-node residency
        gauges with whatever the rendezvous gathered."""
        reports = self._gather_object_reports(timeout)
        folded, poisoned = self._fold_node_reports(reports)
        now = time.time()
        with self.lock:
            rows = []
            by_state: dict[str, int] = {}
            total_bytes = 0
            for oid, ent in self.objects.items():
                st = self._object_state(ent)
                by_state[st] = by_state.get(st, 0) + 1
                total_bytes += ent.size
                owner = (
                    ent.shm.node.hex()
                    if ent.shm is not None and ent.shm.node is not None
                    else "head"
                )
                if node is not None and owner != node:
                    continue
                if state is not None and st != state:
                    continue
                rows.append({
                    "object_id": ObjectID(oid).hex(),
                    "state": st,
                    "node": owner,
                    "size": ent.size,
                    "refcount": ent.refcount,
                    "pins": ent.pins,
                    "age_s": now - ent.created,
                    "seg": ent.shm.name if ent.shm is not None else None,
                    "spill_path": ent.spill_path,
                    "is_error": ent.is_error,
                })
            freed = [
                {"object_id": o, "size": s, "age_s": a,
                 "freed_at": t, "reason": r}
                for o, s, a, t, r in list(self._freed_ring)
            ]
            node_stats = {"head": self._node_object_stats()}
        for tag, s in folded.items():
            if tag == "head":
                # the directory-side head stats are authoritative; keep
                # only the worker-pin fold the head process can't see
                node_stats["head"]["worker_pinned_bytes"] = s["pinned_bytes"]
                continue
            node_stats[tag] = s
        rows.sort(key=lambda r: r["size"], reverse=True)
        if top_n:
            rows = rows[: int(top_n)]
        try:
            self._publish_object_gauges(
                {t: s for t, s in node_stats.items()
                 if t != "head" and s.get("capacity")}
            )
        except Exception as e:  # gauges must never fail the ledger read
            warn_throttled("object ledger: gauge refresh", e)
        return {
            "objects": rows,
            "poisoned": poisoned,
            "freed": freed,
            "summary": {
                "objects": sum(by_state.values()),
                "bytes": total_bytes,
                "by_state": by_state,
                "poisoned": len(poisoned),
            },
            "nodes": node_stats,
        }

    def rpc_object_audit(self, timeout: float = 2.0,
                         pin_lease_s: Optional[float] = None):
        """Cluster-wide leak audit (ISSUE 19; the core-plane analogue of
        ``KVBlockPool.audit()``). Invariants checked, each violation a
        finding with node/object provenance:

        * every owner-registered allocation (arena block or dedicated
          segment) is owned by a live directory locator — orphaned bytes
          are what a producer SIGKILLed after its put landed leaves;
        * every live LOCAL locator's backing is still owner-registered
          (dangling locator: a free raced a hand-out);
        * every spill file belongs to a spilled entry, and every spilled
          entry's file exists;
        * every arena pin (cluster-wide, from the rendezvous reports) is
          younger than the read lease ``pin_lease_s`` (default env
          ``RAY_TPU_PIN_LEASE_S``, 300s) — pinned-forever readers block
          block reuse.

        Publishes the verdict as the ``core_object_leaks`` gauge."""
        if pin_lease_s is None:
            try:
                pin_lease_s = float(os.environ.get("RAY_TPU_PIN_LEASE_S", "300"))
            except ValueError:
                pin_lease_s = 300.0
        reports = self._gather_object_reports(timeout)
        findings: list[dict] = []
        with self.lock:
            owned = self.shm_owner.snapshot()
            live: dict[tuple, str] = {}
            spill_by_path: dict[str, str] = {}
            for oid, ent in self.objects.items():
                if ent.shm is not None and self._loc_is_local(ent.shm):
                    live[(ent.shm.name, ent.shm.offset)] = ObjectID(oid).hex()
                if ent.spill_path is not None:
                    spill_by_path[ent.spill_path] = ObjectID(oid).hex()
            for key, (size, _gen) in owned.items():
                if key not in live:
                    findings.append({
                        "kind": "orphaned-bytes", "node": "head",
                        "seg": key[0], "offset": key[1], "size": size,
                    })
            for key, oid_hex in live.items():
                if key not in owned:
                    findings.append({
                        "kind": "dangling-locator", "node": "head",
                        "object_id": oid_hex,
                        "seg": key[0], "offset": key[1],
                    })
            spill_dir = os.path.join(
                os.path.dirname(self.socket_path), "spill"
            )
            try:
                names = os.listdir(spill_dir)
            except OSError:
                names = []
            for fn in names:
                path = os.path.join(spill_dir, fn)
                if path not in spill_by_path:
                    try:
                        size = os.path.getsize(path)
                    except OSError:
                        size = 0
                    findings.append({
                        "kind": "orphaned-spill-file", "node": "head",
                        "path": path, "size": size,
                    })
            for path, oid_hex in spill_by_path.items():
                if not os.path.exists(path):
                    findings.append({
                        "kind": "missing-spill-file", "node": "head",
                        "object_id": oid_hex, "path": path,
                    })
            checked = {
                "objects": len(self.objects),
                "owned_allocations": len(owned),
                "spill_files": len(names),
            }
        pins_checked = 0
        for node_hex, pids in reports.items():
            for pid, rep in pids.items():
                if pid == "_errors" or not isinstance(rep, dict):
                    continue
                for p in rep.get("pins", ()):
                    pins_checked += 1
                    if (p.get("age_s") or 0) > pin_lease_s:
                        findings.append({
                            "kind": "stale-pin", "node": node_hex,
                            "pid": pid, "seg": p.get("seg"),
                            "offset": p.get("offset"),
                            "size": p.get("size"), "age_s": p.get("age_s"),
                        })
        checked["pins"] = pins_checked
        _object_metrics()["leaks"].set(len(findings))
        return {
            "findings": findings,
            "checked": checked,
            "pin_lease_s": pin_lease_s,
        }

    def rpc_inject_orphan_for_tests(self, size: int = 4096) -> dict:
        """TEST-ONLY leak injection (ISSUE 19 acceptance): lay real bytes
        out in this host's store and register them with the owner ledger
        WITHOUT a directory entry — what a producer SIGKILLed between its
        put landing and any ref existing leaves behind. Returns the
        provenance ``rpc_object_audit`` must then report."""
        from ray_tpu._private.shm_store import write_shm

        sv = ser.serialize(b"\x00" * max(1, int(size)))
        loc = write_shm(sv)
        with self.lock:
            self.shm_owner.register(loc)
        return {"seg": loc.name, "offset": loc.offset,
                "size": loc.total_size, "node": "head"}

    def rpc_waterfall(self, recent: int = 0):
        """Task-hop waterfall summary (``obs waterfall`` / the ``obs top``
        row): per-phase percentile summaries folded from sampled tasks'
        stamp lists, plus optionally the newest raw records (the chrome
        trace nests them as slices)."""
        return _waterfall.summary(recent=int(recent))

    def rpc_task_events(self):
        with self.lock:
            # rid None = a rootless submission (specs no longer ship a
            # per-task minted context — PR-11 zero-cost tracing): derive
            # the task-rooted id LAZILY here, matching what the worker's
            # LazyTaskContext materializes, so the state-API contract
            # (every task row carries a request_id) is unchanged
            return [
                {"task_id": tid.hex(), "name": name, "state": state,
                 "time": t, "kind": kind,
                 "request_id": rid if rid is not None else tid.hex()[:16]}
                for tid, name, state, t, kind, rid in self.task_events
            ]

    def rpc_autoscaler_demand(self):
        """Autoscaler feed: unplaceable resource demand + per-node load.

        Reference: the GCS load report consumed by
        ``autoscaler/_private/autoscaler.py:373`` (resource_demand_scheduler
        bin-packs pending shapes against node types).
        """
        with self.lock:
            demand = []
            demand_labels = []

            def _labels_of(spec):
                st = spec.get("strategy")
                return dict(st[1]) if st and st[0] == "labels" else {}

            for rec in self.pending_sched:
                demand.append(dict(rec["spec"].get("resources") or {}))
                demand_labels.append(_labels_of(rec["spec"]))
            # actor creations waiting for resources count too
            for a in self.actors.values():
                if a.state == ACTOR_PENDING and a.worker is None:
                    demand.append(dict(a.create_spec.get("resources") or {}))
                    demand_labels.append(_labels_of(a.create_spec))
            nodes = []
            now = time.monotonic()
            for n in self.nodes.values():
                busy = bool(n.assigned) or any(
                    w.current_task is not None or w.actor_id is not None
                    for w in n.all_workers
                )
                idle_s = 0.0
                if not busy:
                    # a node with no workers yet is "idle since registration",
                    # never infinitely idle (workers spawn lazily on first
                    # task — inf would get fresh nodes reaped instantly)
                    last = max(
                        (w.idle_since for w in n.all_workers), default=n.created_at
                    )
                    idle_s = now - last
                nodes.append(
                    {
                        "node_id": n.node_id.hex(),
                        "alive": n.alive,
                        "resources_total": dict(n.resources_total),
                        "resources_available": dict(n.resources_avail),
                        "busy": busy,
                        "idle_s": idle_s,
                        "labels": dict(n.labels),
                    }
                )
            return {
                "pending_demand": demand,
                "pending_demand_labels": demand_labels,
                "nodes": nodes,
            }

    def rpc_list_placement_groups(self):
        with self.lock:
            names = {0: "PENDING", 1: "CREATED", 2: "REMOVED"}
            return [
                {
                    "placement_group_id": pg.pg_id.hex(),
                    "name": pg.name,
                    "strategy": pg.strategy,
                    "state": names.get(pg.state, str(pg.state)),
                    "bundles": list(pg.bundles),
                    "bundle_nodes": [
                        n.hex() if n is not None else None for n in pg.bundle_nodes
                    ],
                }
                for pg in self.placement_groups.values()
            ]

    # -------------------------------------------------------------- shutdown

    def shutdown(self):
        with self.lock:
            self._shutdown = True
            workers = [w for n in self.nodes.values() for w in n.all_workers]
            # route frees of agent-host objects while agent conns are still
            # up — their dedicated segments would otherwise outlive the
            # cluster (arenas die with their agents; segments don't)
            for ent in self.objects.values():
                if ent.shm is not None and not self._loc_is_local(ent.shm):
                    self._release_loc(ent.shm)
            self.cv.notify_all()
        for wh in workers:
            wh.alive = False
            try:
                wh.send(("exit",))
            except Exception:  # raylint: disable=RL007
                pass  # best-effort teardown: the worker may already be gone
        for node in self.nodes.values():
            if node.template is not None:
                node.template.shutdown()
                node.template = None
        deadline = time.monotonic() + 2.0
        for wh in workers:
            if wh.proc is not None:
                wh.proc.join(timeout=max(0.0, deadline - time.monotonic()))
                if wh.proc.is_alive():
                    wh.proc.terminate()
        _close_listener(self._listener)
        if self._tcp_listener is not None:
            _close_listener(self._tcp_listener)
        if self.data_server is not None:
            self.data_server.shutdown()
        self._pub_queue.put(None)
        self._spawn_q.put(None)
        self._blocking_pool.shutdown()
        try:
            os.write(self._io_wake_w, b"x")  # unblock the IO selector
        except OSError:
            pass
        self._io_resume.set()
        self._flush_event.set()  # backstop exits now, not at its next poll
        self._snapshot()
        self.shm_owner.shutdown()
        if self.arena_name:
            from ray_tpu._private import shm_store as _shm

            _shm.unlink_arena(self.arena_name)
        try:
            os.unlink(self.socket_path)
        except OSError:
            pass
        # release the pump plumbing (pipes are raw fds: without this every
        # Head — one per test — leaks 4 fds + an epoll fd)
        try:
            self._pump_sel.close()
        except OSError:
            pass
        for fd in (self._io_wake_r, self._io_wake_w, self._io_prog_r, self._io_prog_w):
            try:
                os.close(fd)
            except OSError:
                pass

    # --------------------------------------------------------- observability

    def _event(self, rec, state):
        # hot path (3 events per task): store a compact tuple; consumers
        # (rpc_task_events -> state API / timeline) expand to dicts lazily.
        # The static fields are resolved once per rec, not per event
        pre = rec.get("_ev")
        if pre is None:
            spec = rec["spec"]
            tctx = spec.get("trace_ctx")
            pre = rec["_ev"] = (
                rec["task_id"], spec.get("name"), spec.get("kind"),
                tctx.get("request_id") if tctx else None,
            )
        self.task_events.append(
            (pre[0], pre[1], state, time.time(), pre[2], pre[3])
        )
        if len(self.task_events) > GLOBAL_CONFIG.task_events_max_entries:
            # floor of 1 so tiny settings still trim instead of growing forever
            del self.task_events[: max(1, GLOBAL_CONFIG.task_events_max_entries // 2)]



def _iter_arg_refs(spec: dict):
    for a in spec.get("args", ()):  # ('v', bytes) | ('r', obj_id)
        if a[0] == "r":
            yield a
    for a in spec.get("kwargs", {}).values():
        if a[0] == "r":
            yield a


def _picklable(e) -> bool:
    try:
        import cloudpickle

        cloudpickle.dumps(e)
        return True
    except Exception:
        return False
