"""Runtime environments: per-task/actor env_vars + working_dir.

Reference: ``python/ray/_private/runtime_env/`` — the env system whose two
workhorse features are ``env_vars`` and ``working_dir`` (zipped through the
GCS KV, ``packaging.py``; extracted per node by the runtime-env agent).
TPU-first simplification: no per-node agent daemon — the submitting process
zips the directory into the head KV once (content-addressed), and workers
extract it lazily into a per-key cache directory. ``env_vars`` apply for the
duration of a task (and for an actor's whole life, since actors own their
worker process).

Supported keys: ``env_vars`` (dict str->str), ``working_dir`` (local path).
Unknown keys raise at submission (fail fast, like the reference's
validation).
"""

from __future__ import annotations

import contextlib
import hashlib
import io
import os
import sys
import tempfile
import zipfile
from typing import Any, Optional

_ALLOWED = {"env_vars", "working_dir"}
_KV_PREFIX = "__runtime_env_pkg__/"
_EXTRACT_CACHE: dict[str, str] = {}  # kv key -> extracted dir (per process)


def package(runtime_env: Optional[dict], ctx) -> Optional[dict]:
    """Validate + normalize at submission: working_dir is zipped into the
    head KV (content-addressed, uploaded once)."""
    if not runtime_env:
        return None
    unknown = set(runtime_env) - _ALLOWED
    if unknown:
        raise ValueError(
            f"Unsupported runtime_env key(s) {sorted(unknown)}; "
            f"supported: {sorted(_ALLOWED)}"
        )
    out: dict[str, Any] = {}
    env_vars = runtime_env.get("env_vars")
    if env_vars:
        if not all(isinstance(k, str) and isinstance(v, str) for k, v in env_vars.items()):
            raise TypeError("runtime_env['env_vars'] must be a dict[str, str]")
        out["env_vars"] = dict(env_vars)
    wd = runtime_env.get("working_dir")
    if wd:
        if not os.path.isdir(wd):
            raise ValueError(f"runtime_env['working_dir'] {wd!r} is not a directory")
        buf = io.BytesIO()
        with zipfile.ZipFile(buf, "w", zipfile.ZIP_DEFLATED) as zf:
            for root, _dirs, files in os.walk(wd):
                for name in files:
                    full = os.path.join(root, name)
                    zf.write(full, os.path.relpath(full, wd))
        blob = buf.getvalue()
        key = _KV_PREFIX + hashlib.sha1(blob).hexdigest()
        if ctx.call("kv_get", key=key) is None:
            ctx.call("kv_put", key=key, value=blob)
        out["working_dir_key"] = key
    return out or None


def _extract(key: str, ctx) -> str:
    path = _EXTRACT_CACHE.get(key)
    if path is not None and os.path.isdir(path):
        return path
    blob = ctx.call("kv_get", key=key)
    if blob is None:
        raise RuntimeError(f"runtime_env package {key!r} missing from cluster KV")
    path = os.path.join(
        tempfile.gettempdir(), f"ray_tpu_env_{key.rsplit('/', 1)[-1][:16]}"
    )
    if not os.path.isdir(path):
        tmp = path + f".tmp{os.getpid()}"
        with zipfile.ZipFile(io.BytesIO(blob)) as zf:
            zf.extractall(tmp)
        try:
            os.replace(tmp, path)  # atomic vs concurrent extractors
        except OSError:
            import shutil

            shutil.rmtree(tmp, ignore_errors=True)
    _EXTRACT_CACHE[key] = path
    return path


@contextlib.contextmanager
def applied(runtime_env: Optional[dict], ctx, permanent: bool = False):
    """Worker-side application. ``permanent=True`` (actors) leaves the env
    in place — the actor owns its process for life."""
    if not runtime_env:
        yield
        return
    saved_env: dict[str, Optional[str]] = {}
    saved_cwd = os.getcwd()
    saved_path = list(sys.path)
    try:
        for k, v in (runtime_env.get("env_vars") or {}).items():
            saved_env[k] = os.environ.get(k)
            os.environ[k] = v
        key = runtime_env.get("working_dir_key")
        if key:
            wd = _extract(key, ctx)
            os.chdir(wd)
            if wd not in sys.path:
                sys.path.insert(0, wd)  # reference: working_dir is importable
        yield
    finally:
        if not permanent:
            for k, old in saved_env.items():
                if old is None:
                    os.environ.pop(k, None)
                else:
                    os.environ[k] = old
            try:
                os.chdir(saved_cwd)
            except OSError:
                pass
            sys.path[:] = saved_path
