"""Runtime environments: env_vars, working_dir, py_modules, pip, conda,
container + plugins.

Reference: ``python/ray/_private/runtime_env/`` — ``packaging.py`` (zipped
URIs through the GCS KV, extracted per node with a URI cache), ``pip.py``
(per-env-hash virtualenv built once per node), ``plugin.py`` (the plugin
API third-party env features hang off). TPU-first simplifications:

* no per-node agent daemon — the submitting process zips/uploads
  content-addressed blobs into the head KV once; workers materialize them
  lazily into per-hash cache directories shared by every worker on the
  node (concurrent builders serialize on an fcntl lock);
* ``pip`` environments install into a per-hash PREFIX
  (``pip install --target``) activated by sys.path injection rather than
  exec'ing a venv interpreter: this image's base interpreter is itself a
  venv, so a child venv cannot chain ``--system-site-packages`` to reach
  jax/ray_tpu. The activation point (marked "pip ACTIVATION SEAM" inside
  :func:`applied`) is where an exec-based implementation would slot in.
  Requirements that name LOCAL files (wheels) are shipped through the KV,
  so air-gapped clusters install with ``--no-index``;
* ``conda`` (reference ``runtime_env/conda.py``): yml specs build a
  per-content-hash prefix env once per node (``conda env create -p``);
  named envs resolve against the node's installation. Activation is
  in-process (PATH/CONDA_PREFIX + site-packages injection when the
  interpreter minor version matches) — the "conda ACTIVATION SEAM" in
  :func:`applied` is where an exec-based worker swap would slot in;
* ``container`` (reference ``runtime_env/container.py``): actors (which
  own a dedicated worker process) spawn inside ``podman run`` joining the
  host's network/IPC/PID namespaces with /tmp, /dev/shm, and the package
  root bound — see :func:`container_wrap`, applied in
  ``head._spawn_worker`` and ``node_agent._spawn``. Pooled task workers
  reject the key at submission;
* plugins: :func:`register_plugin` adds a key handled by a
  :class:`RuntimeEnvPlugin` — ``package_value`` runs at submission (upload
  side-channel data through ``ctx``), ``apply`` is a worker-side context
  manager.

``env_vars`` apply for the duration of a task (and for an actor's whole
life, since actors own their worker process). Unknown non-plugin keys
raise at submission (fail fast, like the reference's validation).
"""

from __future__ import annotations

import contextlib
import hashlib
import io
import os
import subprocess
import sys
import tempfile
import zipfile
from typing import Any, Optional

_ALLOWED = {"env_vars", "working_dir", "py_modules", "pip", "conda", "container"}
_KV_PREFIX = "__runtime_env_pkg__/"
_EXTRACT_CACHE: dict[str, str] = {}  # kv key -> extracted dir (per process)


class RuntimeEnvPlugin:
    """Third-party runtime_env feature (reference: runtime_env/plugin.py).

    Subclass, then ``register_plugin("mykey", MyPlugin())`` — tasks/actors
    may then pass ``runtime_env={"mykey": value}``.
    """

    def package_value(self, value, ctx):
        """Submission-side: validate/normalize; may upload blobs via
        ``ctx.call("kv_put", ...)``. The return value ships in the spec."""
        return value

    @contextlib.contextmanager
    def apply(self, value, ctx):
        """Worker-side: set up around the task (or actor lifetime)."""
        yield


_PLUGINS: dict[str, RuntimeEnvPlugin] = {}

#: raylint RL017 — plugin registration is an import-time dict store on the
#: driver; worker task bodies only READ it (dict get is GIL-atomic), and a
#: registration racing a running task is a caller error by contract
LOCKFREE = ("_PLUGINS: atomic",)


def register_plugin(key: str, plugin: RuntimeEnvPlugin) -> None:
    if key in _ALLOWED:
        raise ValueError(f"{key!r} is a built-in runtime_env key")
    _PLUGINS[key] = plugin


def package(runtime_env: Optional[dict], ctx, kind: str = "task") -> Optional[dict]:
    """Validate + normalize at submission: working_dir is zipped into the
    head KV (content-addressed, uploaded once). ``kind`` is "task" or
    "actor" — container isolation needs a dedicated worker process, which
    only actors (and job supervisors) own."""
    if not runtime_env:
        return None
    unknown = set(runtime_env) - _ALLOWED - set(_PLUGINS)
    if unknown:
        raise ValueError(
            f"Unsupported runtime_env key(s) {sorted(unknown)}; "
            f"supported: {sorted(_ALLOWED | set(_PLUGINS))}"
        )
    out: dict[str, Any] = {}
    env_vars = runtime_env.get("env_vars")
    if env_vars:
        if not all(isinstance(k, str) and isinstance(v, str) for k, v in env_vars.items()):
            raise TypeError("runtime_env['env_vars'] must be a dict[str, str]")
        out["env_vars"] = dict(env_vars)
    wd = runtime_env.get("working_dir")
    if wd:
        if not os.path.isdir(wd):
            raise ValueError(f"runtime_env['working_dir'] {wd!r} is not a directory")
        buf = io.BytesIO()
        with zipfile.ZipFile(buf, "w", zipfile.ZIP_DEFLATED) as zf:
            for root, _dirs, files in os.walk(wd):
                for name in files:
                    full = os.path.join(root, name)
                    zf.write(full, os.path.relpath(full, wd))
        out["working_dir_key"] = _kv_put_blob(buf.getvalue(), ctx)
    mods = runtime_env.get("py_modules")
    if mods:
        keys = []
        for mod in mods:
            if not os.path.exists(mod):
                raise ValueError(f"runtime_env['py_modules'] entry {mod!r} not found")
            keys.append(_upload_module(mod, ctx))
        out["py_modules_keys"] = keys
    reqs = runtime_env.get("pip")
    if reqs:
        if isinstance(reqs, str):
            # the string form names a requirements FILE (reference pip.py
            # semantics), expanded at submission
            if not os.path.isfile(reqs):
                raise ValueError(f"runtime_env['pip'] requirements file {reqs!r} not found")
            with open(reqs) as fh:
                reqs = [
                    line.strip()
                    for line in fh.read().splitlines()
                    if line.strip() and not line.strip().startswith("#")
                ]
        shipped = []
        for r in reqs:
            remote_form = "://" in r or r.startswith("git+") or " @ " in r
            looks_local = not remote_form and (
                "/" in r or r.endswith((".whl", ".tar.gz", ".zip"))
            )
            if looks_local and not os.path.isfile(r):
                # fail at SUBMISSION like working_dir/py_modules do, not
                # minutes later on every worker (or worse, let a connected
                # pip try to resolve the path against an index)
                raise ValueError(f"runtime_env['pip'] local distribution {r!r} not found")
            if looks_local:
                # a LOCAL distribution (wheel/sdist): ship its bytes so
                # every node can install it without an index (air-gapped)
                with open(r, "rb") as fh:
                    blob = fh.read()
                shipped.append({
                    "file_key": _kv_put_blob(blob, ctx),
                    "name": os.path.basename(r),
                })
            else:
                shipped.append({"req": r})
        out["pip"] = shipped
    conda = runtime_env.get("conda")
    if conda:
        # reference conda.py semantics: a dict is an environment.yml spec,
        # a string is either a yml FILE path or the NAME of a pre-existing
        # env on the nodes. yml content ships in the spec (it is tiny) so
        # workers need no submission-host filesystem access.
        if isinstance(conda, dict):
            import yaml as _yaml

            out["conda"] = {"yaml": _yaml.safe_dump(conda, sort_keys=True)}
        elif isinstance(conda, str) and conda.endswith((".yml", ".yaml")):
            if not os.path.isfile(conda):
                raise ValueError(f"runtime_env['conda'] file {conda!r} not found")
            with open(conda) as f:
                out["conda"] = {"yaml": f.read()}
        elif isinstance(conda, str):
            out["conda"] = {"name": conda}
        else:
            raise TypeError("runtime_env['conda'] must be a dict, yml path, or env name")
    container = runtime_env.get("container")
    if container:
        if kind != "actor":
            # a pooled task worker cannot be retroactively containerized;
            # the reference's worker-level container support likewise rides
            # dedicated worker startup (runtime_env/container.py)
            raise ValueError(
                "runtime_env['container'] requires a dedicated worker "
                "process — use an actor (or submit a job)"
            )
        if not isinstance(container, dict) or not container.get("image"):
            raise TypeError("runtime_env['container'] must be {'image': ..., ...}")
        unknown_c = set(container) - {"image", "run_options", "worker_python", "runner"}
        if unknown_c:
            raise ValueError(f"unsupported container key(s) {sorted(unknown_c)}")
        out["container"] = {
            "image": str(container["image"]),
            "run_options": [str(o) for o in container.get("run_options") or []],
            "worker_python": str(container.get("worker_python") or "python3"),
            **({"runner": str(container["runner"])} if container.get("runner") else {}),
        }
    for key, plugin in _PLUGINS.items():
        if key in runtime_env:
            out.setdefault("plugins", {})[key] = plugin.package_value(
                runtime_env[key], ctx
            )
    return out or None


def _kv_put_blob(blob: bytes, ctx) -> str:
    """Content-addressed upload-once into the cluster KV."""
    key = _KV_PREFIX + hashlib.sha1(blob).hexdigest()
    if ctx.call("kv_get", key=key) is None:
        ctx.call("kv_put", key=key, value=blob)
    return key


def _upload_module(path: str, ctx) -> dict:
    """Zip one py_modules entry so its TOP-LEVEL name lands importable
    (reference: py_modules upload in packaging.py)."""
    path = os.path.abspath(path)
    base = os.path.basename(path.rstrip("/"))
    buf = io.BytesIO()
    with zipfile.ZipFile(buf, "w", zipfile.ZIP_DEFLATED) as zf:
        if os.path.isdir(path):
            for root, _dirs, files in os.walk(path):
                for name in files:
                    full = os.path.join(root, name)
                    zf.write(full, os.path.join(base, os.path.relpath(full, path)))
        else:
            zf.write(path, base)
    return {"key": _kv_put_blob(buf.getvalue(), ctx), "name": base}


def _extract(key: str, ctx) -> str:
    path = _EXTRACT_CACHE.get(key)
    if path is not None and os.path.isdir(path):
        return path
    blob = ctx.call("kv_get", key=key)
    if blob is None:
        raise RuntimeError(f"runtime_env package {key!r} missing from cluster KV")
    path = os.path.join(
        tempfile.gettempdir(), f"ray_tpu_env_{key.rsplit('/', 1)[-1][:16]}"
    )
    if not os.path.isdir(path):
        tmp = path + f".tmp{os.getpid()}"
        with zipfile.ZipFile(io.BytesIO(blob)) as zf:
            zf.extractall(tmp)
        try:
            os.replace(tmp, path)  # atomic vs concurrent extractors
        except OSError:
            import shutil

            shutil.rmtree(tmp, ignore_errors=True)
    _EXTRACT_CACHE[key] = path
    return path


def _cache_root() -> str:
    d = os.path.join(tempfile.gettempdir(), "ray_tpu_runtime_env")
    os.makedirs(d, exist_ok=True)
    return d


@contextlib.contextmanager
def _build_lock(name: str):
    """Cross-process build serialization (several workers on a node may
    need the same env at once — exactly one builds, the rest wait)."""
    import fcntl

    lock_path = os.path.join(_cache_root(), name + ".lock")
    with open(lock_path, "w") as f:
        fcntl.flock(f, fcntl.LOCK_EX)
        try:
            yield
        finally:
            fcntl.flock(f, fcntl.LOCK_UN)


def ensure_pip_prefix(shipped: list, ctx) -> str:
    """Materialize the pip environment for this node (reference: pip.py —
    per-env-hash virtualenv built once, cached by hash). Returns the
    installed prefix directory; built exactly once per node per hash (the
    ``.done`` marker is the cache hit)."""
    env_hash = hashlib.sha1(
        repr(sorted(e.get("req") or e["file_key"] for e in shipped)).encode()
    ).hexdigest()[:16]
    prefix = os.path.join(_cache_root(), f"pip-{env_hash}")
    done = os.path.join(prefix, ".done")
    if os.path.exists(done):
        return prefix
    with _build_lock(f"pip-{env_hash}"):
        if os.path.exists(done):
            return prefix  # another worker built it while we waited
        import shutil

        # build into a scratch dir, promote atomically: a failed/timed-out
        # install must never leave a half-written prefix that a retry's
        # pip (which does NOT replace existing --target dirs) then seals
        # behind a .done marker
        scratch = prefix + ".building"
        shutil.rmtree(scratch, ignore_errors=True)
        shutil.rmtree(prefix, ignore_errors=True)
        os.makedirs(scratch)
        args = []
        all_local = True
        for e in shipped:
            if "file_key" in e:
                blob = ctx.call("kv_get", key=e["file_key"])
                if blob is None:
                    raise RuntimeError(f"pip distribution {e['name']} missing from KV")
                dist = os.path.join(scratch, e["name"])
                with open(dist, "wb") as f:
                    f.write(blob)
                args.append(dist)
            else:
                args.append(e["req"])
                all_local = False
        cmd = [sys.executable, "-m", "pip", "install", "--target", scratch,
               "--no-warn-script-location", "--quiet"]
        if all_local:
            cmd.append("--no-index")  # air-gapped: everything shipped via KV
        try:
            proc = subprocess.run(cmd + args, capture_output=True, text=True, timeout=600)
        except subprocess.TimeoutExpired as e:
            shutil.rmtree(scratch, ignore_errors=True)
            raise RuntimeError(f"runtime_env pip install timed out: {e}") from None
        if proc.returncode != 0:
            shutil.rmtree(scratch, ignore_errors=True)
            raise RuntimeError(
                f"runtime_env pip install failed (rc={proc.returncode}):\n"
                f"{proc.stderr[-2000:]}"
            )
        for e in shipped:  # the wheels' CONTENTS are installed; drop the
            if "file_key" in e:  # shipped copies from the sys.path prefix
                try:
                    os.unlink(os.path.join(scratch, e["name"]))
                except OSError:
                    pass
        with open(os.path.join(scratch, ".done"), "w") as f:
            f.write("ok")
        os.rename(scratch, prefix)
    return prefix


# named env -> resolved prefix, per worker process: pooled workers apply
# envs per TASK, and a conda subprocess per task would dominate latency
_NAMED_CONDA_CACHE: dict[str, str] = {}


def _conda_exe() -> Optional[str]:
    import shutil

    return (
        os.environ.get("RAY_TPU_CONDA_EXE")
        or os.environ.get("CONDA_EXE")
        or shutil.which("conda")
        or shutil.which("mamba")
        or shutil.which("micromamba")
    )


def ensure_conda_prefix(spec: dict) -> str:
    """Materialize the conda environment for this node (reference: conda.py
    ``get_or_create_conda_env`` — per-yml-hash env built once, cached).
    Named envs resolve against the node's conda installation; yml specs
    create a prefix env under the runtime-env cache, exactly once per node
    per content hash."""
    import json
    import shutil
    import subprocess as sp

    exe = _conda_exe()
    if exe is None:
        raise RuntimeError(
            "runtime_env['conda'] requires a conda/mamba binary on the node "
            "(set RAY_TPU_CONDA_EXE to override discovery)"
        )
    name = spec.get("name")
    if name:
        cached = _NAMED_CONDA_CACHE.get(name)
        if cached is not None:
            return cached
        if name == "base":
            # the root prefix's basename is the install dir ('miniconda3'),
            # never 'base' — resolve it like the reference conda.py does
            proc = sp.run([exe, "info", "--json"], capture_output=True, text=True, timeout=120)
            if proc.returncode != 0:
                raise RuntimeError(f"conda info failed:\n{proc.stderr[-1000:]}")
            root = json.loads(proc.stdout).get("root_prefix")
            if root:
                _NAMED_CONDA_CACHE[name] = root
                return root
        proc = sp.run([exe, "env", "list", "--json"], capture_output=True, text=True, timeout=120)
        if proc.returncode != 0:
            raise RuntimeError(f"conda env list failed:\n{proc.stderr[-1000:]}")
        for prefix in json.loads(proc.stdout).get("envs", []):
            if os.path.basename(prefix) == name:
                _NAMED_CONDA_CACHE[name] = prefix
                return prefix
        raise RuntimeError(f"conda env {name!r} not found on this node")
    yml = spec["yaml"]
    env_hash = hashlib.sha1(yml.encode()).hexdigest()[:16]
    prefix = os.path.join(_cache_root(), f"conda-{env_hash}")
    done = os.path.join(prefix, ".done")
    if os.path.exists(done):
        return prefix
    with _build_lock(f"conda-{env_hash}"):
        if os.path.exists(done):
            return prefix  # another worker built it while we waited
        scratch = prefix + ".building"
        shutil.rmtree(scratch, ignore_errors=True)
        shutil.rmtree(prefix, ignore_errors=True)
        yml_path = os.path.join(_cache_root(), f"conda-{env_hash}.yml")
        with open(yml_path, "w") as f:
            f.write(yml)
        try:
            proc = sp.run(
                [exe, "env", "create", "-p", scratch, "-f", yml_path, "-q"],
                capture_output=True,
                text=True,
                timeout=900,
            )
        except sp.TimeoutExpired as e:
            shutil.rmtree(scratch, ignore_errors=True)
            raise RuntimeError(f"conda env create timed out: {e}") from None
        if proc.returncode != 0:
            shutil.rmtree(scratch, ignore_errors=True)
            raise RuntimeError(
                f"conda env create failed (rc={proc.returncode}):\n{proc.stderr[-2000:]}"
            )
        with open(os.path.join(scratch, ".done"), "w") as f:
            f.write("ok")
        os.rename(scratch, prefix)
    return prefix


def container_wrap(argv: list, env: dict, pkg_root: str, spec: dict) -> tuple[list, dict]:
    """Wrap a worker spawn command in a container runner invocation
    (reference: runtime_env/container.py — podman run with host namespaces).

    The worker must still reach the head's AF_UNIX socket (/tmp), the shm
    arena (/dev/shm), and the ray_tpu package (ro bind of pkg_root), so the
    container joins the host's network/IPC/PID namespaces and binds those
    paths. ``argv`` must start with the host python; it is swapped for the
    image's ``worker_python``. RAY_TPU_*/PYTHONPATH env vars cross the
    boundary as explicit --env flags (a container does not inherit the
    spawner's environ). Returns (wrapped_argv, spawn_env)."""
    runner = (
        spec.get("runner")
        or os.environ.get("RAY_TPU_CONTAINER_RUNNER")
        or "podman"
    )
    tmp = tempfile.gettempdir()  # head socket + env caches follow TMPDIR
    prefix = [
        runner,
        "run",
        "--rm",
        "--network=host",
        "--ipc=host",
        "--pid=host",
        "-v",
        f"{pkg_root}:{pkg_root}:ro",
        "-v",
        f"{tmp}:{tmp}",
        "-v",
        "/dev/shm:/dev/shm",
    ]
    if tmp != "/tmp":
        prefix += ["-v", "/tmp:/tmp"]
    for k, v in sorted(env.items()):
        if k == "PYTHONPATH" or k.startswith("RAY_TPU_"):
            prefix += ["--env", f"{k}={v}"]
    prefix += spec.get("run_options") or []
    prefix.append(spec["image"])
    inner = [spec.get("worker_python") or "python3"] + list(argv[1:])
    return prefix + inner, env


@contextlib.contextmanager
def applied(runtime_env: Optional[dict], ctx, permanent: bool = False):
    """Worker-side application. ``permanent=True`` (actors) leaves the env
    in place — the actor owns its process for life."""
    if not runtime_env:
        yield
        return
    saved_env: dict[str, Optional[str]] = {}
    saved_cwd = os.getcwd()
    saved_path = list(sys.path)

    def _restore():
        for k, old in saved_env.items():
            if old is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = old
        try:
            os.chdir(saved_cwd)
        except OSError:
            pass
        sys.path[:] = saved_path

    with contextlib.ExitStack() as stack:
        # registered FIRST so it unwinds LAST: plugin teardown must run in
        # the environment the plugin was set up in (env vars, working_dir,
        # sys.path still applied)
        stack.callback(_restore)
        for k, v in (runtime_env.get("env_vars") or {}).items():
            saved_env[k] = os.environ.get(k)
            os.environ[k] = v
        conda = runtime_env.get("conda")
        if conda:
            # conda ACTIVATION SEAM: like pip below, activation is in-process
            # — PATH/CONDA_PREFIX for the env's binaries + native libs, and
            # sys.path for its pure-python packages when the env's
            # interpreter minor version matches this worker's. A full
            # interpreter swap would slot in at worker spawn (next to the
            # container prefix in head._spawn_worker).
            prefix = ensure_conda_prefix(conda)
            for k, v in (
                ("PATH", os.path.join(prefix, "bin") + os.pathsep + os.environ.get("PATH", "")),
                ("CONDA_PREFIX", prefix),
                ("CONDA_DEFAULT_ENV", os.path.basename(prefix)),
            ):
                saved_env.setdefault(k, os.environ.get(k))
                os.environ[k] = v
            site = os.path.join(
                prefix, "lib", f"python{sys.version_info[0]}.{sys.version_info[1]}", "site-packages"
            )
            if os.path.isdir(site):
                sys.path.insert(0, site)
        reqs = runtime_env.get("pip")
        if reqs:
            # pip ACTIVATION SEAM (see module docstring): swap this
            # sys.path injection for an exec-based per-env interpreter to
            # get full process isolation
            sys.path.insert(0, ensure_pip_prefix(reqs, ctx))
        for ent in runtime_env.get("py_modules_keys") or []:
            root = _extract(ent["key"], ctx)
            if root not in sys.path:
                sys.path.insert(0, root)
        key = runtime_env.get("working_dir_key")
        if key:
            wd = _extract(key, ctx)
            os.chdir(wd)
            if wd not in sys.path:
                sys.path.insert(0, wd)  # reference: working_dir is importable
        for pkey, value in (runtime_env.get("plugins") or {}).items():
            plugin = _PLUGINS.get(pkey)
            if plugin is None:
                raise RuntimeError(
                    f"runtime_env plugin {pkey!r} is not registered in the "
                    f"worker process (register it in the task/actor module)"
                )
            stack.enter_context(plugin.apply(value, ctx))
        if permanent:
            stack.pop_all()  # actor lifetime: nothing is ever undone
        yield
