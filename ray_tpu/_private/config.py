"""Runtime configuration flags.

TPU-native analogue of the reference's ``RAY_CONFIG(type, name, default)`` flag
system (``src/ray/common/ray_config_def.h`` — 218 flags, overridable via
``RAY_{name}`` env vars or a ``_system_config`` dict passed to ``ray.init``).

We keep the same three override tiers: compiled-in default < environment
variable ``RAY_TPU_{NAME}`` < explicit ``_system_config`` dict at ``init()``.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Any


@dataclasses.dataclass
class Config:
    # -- object store ------------------------------------------------------
    #: Objects at or below this many serialized bytes live in the in-process
    #: store; larger ones go to a shared-memory segment (reference: core
    #: worker memory store promotes to plasma above ~100KB).
    max_direct_call_object_size: int = 100 * 1024
    #: Zero-copy data plane (ISSUE 18): a producer whose host has the native
    #: arena attached writes any value ABOVE this many serialized bytes into
    #: shared memory and ships only the locator over the control socket —
    #: payload bytes never transit the head. Below it, inlining wins (the
    #: locator + directory entry costs more than the bytes). Only applies
    #: when an arena is actually attached; without one the fallback cutoff
    #: is ``max_direct_call_object_size`` (a dedicated POSIX segment per
    #: mid-size object would pay shm_open+mmap+fault per put — a regression,
    #: not an optimisation). Set >= max_direct_call_object_size to restore
    #: the pre-ISSUE-18 inline behavior.
    core_shm_inline_threshold: int = 8 * 1024
    #: Pipelined worker puts (ISSUE 18): ``ray.put`` from a worker ships
    #: fire-and-forget (seq-0, in-order on the conn) instead of blocking a
    #: round trip per object, so put bursts are bounded by head processing
    #: rather than N RTTs. ``False`` restores the blocking put (the
    #: BENCH_r09 "before" arm; ``ray://`` drivers always block — their
    #: reconnect window cannot detect a lost un-acked put).
    core_put_pipeline: bool = True
    #: Logical "memory" resource advertised by a node when ``ray.init`` is not
    #: given ``object_store_memory`` (reference: plasma store capacity).
    object_store_memory: int = 0  # 0 = auto (30% of system RAM)
    #: shm arena watermark: above this, least-recently-used unpinned objects
    #: spill to disk (reference: local_object_manager.h spill throttles).
    #: 0 = auto (object_store_memory, else 2 GiB).
    object_spilling_threshold_bytes: int = 0
    #: Size of the native shared-memory arena (the plasma-equivalent C++
    #: allocator in ``ray_tpu/_native/arena.cc``) each head creates for its
    #: host. The segment is sparse — pages commit on first touch — so the
    #: default costs nothing until used. 0 disables the arena (every object
    #: gets a dedicated POSIX segment, the pure-Python fallback).
    object_store_arena_bytes: int = 4 * 1024 * 1024 * 1024
    #: Objects at or below this many serialized bytes are placed in the
    #: arena (one lock-protected pointer bump instead of a per-object
    #: shm_open+mmap+unlink syscall round-trip — and, critically for write
    #: throughput, arena pages are faulted once and then RECYCLED across
    #: objects, where a fresh POSIX segment pays a page fault + kernel zero
    #: per 4K on every put: ~1.6 GB/s faulting vs memcpy speed recycled).
    #: Larger objects use a dedicated segment whose mapping supports
    #: zero-copy reads for the lifetime of the value (arena reads copy out
    #: under a pin, so blocks can be recycled safely — see arena.cc
    #: pin/generation protocol).
    arena_max_object_bytes: int = 64 * 1024 * 1024

    #: Rebuild lost task-produced objects by resubmitting their creating
    #: task (reference: object_recovery_manager.h lineage reconstruction).
    enable_lineage_reconstruction: bool = True
    #: Total bytes of creating-task specs retained for reconstruction;
    #: beyond this the oldest objects silently lose reconstructability
    #: (reference: lineage total-size eviction in reference_count.h).
    max_lineage_bytes: int = 64 * 1024 * 1024
    #: Path for head-state snapshots (KV store, function table). Empty =
    #: no persistence. With a path set, a restarting head reloads the
    #: snapshot (reference: GCS Redis-backed table storage for HA).
    gcs_snapshot_path: str = ""
    #: Seconds between periodic snapshots (also written at shutdown).
    gcs_snapshot_interval_s: float = 10.0

    # -- scheduler ---------------------------------------------------------
    #: Hybrid scheduling policy: pack onto busiest feasible node until its
    #: critical-resource utilization exceeds this threshold, then prefer the
    #: least-utilized node (reference: hybrid_scheduling_policy.cc,
    #: ``scheduler_spread_threshold``).
    scheduler_spread_threshold: float = 0.5
    #: Max queued-but-infeasible warning interval.
    infeasible_warn_interval_s: float = 30.0

    # -- memory monitor ----------------------------------------------------
    #: Host memory usage fraction above which the OOM killer picks a victim
    #: worker (reference: memory_monitor.h usage threshold, default 0.95).
    memory_usage_threshold: float = 0.95
    #: Memory monitor sampling interval; 0 disables the monitor (the
    #: reference defaults to 250ms — conservative default here so co-tenant
    #: CI machines running hot don't see spurious kills; enable via
    #: _system_config or RAY_TPU env override).
    memory_monitor_refresh_ms: int = 0

    # -- workers -----------------------------------------------------------
    #: Idle (non-actor) workers are reaped by the health loop after this many
    #: seconds without a task, when nothing is queued (reference: worker_pool
    #: idle worker killing). 0 disables reaping.
    idle_worker_keep_alive_s: float = 60.0
    #: Default max_retries for normal tasks (reference:
    #: ``task_retry_delay_ms`` / default 3 retries).
    default_max_retries: int = 3
    #: A spawned worker process that has not registered with the head within
    #: this many seconds is killed and respawned (reference:
    #: ``worker_register_timeout_seconds``, ray_config_def.h) — turns an
    #: interpreter that wedges at startup into a logged hiccup instead of an
    #: indefinite hang of whatever is waiting on its task. 0 disables the
    #: kill/respawn (agent-side spawns that crash before connecting then
    #: fall back to a fixed 60s reap).
    worker_register_timeout_s: float = 30.0
    #: Max worker processes booting (spawned, not yet registered) per node
    #: at once; further spawns queue in the dispatcher. Interpreter boot is
    #: CPU-bound, so an unbounded spawn storm (e.g. 100 actor creations)
    #: makes EVERY boot exceed the registration timeout (reference:
    #: ``maximum_startup_concurrency`` ≈ num_cpus, ray_config_def.h).
    #: 0 = per-node CPU count (min 2).
    worker_startup_concurrency: int = 0
    #: How many times a registration-timed-out spawn is retried before the
    #: slot's work is failed (actor creation) or left to the scheduler
    #: (pool workers).
    worker_spawn_retries: int = 3
    #: Fork new workers from a per-node warm template process
    #: (worker_template.py) instead of cold interpreter boots: ~5-10ms per
    #: worker vs ~300ms+, the forkserver analog of the reference's
    #: pre-started worker pool (worker_pool.h:152). Containerised workers
    #: always cold-spawn. Disable to debug spawn-path issues.
    worker_forkserver_enabled: bool = True

    #: Pipeline up to this many plain tasks of identical scheduling
    #: signature onto one worker (followers ride the head task's resource
    #: lease; alloc transfers at completion). Hides the head<->worker
    #: round-trip entirely for small-task storms (reference:
    #: ``max_tasks_in_flight_per_worker``, direct task submitter). 1
    #: disables pipelining.
    max_tasks_in_flight_per_worker: int = 4

    #: Streaming-generator backpressure window: a producer pauses once this
    #: many yielded items are unconsumed (reference:
    #: ``_generator_backpressure_num_objects``). Consumer progress is pushed
    #: back to the worker as stream_ack messages.
    streaming_backpressure_items: int = 16

    # -- actors ------------------------------------------------------------
    default_max_restarts: int = 0
    default_max_task_retries: int = 0

    #: After a head crash/restart, node agents and detached-actor workers
    #: retry the head address this long before giving up (reference: the
    #: raylet reconnect window, ray_config_def.h:56-60
    #: ``gcs_rpc_server_reconnect_timeout_s``). The restarted head holds
    #: restored detached actors for the same window before re-creating
    #: them fresh.
    head_reconnect_grace_s: float = 30.0
    #: How long a disconnected ``ray://`` client session keeps its refs and
    #: actors alive waiting for a reconnect-with-token before the head
    #: releases them (reference: the client proxier's cleanup window,
    #: ``util/client/server/proxier.py``).
    client_reconnect_grace_s: float = 30.0

    # -- object data plane -------------------------------------------------
    #: Chunk size for node-to-node object transfers on the peer-to-peer
    #: data plane (reference: object_manager.h ``object_chunk_size``, 64MB
    #: there; smaller here because chunks also bound the sender's pin hold).
    object_transfer_chunk_bytes: int = 8 * 1024 * 1024
    #: A node that answered "I don't hold that object" is not re-asked for
    #: this long (reference: pull manager retry backoff) — bounds directory
    #: chatter while a producer is still writing.
    object_location_negative_cache_s: float = 5.0

    # -- collective --------------------------------------------------------
    #: Host-mediated allreduce switches from flat fan-in to the chunked
    #: ring algorithm at this tensor size (reference: collective group
    #: picks ring for large payloads).
    collective_ring_threshold_bytes: int = 1 << 22

    # -- health ------------------------------------------------------------
    #: Interval of the head's liveness sweep over worker processes
    #: (reference: GcsHealthCheckManager probing raylets).
    health_check_interval_s: float = 1.0
    #: Interval at which node agents push /proc-derived CPU/memory/disk
    #: stats to the head (reference: the per-node reporter agent's
    #: ``metrics_report_interval_ms``).
    node_stats_report_interval_s: float = 5.0

    # -- control-plane internals ------------------------------------------
    #: Backstop flush period of the head's outbound-message queue; normal
    #: sends flush immediately after the head lock releases — this poll
    #: bounds the tail when the enqueuing thread parks before flushing
    #: (enqueue deliberately never wakes the backstop; see _enqueue_send).
    outbox_flush_backstop_s: float = 0.05
    #: Task-event feed retention: when the in-memory feed exceeds this many
    #: records, the oldest half is dropped (reference:
    #: ``task_events_max_num_task_in_gcs``).
    task_events_max_entries: int = 100_000
    #: Pipelined submission (reference: lease-pipelined direct task
    #: submission + ``max_grpc_message_size`` batching): socket contexts
    #: buffer ``.remote()`` specs into one ``submit_batch`` message instead
    #: of paying a send+reply rendezvous per task. A buffer flushes at this
    #: many specs, before any other head RPC, or at the backstop below.
    core_submit_batch_max: int = 64
    #: Submit-window flow control: tasks allowed in un-acked submit windows
    #: before a flush blocks for acks (the head acks WINDOWS, not tasks).
    core_submit_window_tasks: int = 4096
    #: Backstop flush period for a fire-and-forget submit buffer whose
    #: owner never issues another head RPC (side-effect-only tasks).
    core_submit_flush_backstop_s: float = 0.005
    #: Worker completion coalescing: when the worker still has queued work,
    #: finished-task replies accumulate (drained off-path by the reply
    #: flusher thread) and ship as one ``tasks_done_batch``; an idle worker
    #: always ships inline. Caps one batch message.
    core_reply_batch_max: int = 64
    #: Driver-side dispatch coalescing: an in-process submit leaves its
    #: ``run_task`` in the head outbox unflushed until this many messages
    #: queue (or until any blocking call / the outbox backstop flushes), so
    #: an async submit burst ships as few ``run_task_batch`` socket writes.
    core_dispatch_coalesce: int = 16
    #: Hard cap on a submitted spec's total inline (by-value) argument
    #: bytes on the batched submit path; beyond it the task's refs resolve
    #: to an async error telling the caller to ``put()`` the argument.
    core_max_spec_inline_bytes: int = 8 * 1024 * 1024

    # -- serving / dashboards ---------------------------------------------
    #: Default port of ``serve.start`` HTTP ingress proxies (reference:
    #: serve's ``http_options.port``).
    serve_http_port: int = 8000
    #: Attempts per Serve handle call across replica failures before the
    #: error surfaces to the caller (reference: router retry policy).
    serve_handle_max_retries: int = 4
    #: Default port of ``ray_tpu.dashboard.start`` (reference: 8265).
    dashboard_port: int = 8265

    # -- logging -----------------------------------------------------------
    log_to_driver: bool = True

    def apply_overrides(self, system_config: dict[str, Any] | None = None) -> None:
        for f in dataclasses.fields(self):
            env = os.environ.get(f"RAY_TPU_{f.name.upper()}")
            if env is not None:
                setattr(self, f.name, _coerce(env, f.type))
        for k, v in (system_config or {}).items():
            if not hasattr(self, k):
                raise ValueError(f"Unknown _system_config key: {k!r}")
            setattr(self, k, v)


def _coerce(raw: str, typ: Any) -> Any:
    typ = str(typ)
    if "bool" in typ:
        return raw.lower() in ("1", "true", "yes")
    if "int" in typ:
        return int(raw)
    if "float" in typ:
        return float(raw)
    return raw


GLOBAL_CONFIG = Config()
GLOBAL_CONFIG.apply_overrides()

_DEFAULTS = Config()


def config_overrides() -> dict[str, Any]:
    """The non-default fields of the live config — what a head ships to a
    joining node agent so the ``_system_config`` tier reaches remote
    agent/worker processes (reference: GCS serving system_config to
    raylets at registration)."""
    return {
        f.name: getattr(GLOBAL_CONFIG, f.name)
        for f in dataclasses.fields(GLOBAL_CONFIG)
        if getattr(GLOBAL_CONFIG, f.name) != getattr(_DEFAULTS, f.name)
    }


def apply_shipped(overrides: dict[str, Any]) -> None:
    """Apply head-shipped overrides in an agent process, LOSING to any
    explicit local env var (the operator set it on that host on purpose)."""
    for k, v in overrides.items():
        if hasattr(GLOBAL_CONFIG, k) and f"RAY_TPU_{k.upper()}" not in os.environ:
            setattr(GLOBAL_CONFIG, k, v)


# ---------------------------------------------------------------------------
# cluster auth (reference: the redis password / auth cookie the daemons share)
# ---------------------------------------------------------------------------

DEFAULT_AUTHKEY = b"ray-tpu-insecure-default"


def resolve_authkey() -> bytes:
    """Shared secret for the head's control-plane listeners. Set
    ``RAY_TPU_AUTHKEY`` (hex) on every host of a real deployment; the
    default only suits single-user/dev clusters (like the reference's
    default-open gRPC ports)."""
    import os

    raw = os.environ.get("RAY_TPU_AUTHKEY")
    return bytes.fromhex(raw) if raw else DEFAULT_AUTHKEY
