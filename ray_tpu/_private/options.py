"""Resolution of ``@remote(...)`` / ``.options(...)`` keyword options.

Counterpart of the reference's ``python/ray/_private/ray_option_utils.py``:
one table of valid options shared by tasks and actors, resource keywords
folded into a resource dict, scheduling strategies validated. TPU chips are
first-class (``num_tpus`` → ``"TPU"`` resource), GPUs kept for logical-
resource parity in tests.
"""

from __future__ import annotations

from typing import Any, Optional

from ray_tpu.util.scheduling_strategies import (
    NodeAffinitySchedulingStrategy,
    PlacementGroupSchedulingStrategy,
)

_COMMON = {
    "num_cpus", "num_gpus", "num_tpus", "memory", "resources", "name",
    "scheduling_strategy", "max_retries", "runtime_env", "num_returns",
    "placement_group", "placement_group_bundle_index",
    "placement_group_capture_child_tasks", "_metadata", "label_selector",
}
_ACTOR_ONLY = {"max_restarts", "max_task_retries", "max_concurrency", "concurrency_groups", "lifetime", "namespace", "get_if_exists"}


def validate(options: dict[str, Any], is_actor: bool) -> None:
    allowed = _COMMON | (_ACTOR_ONLY if is_actor else set())
    for k in options:
        if k not in allowed:
            raise ValueError(f"Invalid option {k!r} for {'actor' if is_actor else 'task'}")
    st = options.get("scheduling_strategy")
    pg = options.get("placement_group")
    if options.get("label_selector") and (
        st not in (None, "DEFAULT") or (pg is not None and pg != "default")
    ):
        # fail fast: to_strategy can honor only one placement policy, and
        # silently dropping the label constraint would mis-place the task
        raise ValueError(
            "label_selector cannot be combined with another placement policy "
            f"(scheduling_strategy={st!r}, placement_group={pg!r}); use "
            "NodeLabelSchedulingStrategy(hard=...) instead"
        )


def to_resources(options: dict[str, Any], is_actor: bool) -> dict[str, float]:
    res = dict(options.get("resources") or {})
    for key, rname in (("num_cpus", "CPU"), ("num_gpus", "GPU"), ("num_tpus", "TPU")):
        v = options.get(key)
        if v is not None:
            if v < 0:
                raise ValueError(f"{key} must be >= 0")
            res[rname] = float(v)
    if options.get("memory") is not None:
        res["memory"] = float(options["memory"])
    if "CPU" not in res:
        # Reference defaults: tasks take 1 CPU; actors take 0 for their
        # lifetime (they can oversubscribe — actor.py docstring in reference).
        res["CPU"] = 0.0 if is_actor else 1.0
    return res


def to_strategy(options: dict[str, Any]) -> Optional[tuple]:
    pg = options.get("placement_group")
    if pg is not None and pg != "default":
        return (
            "pg",
            pg.id if hasattr(pg, "id") else pg,
            options.get("placement_group_bundle_index", -1),
            options.get("placement_group_capture_child_tasks", False),
        )
    strategy = options.get("scheduling_strategy")
    if strategy is None or strategy == "DEFAULT":
        sel = options.get("label_selector")
        if sel:
            # label_selector = hard label requirements without a full
            # strategy object (reference: label_selector task option)
            return ("labels", tuple(sorted(sel.items())), ())
        return None
    if strategy == "SPREAD":
        return ("spread",)
    if isinstance(strategy, PlacementGroupSchedulingStrategy):
        pg = strategy.placement_group
        return ("pg", pg.id, strategy.placement_group_bundle_index if strategy.placement_group_bundle_index is not None else -1, strategy.placement_group_capture_child_tasks)
    if isinstance(strategy, NodeAffinitySchedulingStrategy):
        return ("node", strategy.node_id, strategy.soft)
    from ray_tpu.util.scheduling_strategies import NodeLabelSchedulingStrategy

    if isinstance(strategy, NodeLabelSchedulingStrategy):
        return (
            "labels",
            tuple(sorted(strategy.hard.items())),
            tuple(sorted(strategy.soft.items())),
        )
    raise ValueError(f"Unknown scheduling strategy: {strategy!r}")
