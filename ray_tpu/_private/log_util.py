"""Throttled daemon-loop warnings.

Daemon loops (health checks, reconcilers, stats pumps) must survive any
exception, but swallowing them silently turns real outages invisible —
raylint's RL007. This helper is the sanctioned middle ground: always keep
the loop alive, print the first failure per call-site immediately, then
rate-limit repeats so a persistent fault logs once per interval instead of
once per tick.
"""

from __future__ import annotations

import threading
import time

_lock = threading.Lock()
_last_emit: dict[str, float] = {}
_suppressed: dict[str, int] = {}
_MAX_KEYS = 1024  # call sites may key on channel/node names: bound the table


def warn_throttled(key: str, exc: BaseException, interval_s: float = 60.0) -> None:
    """Print ``[ray_tpu] <key>: <exc!r>`` at most once per ``interval_s``
    per ``key``; repeats within the window are counted and reported with the
    next emission so nothing is lost, only batched."""
    now = time.monotonic()
    with _lock:
        last = _last_emit.get(key)
        if last is not None and now - last < interval_s:
            _suppressed[key] = _suppressed.get(key, 0) + 1
            return
        if key not in _last_emit and len(_last_emit) >= _MAX_KEYS:
            oldest = min(_last_emit, key=_last_emit.get)
            del _last_emit[oldest]
            _suppressed.pop(oldest, None)
        _last_emit[key] = now
        n = _suppressed.pop(key, 0)
    suffix = f" ({n} similar suppressed)" if n else ""
    try:
        print(f"[ray_tpu] WARNING: {key}: {exc!r}{suffix}")
    except Exception:
        # stdout may be a closed pipe (parent gone, interpreter teardown).
        # This helper runs inside daemon-loop except handlers whose entire
        # job is keeping the loop alive — it must never raise.
        pass
