"""Head-side SLO alert engine: the stateful fire/resolve machine.

``util.slo`` owns the pure burn-rate math; this module owns the per-rule
state machine the head's ``head-alerts`` thread ticks against the drained
metric series:

* a rule whose evaluation breaches FIRES immediately — the multi-window
  burn-rate condition is its own damping (the slow window must agree), so
  an extra pending phase would only delay the page;
* a firing rule RESOLVES only after ``resolve_after_s`` of continuously
  clean evaluations (flapping hysteresis — one good window mid-incident
  must not close and re-open the alert);
* every transition lands in the flight recorder (``alert.fire`` /
  ``alert.resolve`` events, visible to ``obs events``/``obs req`` drains
  and crash flushes) and in the manager's state for ``obs alerts`` /
  ``/api/alerts``;
* firing alerts labeled ``{"serve": "upscale"}`` feed the serve
  autoscaler: a burning latency SLO adds one replica of upscale pressure
  (``serve/_private/controller.desired_replicas``).
"""

from __future__ import annotations

import threading
import time
from typing import Optional

from ray_tpu._private import events as _events
from ray_tpu.util import slo as _slo

OK = "OK"
FIRING = "FIRING"
RESOLVED = "RESOLVED"  # terminal display state until the next breach


class _RuleState:
    __slots__ = (
        "status", "since", "last_value", "last_detail", "clear_since",
        "fired_count", "last_transition",
    )

    def __init__(self):
        self.status = OK
        self.since: Optional[float] = None
        self.last_value = 0.0
        self.last_detail: dict = {}
        self.clear_since: Optional[float] = None
        self.fired_count = 0
        self.last_transition: Optional[float] = None


class AlertManager:
    """Evaluates a rule set against merged series and tracks transitions."""

    def __init__(self, rules: Optional[list] = None):
        self._lock = threading.Lock()
        self.rules = list(rules) if rules is not None else _slo.default_rules()
        self._states: dict[str, _RuleState] = {r.name: _RuleState() for r in self.rules}

    def set_rules(self, rules: list) -> None:
        with self._lock:
            self.rules = list(rules)
            for r in self.rules:
                self._states.setdefault(r.name, _RuleState())

    def evaluate(self, merged: dict, now: Optional[float] = None) -> list[dict]:
        """One pass over every rule. Returns the transitions that happened
        (``[{"rule", "to", "value"}...]``); each is also recorded as an
        ``alert.*`` flight-recorder event in this (the head's) process."""
        now = time.time() if now is None else now
        transitions = []
        with self._lock:
            for rule in self.rules:
                st = self._states[rule.name]
                try:
                    res = _slo.evaluate_rule(rule, merged, now)
                except Exception as e:  # a broken rule must not kill the rest
                    res = {"breached": False, "value": 0.0,
                           "detail": {"error": repr(e)}}
                st.last_value = float(res.get("value", 0.0))
                st.last_detail = dict(res.get("detail") or {})
                if res["breached"]:
                    st.clear_since = None
                    if st.status != FIRING:
                        st.status = FIRING
                        st.since = now
                        st.fired_count += 1
                        st.last_transition = now
                        transitions.append(
                            {"rule": rule.name, "to": FIRING, "value": st.last_value}
                        )
                        _events.record(
                            "alert.fire", rule=rule.name, value=st.last_value,
                            labels=dict(rule.labels), metric=rule.metric,
                            **{k: v for k, v in st.last_detail.items()
                               if isinstance(v, (int, float))},
                        )
                elif st.status == FIRING:
                    if st.clear_since is None:
                        st.clear_since = now
                    if now - st.clear_since >= rule.resolve_after_s:
                        st.status = RESOLVED
                        st.last_transition = now
                        transitions.append(
                            {"rule": rule.name, "to": RESOLVED, "value": st.last_value}
                        )
                        _events.record(
                            "alert.resolve", rule=rule.name, value=st.last_value,
                            firing_s=round(now - (st.since or now), 3),
                        )
        return transitions

    def state(self) -> list[dict]:
        """Per-rule view for ``obs alerts`` / ``/api/alerts``."""
        with self._lock:
            out = []
            for rule in self.rules:
                st = self._states[rule.name]
                out.append(
                    {
                        "rule": rule.name,
                        "metric": rule.metric,
                        "kind": rule.kind,
                        "status": st.status,
                        "value": st.last_value,
                        "detail": st.last_detail,
                        "since": st.since,
                        "fired_count": st.fired_count,
                        "labels": dict(rule.labels),
                        "description": rule.description,
                    }
                )
            return out

    def firing(self) -> list[dict]:
        return [a for a in self.state() if a["status"] == FIRING]
