"""Per-node metrics + on-demand worker stack dumps.

Reference: ``dashboard/modules/reporter/reporter_agent.py`` (per-node
psutil stats shipped to the dashboard) and ``profile_manager.py:61-97``
(on-demand py-spy stack dumps of stuck workers). TPU-first shape, no agent
daemon:

* node stats are read straight from ``/proc`` (cpu/mem/disk — psutil-free)
  by the head for its host and by each node agent for theirs, shipped on
  the existing control conns and served from the head's node table;
* stack dumps use ``faulthandler.register(SIGUSR1)``: every worker arms a
  C-level signal handler at startup that writes ALL thread stacks to a
  per-pid file — it fires even when the GIL is held or the interpreter is
  wedged mid-syscall, which is exactly the py-spy property that matters
  for debugging a stuck worker (a cooperative RPC would just hang with
  it). The head signals its local workers directly; agents signal theirs.
"""

from __future__ import annotations

import os
import signal
import time
from typing import Optional

STACKS_DIR = "/tmp/ray_tpu_stacks"


# -- worker side -------------------------------------------------------------


def arm_stack_dumps() -> Optional[str]:
    """Arm SIGUSR1 → all-thread stack dump into this process's stack file.
    Called once at worker startup; safe to call anywhere."""
    import atexit
    import faulthandler

    try:
        os.makedirs(STACKS_DIR, exist_ok=True)
        path = os.path.join(STACKS_DIR, f"{os.getpid()}.stacks")
        f = open(path, "w")  # held open for the process lifetime (signal-safe fd)
        try:
            faulthandler.register(signal.SIGUSR1, file=f, all_threads=True)
        except BaseException:
            f.close()  # a failed arm must not leak the fd (RL016)
            raise
        atexit.register(_unlink_quiet, path)  # crash-killed workers are
        # reaped by their spawner (head death path / agent proc sweep)
        return path
    except (OSError, ValueError, AttributeError):
        return None  # non-posix / restricted env: dumps unavailable


def _unlink_quiet(path: str) -> None:
    try:
        os.unlink(path)
    except OSError:
        pass


def reap_stack_file(pid: int) -> None:
    """Spawner-side cleanup for a dead worker's stack file."""
    _unlink_quiet(os.path.join(STACKS_DIR, f"{pid}.stacks"))


def dump_pids(pids: list[int], timeout: float = 2.0) -> dict[int, str]:
    """Signal each pid and collect its stack file (LAST dump). Used by the
    head for local workers and by node agents for theirs."""
    marks: dict[int, Optional[int]] = {}
    out: dict[int, str] = {}
    for pid in pids:
        path = os.path.join(STACKS_DIR, f"{pid}.stacks")
        if not os.path.exists(path):
            # NEVER signal a process that has not armed the handler: the
            # default SIGUSR1 disposition TERMINATES it (a worker still
            # importing, or a restricted env where arming failed)
            out[pid] = "<stack handler not armed>"
            marks[pid] = None
            continue
        marks[pid] = os.path.getsize(path)
        try:
            os.kill(pid, signal.SIGUSR1)
        except (OSError, ProcessLookupError):
            out[pid] = "<process gone>"
            marks[pid] = None
    deadline = time.monotonic() + timeout
    pending = {p for p, m in marks.items() if m is not None}
    last_size = {p: marks[p] for p in pending}
    while pending and time.monotonic() < deadline:
        for pid in list(pending):
            path = os.path.join(STACKS_DIR, f"{pid}.stacks")
            try:
                size = os.path.getsize(path)
            except OSError:
                pending.discard(pid)
                continue
            # the handler writes the dump as many small writes: only read
            # once the size has grown AND been stable for one poll, or a
            # loaded host returns a dump missing its later threads
            if size > marks[pid] and size == last_size[pid]:
                with open(path) as f:
                    f.seek(marks[pid])
                    out[pid] = f.read()
                pending.discard(pid)
            last_size[pid] = size
        if pending:
            time.sleep(0.05)
    for pid in pending:
        out.setdefault(pid, "<no dump within timeout>")
    return out


# -- node stats --------------------------------------------------------------

_last_cpu: Optional[tuple] = None


def node_stats() -> dict:
    """One /proc sample: cpu percent (since the previous call), memory,
    disk of the tmp filesystem, load average."""
    global _last_cpu
    stats: dict = {"time": time.time(), "pid": os.getpid()}
    try:
        with open("/proc/stat") as f:
            parts = f.readline().split()[1:8]
        vals = [int(x) for x in parts]
        idle, total = vals[3] + vals[4], sum(vals)
        if _last_cpu is not None:
            didle, dtotal = idle - _last_cpu[0], total - _last_cpu[1]
            stats["cpu_percent"] = round(100.0 * (1 - didle / dtotal), 1) if dtotal else 0.0
        _last_cpu = (idle, total)
    except (OSError, ValueError, ZeroDivisionError):
        pass
    try:
        info = {}
        with open("/proc/meminfo") as f:
            for line in f:
                k, v = line.split()[:2]
                info[k.rstrip(":")] = int(v)
        stats["mem_total_kb"] = info.get("MemTotal", 0)
        stats["mem_available_kb"] = info.get("MemAvailable", 0)
        if info.get("MemTotal"):
            stats["mem_percent"] = round(
                100.0 * (1 - info.get("MemAvailable", 0) / info["MemTotal"]), 1
            )
    except (OSError, ValueError):
        pass
    try:
        st = os.statvfs("/tmp")
        stats["disk_free_bytes"] = st.f_bavail * st.f_frsize
        stats["disk_total_bytes"] = st.f_blocks * st.f_frsize
    except OSError:
        pass
    try:
        stats["load_avg_1m"] = os.getloadavg()[0]
    except OSError:
        pass
    return stats


# ---------------------------------------------------------------------------
# on-demand sampling CPU profiler (reference: the dashboard's py-spy
# ``/worker/cpu_profile`` endpoint — dashboard/modules/reporter spawns
# ``py-spy record`` against a worker pid). TPU-native take: no subprocess
# and no ptrace needed — the worker samples ITSELF from a daemon thread via
# sys._current_frames(), emitting Brendan-Gregg collapsed-stack lines that
# flamegraph.pl / speedscope ingest directly. ptrace-free matters in
# containers (CAP_SYS_PTRACE is usually dropped); the trade-off is that a
# fully wedged interpreter can't self-sample — that case is covered by the
# SIGUSR1 faulthandler dumps above, which are C-level.
# ---------------------------------------------------------------------------


def sample_profile(duration_s: float = 2.0, interval_s: float = 0.01) -> str:
    """Sample every thread's Python stack for ``duration_s``; returns
    collapsed-stack text (``frame;frame;frame count`` per line, hottest
    first). Frames render as ``file.py:function``."""
    import sys
    import threading

    counts: dict[str, int] = {}
    me = threading.get_ident()
    end = time.monotonic() + max(0.05, duration_s)
    interval_s = max(0.001, interval_s)
    while time.monotonic() < end:
        for tid, frame in sys._current_frames().items():
            if tid == me:
                continue  # never profile the profiler
            parts: list[str] = []
            f = frame
            while f is not None:
                code = f.f_code
                parts.append(f"{os.path.basename(code.co_filename)}:{code.co_name}")
                f = f.f_back
            key = ";".join(reversed(parts))
            counts[key] = counts.get(key, 0) + 1
        time.sleep(interval_s)
    lines = [f"{k} {v}" for k, v in sorted(counts.items(), key=lambda kv: -kv[1])]
    return "\n".join(lines)
