"""Per-process runtime context: driver or worker.

TPU-native counterpart of the reference's core worker (``src/ray/core_worker/
core_worker.h:290`` + the Cython bridge ``python/ray/_raylet.pyx``): every
process participating in the cluster holds exactly one context object through
which ``put/get/wait/submit_task/create_actor/...`` flow. The driver context
calls the in-process Head directly; worker contexts speak the same method
names over the unix-socket control plane, so the API layer above is written
once.
"""

from __future__ import annotations

import hashlib
import itertools
import os
import queue
import threading
import time
from typing import Any, Optional

from ray_tpu import exceptions as rex
from ray_tpu._private import serialization as ser
from ray_tpu._private.config import GLOBAL_CONFIG
from ray_tpu._private.ids import ObjectID, TaskID
from ray_tpu._private.log_util import warn_throttled
from ray_tpu._private.shm_store import ShmReader

_ctx: Optional["BaseContext"] = None
_ctx_lock = threading.Lock()


def get_ctx() -> "BaseContext":
    if _ctx is None:
        raise rex.RayError("ray_tpu.init() has not been called in this process")
    return _ctx


def set_ctx(ctx: Optional["BaseContext"]):
    global _ctx
    _ctx = ctx


def is_initialized() -> bool:
    return _ctx is not None


# --------------------------------------------------------------------------


class ObjectRef:
    """Handle to a (possibly pending) object (reference: ObjectRef /
    ``ObjectID`` + distributed refcount in ``reference_count.h``).

    GC model: every live ObjectRef instance — including ones that crossed a
    serialization boundary — holds one count at the owner, released on GC.
    Serialization uses a borrow protocol (``reference_count.h:61-115``
    borrower bookkeeping, simplified): ``__reduce__`` takes a nonce-tagged
    transit count (``borrow_begin``); the first deserialization claims it
    (``borrow_claim`` — no double count), later deserializations of the same
    pickle (e.g. a retried task's args) each add their own. A serialized ref
    that is never deserialized leaks its transit count — bounded by dropped
    messages, vs. the reference's full borrower-death tracking.
    """

    __slots__ = ("_id", "_owned", "__weakref__")

    def __init__(self, id_bytes: bytes, owned: bool = False):
        self._id = id_bytes
        self._owned = owned

    def binary(self) -> bytes:
        return self._id

    def hex(self) -> str:
        return self._id.hex()

    def __hash__(self):
        return hash(self._id)

    def __eq__(self, other):
        return isinstance(other, ObjectRef) and other._id == self._id

    def __repr__(self):
        return f"ObjectRef({self._id.hex()})"

    def __del__(self):
        # GC-safety: __del__ can fire at ANY allocation point, including in a
        # thread that holds (or is awaited by a holder of) the head lock or a
        # connection send lock. The only safe operation here is a reentrant
        # SimpleQueue.put; a dedicated drain thread performs the real
        # decrement (reference: reference_count.h posts decrements to the
        # io_context for the same reason — never block in a destructor).
        if self._owned and _ctx is not None and not _ctx.closed:
            try:
                _ctx.enqueue_gc("call", ("free_ref_async", {"obj_id": self._id}))
            except Exception:
                pass

    def __reduce__(self):
        nonce = None
        if _ctx is not None and not _ctx.closed:
            try:
                import os as _os

                nonce = _os.urandom(8)
                _ctx.call("borrow_begin", obj_id=self._id, nonce=nonce)
            except Exception:
                nonce = None
        return (_deserialized_ref, (self._id, nonce))

    def future(self):
        """concurrent.futures.Future view of this ref."""
        import concurrent.futures

        fut: concurrent.futures.Future = concurrent.futures.Future()

        def _poll():
            try:
                fut.set_result(get_ctx().get([self], timeout=None)[0])
            except BaseException as e:  # noqa: BLE001
                fut.set_exception(e)

        threading.Thread(target=_poll, daemon=True).start()
        return fut


def _deserialized_ref(id_bytes: bytes, nonce: bytes = None) -> ObjectRef:
    if nonce is None:
        return ObjectRef(id_bytes, owned=False)  # pre-borrow pickles / no ctx
    ref = ObjectRef(id_bytes, owned=True)  # this holder releases on GC
    if _ctx is not None and not _ctx.closed:
        try:
            _ctx.call("borrow_claim", obj_id=id_bytes, nonce=nonce)
        except Exception:
            ref._owned = False
    else:
        ref._owned = False
    return ref


# --------------------------------------------------------------------------


class ObjectRefGenerator:
    """Iterator over a streaming task's per-item ObjectRefs
    (``num_returns="streaming"``; reference: ``ObjectRefGenerator`` in
    _raylet.pyx:1230 + streaming bookkeeping in task_manager.cc).

    Each ``next()`` blocks until the producer has yielded that item, then
    returns an owned ObjectRef resolving to the yielded value — items arrive
    while the task is still running, with a consumer-acked backpressure
    window on the producer. A mid-stream producer exception is raised from
    ``next()`` once the already-produced items are drained. Dropping the
    generator cancels a still-running producer and frees unconsumed items.
    """

    def __init__(self, task_id: bytes, completion_ref: "ObjectRef", ctx):
        self._task_id = task_id
        self._completion_ref = completion_ref  # holds the error carrier alive
        self._ctx = ctx
        self._i = 0
        self._done = False
        self._disposed = False

    def __iter__(self):
        return self

    def __next__(self) -> "ObjectRef":
        return self._next(timeout=None)

    def _next(self, timeout: Optional[float]) -> "ObjectRef":
        if self._done or self._disposed:
            raise StopIteration
        kind, payload = self._ctx.call(
            "stream_next", task_id=self._task_id, index=self._i, timeout=timeout
        )
        if kind == "end":
            self._done = True
            raise StopIteration
        if kind == "error":
            self._done = True
            # the completion object carries the producer's exception;
            # resolving it raises with proper cause chaining
            self._ctx.get([ObjectRef(payload)], timeout=30)
            raise rex.RayError("stream failed but completion held no error")
        self._i += 1
        return ObjectRef(payload, owned=True)

    def close(self) -> None:
        self._dispose(blocking=True)

    def _dispose(self, blocking: bool) -> None:
        """Single dispose path: explicit close() blocks; the GC path may only
        enqueue (a blocking RPC from a GC tick can deadlock against a thread
        holding the head lock — see ObjectRef.__del__)."""
        if self._disposed:
            return
        self._disposed = True
        try:
            if blocking:
                self._ctx.call("stream_dispose", task_id=self._task_id)
            elif not self._ctx.closed:
                self._ctx.enqueue_gc(
                    "call", ("stream_dispose", {"task_id": self._task_id})
                )
        except Exception:
            pass

    def __del__(self):
        self._dispose(blocking=False)

    def __repr__(self):
        return f"ObjectRefGenerator({self._task_id.hex()[:8]}, next={self._i})"


class BaseContext:
    def __init__(self):
        self.closed = False
        self.remote = False  # True = different host than the head (no shm)
        # test hook, read once per context (not per get): skip the same-host
        # shm shortcut so same-machine tests exercise the real network path
        self._force_dp = os.environ.get("RAY_TPU_FORCE_DATA_PLANE") == "1"
        self.authkey: Optional[bytes] = None  # data-plane auth (set by subclasses)
        self.head_host: str = "127.0.0.1"  # host we reach the control plane on
        self._data_addrs: dict = {}  # node bin -> (host, port) cache
        self._uploaded_funcs: set[bytes] = set()
        self._readers: dict[bytes, ShmReader] = {}
        self._readers_lock = threading.Lock()
        # task-id source (see new_task_returns): nonce drawn once per context
        self._task_nonce = os.urandom(6)
        self._task_seq = itertools.count(1)
        self.current_actor = None  # set in actor workers
        self.node_id_bin: Optional[bytes] = None
        self.task_depth = 0
        # named-actor namespace this context creates/looks up in ("default"
        # for local drivers and workers; ray:// clients get their session's
        # — usually anonymous — namespace from the driver_ack handshake)
        self.namespace: str = "default"
        # pubsub: channel -> local callbacks fed by head "pub" pushes
        # (reference: src/ray/pubsub subscriber channels)
        self._pub_sinks: dict[str, list] = {}
        self._pub_lock = threading.Lock()
        # GC drain: __del__ methods (ObjectRef, generators, actor handles,
        # compiled DAGs) may ONLY touch this queue — SimpleQueue.put is
        # C-implemented and reentrant-safe, so a GC tick inside a lock-held
        # critical section can never re-enter head/connection locks. The
        # drain thread performs the real (possibly blocking) calls.
        self._gc_q: "queue.SimpleQueue" = queue.SimpleQueue()
        self._thunk_threads: list[threading.Thread] = []
        self._gc_thread = threading.Thread(
            target=self._gc_drain_loop, name="gc-drain", daemon=True
        )
        self._gc_thread.start()

    def enqueue_gc(self, kind: str, payload) -> None:
        """The ONLY operation a __del__ may perform against the runtime.
        kind: "call" -> (method, kwargs) executed via self.call;
        "thunk" -> zero-arg callable run on the drain thread."""
        self._gc_q.put((kind, payload))

    def _gc_drain_loop(self) -> None:
        while True:
            item = self._gc_q.get()
            if item is None:
                return
            if self.closed:
                continue  # keep draining so shutdown's sentinel is reached
            kind, payload = item
            try:
                if kind == "call":
                    method, kwargs = payload
                    self.call(method, **kwargs)
                elif kind == "thunk":
                    # thunks may block for seconds (e.g. CompiledDAG teardown
                    # joins its exec loops): run off-thread so queued ref
                    # frees aren't stalled behind them; tracked so shutdown's
                    # drain can join them (they unlink shm channels)
                    try:
                        t = threading.Thread(target=payload, daemon=True)
                        self._thunk_threads = [
                            x for x in self._thunk_threads if x.is_alive()
                        ]
                        self._thunk_threads.append(t)
                        t.start()
                    except RuntimeError:
                        payload()
            except Exception as e:
                # best-effort: the process may be tearing down
                warn_throttled("gc drain loop", e)

    # -- transport: subclasses implement call() --------------------------------
    def call(self, method: str, **payload) -> Any:
        raise NotImplementedError

    # -- pubsub ------------------------------------------------------------
    def on_pub(self, channel: str, payload) -> None:
        with self._pub_lock:
            sinks = list(self._pub_sinks.get(channel, ()))
        for fn in sinks:
            try:
                fn(channel, payload)
            except Exception as e:
                warn_throttled(f"pubsub callback on {channel}", e)

    def pub_register(self, channel: str, fn) -> None:
        with self._pub_lock:
            first = not self._pub_sinks.get(channel)  # missing OR emptied
            self._pub_sinks.setdefault(channel, []).append(fn)
        if first:
            self.call("subscribe", channel=channel)

    def pub_unregister(self, channel: str, fn) -> None:
        with self._pub_lock:
            sinks = self._pub_sinks.get(channel, [])
            if fn in sinks:
                sinks.remove(fn)
            empty = not sinks
        if empty:
            try:
                self.call("unsubscribe", channel=channel)
            except Exception:
                pass

    # -- objects ----------------------------------------------------------
    def put(self, value: Any) -> ObjectRef:
        if isinstance(value, ObjectRef):
            raise TypeError("Calling put() on an ObjectRef is not allowed.")
        sv = ser.serialize(value)
        # take_ref: the returned ObjectRef holds one refcount, taken inside
        # the put itself (one head round trip, not put + add_ref — without
        # the count, a single use as a task arg would unpin and evict).
        obj_id = self.put_serialized(sv, take_ref=True)
        return ObjectRef(obj_id, owned=True)

    def put_serialized(
        self, sv: ser.SerializedValue, is_error=False, take_ref=False
    ) -> bytes:
        raise NotImplementedError

    def get(self, refs: list[ObjectRef], timeout: Optional[float]) -> list[Any]:
        locators = self.call("get", obj_ids=[r.binary() for r in refs], timeout=timeout)
        out = []
        for r, loc in zip(refs, locators):
            value = self._materialize(r.binary(), loc)
            kind, payload, is_err = loc
            if is_err:
                if isinstance(value, rex.RayTaskError):
                    raise value.as_instanceof_cause()
                raise value
            out.append(value)
        return out

    def store_value(self, sv: "ser.SerializedValue", is_error: bool = False):
        """Locator for a freshly serialized value. Large payloads go into
        THIS host's shared memory (arena or dedicated segment) and only the
        locator travels — on agent hosts the bytes are then served
        peer-to-peer by the agent's data server (data_plane.py). A remote
        process without a local store (a ``ray://`` driver) ships inline."""
        from ray_tpu._private.shm_store import _current_write_arena, write_shm

        if sv.total_size <= GLOBAL_CONFIG.max_direct_call_object_size:
            return ("inline", sv.to_bytes(), is_error)
        if self.remote:
            arena = _current_write_arena()
            if arena is None:
                # no host-local store to serve from (remote driver, or agent
                # without the native arena): the head re-lays these into its
                # shm and its spill watermark owns the lifetime
                return ("inline", sv.to_bytes(), is_error)
            if (
                sv.total_size <= GLOBAL_CONFIG.arena_max_object_bytes
                and arena.used + sv.total_size > 0.9 * arena.capacity
            ):
                # agent arena under pressure: agents have no spill of their
                # own (the head owns object lifetimes), so degrade to the
                # head-mediated path where the spill machinery applies
                # instead of running the agent host out of /dev/shm
                return ("inline", sv.to_bytes(), is_error)
        loc = write_shm(sv)
        loc.node = self.node_id_bin
        return ("shm", loc, is_error)

    def _data_address_for(self, node_bin) -> Optional[tuple]:
        cached = self._data_addrs.get(node_bin)
        now = time.monotonic()
        if cached is not None and (cached[0] is not None or now < cached[1]):
            addr = cached[0]
        else:
            try:
                addr = self.call("data_address", node_id=node_bin)
            except Exception:
                addr = None
            # a negative result is transient (control hiccup, node still
            # registering): cache it briefly only, or one bad lookup would
            # disable the data plane for this node forever
            self._data_addrs[node_bin] = (
                addr, now + GLOBAL_CONFIG.object_location_negative_cache_s
            )
        if addr is None:
            return None
        host, port = addr
        return (host or self.head_host, port)

    def _fetch_via_data_plane(self, obj_id: bytes, payload):
        """Pull an object's bytes straight from its owning host (reference:
        pull_manager.cc chunked pulls). Returns (True, value) or (False,
        None) when the object is gone / the data plane can't serve it —
        callers then run the lost-object recovery path."""
        from ray_tpu._private import data_plane

        if self.authkey is None:
            return False, None
        addr = self._data_address_for(payload.node)
        if addr is None:
            return False, None
        try:
            mv = data_plane.fetch(addr, self.authkey, payload)
        except data_plane.ObjectGone:
            return False, None
        except OSError:
            # owner unreachable (died? network?): drop the cached address
            # and try the head-mediated inline fallback before declaring loss
            self._data_addrs.pop(payload.node, None)
            try:
                loc = self.call("get_inline", obj_ids=[obj_id], timeout=0)[0]
            except Exception:
                return False, None
            if loc[0] == "inline":
                return True, ser.deserialize_value(
                    ser.SerializedValue.from_bytes(loc[1])
                )
            return False, None
        return True, data_plane.read_layout(mv, payload)

    def _materialize(self, obj_id: bytes, locator, _retry: bool = True):
        kind, payload, is_err = locator
        if kind == "inline":
            return ser.deserialize_value(ser.SerializedValue.from_bytes(payload))
        force_dp = (
            self._force_dp
            and payload.node is not None
            and payload.node != self.node_id_bin
        )
        reader = None
        if not force_dp:
            with self._readers_lock:
                reader = self._readers.get(obj_id)
                if reader is None:
                    try:
                        # local-first: on the owning host (or any same-host
                        # simulated node) the shm attaches by name, zero-copy
                        reader = ShmReader(payload)
                    except FileNotFoundError:
                        # not on this host — or spilled/unlinked under us
                        reader = None
        if reader is None:
            # the data plane must get its shot even on the recovery retry:
            # a lineage rebuild can land the fresh copy on a REMOTE host
            ok, value = self._fetch_via_data_plane(obj_id, payload)
            if ok:
                return value
            if not _retry:
                raise FileNotFoundError(f"object {obj_id.hex()} unavailable")
        if reader is None:
            # tell the head the backing is gone so it can restore from spill
            # or rebuild via lineage (reference: object recovery manager),
            # then block in get until a fresh copy lands
            try:
                self.call("report_lost", obj_ids=[obj_id])
            except Exception:
                pass
            fresh = self.call("get", obj_ids=[obj_id], timeout=None)[0]
            value = self._materialize(obj_id, fresh, _retry=False)
            if fresh[2]:
                # the object resolved to an error AFTER the caller already
                # checked its (stale) locator — raise here, matching the
                # caller-side error semantics
                if isinstance(value, rex.RayTaskError):
                    raise value.as_instanceof_cause()
                raise value
            return value
        value = reader.read()
        self._sweep_readers()
        return value

    def _sweep_readers(self, limit: int = 256):
        if len(self._readers) <= limit:
            return
        with self._readers_lock:
            for oid in list(self._readers)[: len(self._readers) - limit]:
                self._readers.pop(oid).close()

    def wait(self, refs, num_returns, timeout, fetch_local=True):
        ids = [r.binary() for r in refs]
        ready_ids = set(self.call("wait", obj_ids=ids, num_returns=num_returns, timeout=timeout))
        ready, not_ready = [], []
        for r in refs:
            (ready if r.binary() in ready_ids and len(ready) < num_returns else not_ready).append(r)
        return ready, not_ready

    # -- functions --------------------------------------------------------
    def upload_function(self, blob: bytes) -> bytes:
        func_id = hashlib.sha1(blob).digest()[:16]
        if func_id not in self._uploaded_funcs:
            self.call("put_function", func_id=func_id, blob=blob)
            self._uploaded_funcs.add(func_id)
        return func_id

    # -- spec building ----------------------------------------------------
    def serialize_args(self, args, kwargs):
        def one(v):
            if isinstance(v, ObjectRef):
                return ("r", v.binary())
            sv = ser.serialize(v)
            if sv.total_size > GLOBAL_CONFIG.max_direct_call_object_size:
                # big by-value arg: implicit put (reference: dependency
                # resolver promotes >100KB args to plasma)
                return ("r", self.put_serialized(sv))
            return ("v", sv.to_bytes())

        return [one(a) for a in args], {k: one(v) for k, v in kwargs.items()}

    def submit_task(self, spec: dict) -> list[ObjectRef]:
        # the head takes the submitter's refs on the return ids inside
        # submit_task itself — one round trip, not 1 + num_returns
        refs = [ObjectRef(rid, owned=True) for rid in spec["return_ids"]]
        wf = spec.get("wf")
        if wf is not None:
            # deferred import (util package ↔ runtime cycle); only the
            # sampled-and-stamped path pays the sys.modules lookup
            from ray_tpu.util import waterfall as _waterfall

            _waterfall.stamp(wf)  # socket_write: the submit RPC begins
        self.call("submit_task", spec=spec)
        return refs

    def submit_actor_task(self, spec: dict) -> list[ObjectRef]:
        refs = [ObjectRef(rid, owned=True) for rid in spec["return_ids"]]
        wf = spec.get("wf")
        if wf is not None:
            from ray_tpu.util import waterfall as _waterfall

            _waterfall.stamp(wf)  # socket_write: the submit RPC begins
        self.call("submit_actor_task", spec=spec)
        return refs

    def new_task_returns(self, num_returns: int):
        # Task ids end in 4 zero bytes so a return ObjectID's 12-byte prefix
        # uniquely reconstructs its task id (used by ray_tpu.cancel()).
        # 6-byte per-process nonce + 6-byte counter instead of a per-task
        # urandom syscall: uniqueness across submitters comes from the nonce
        # (48 bits — birthday-safe for any realistic process count), and the
        # counter never wraps in practice (2^48 submissions).
        prefix = self._task_nonce + next(self._task_seq).to_bytes(6, "big")
        # raw bytes on purpose: this runs once per .remote() and the
        # TaskID/ObjectID wrappers would be built only to call .binary()
        # (layout must match ObjectID.for_task_return: prefix + LE index)
        return prefix + b"\x00\x00\x00\x00", [
            prefix + i.to_bytes(4, "little") for i in range(num_returns)
        ]

    def shutdown(self):
        # drain already-queued GC work (ref frees, stream disposes, DAG
        # teardowns) while the control plane is still up, THEN mark closed —
        # the reverse order would silently discard them. Bounded join: a
        # drain item wedged on a dying head must not hang shutdown.
        self._gc_q.put(None)
        if threading.current_thread() is not self._gc_thread:
            self._gc_thread.join(timeout=5.0)
        for t in self._thunk_threads:  # DAG teardowns must finish their
            if t is not threading.current_thread():  # channel unlinks
                t.join(timeout=5.0)
        self.closed = True
        with self._readers_lock:
            for reader in self._readers.values():
                reader.close()
            self._readers.clear()


class DriverContext(BaseContext):
    """Runs in the driver process; owns the Head."""

    def __init__(self, head, node_id_bin: bytes):
        super().__init__()
        self.head = head
        self.node_id_bin = node_id_bin
        self.authkey = head.authkey

    def call(self, method: str, **payload):
        if method == "subscribe":
            return self.head.subscribe_local(payload["channel"], self.on_pub)
        if method == "unsubscribe":
            return self.head.unsubscribe_local(payload["channel"], self.on_pub)
        if method == "free_ref_async":
            # runs on the gc-drain thread (never from __del__ directly):
            # blocking on the head lock here is safe, and eviction may queue
            # agent sends that need flushing like any other in-process call
            try:
                return self.head.remove_ref(payload["obj_id"])
            finally:
                self.head.flush_outbox()
        if method == "add_ref":
            return self.head.add_ref(payload["obj_id"])
        if method == "get":
            return self.head.get_locators(payload["obj_ids"], payload.get("timeout"))
        if method == "wait":
            return self.head.wait_objects(payload["obj_ids"], payload["num_returns"], payload.get("timeout"))
        if method == "submit_task":  # hot path: skip the getattr dispatch
            try:
                return self.head.submit_task(payload["spec"])
            finally:
                self.head.flush_outbox()
        try:
            return getattr(self.head, "rpc_" + method)(**payload)
        finally:
            # in-process calls bypass _run_request: drain any worker sends
            # this call queued (head.flush_outbox docstring)
            self.head.flush_outbox()

    def put_serialized(self, sv, is_error=False, take_ref=False) -> bytes:
        try:
            return self.head.put_serialized(sv, is_error, take_ref=take_ref)
        finally:
            self.head.flush_outbox()


class WorkerContext(BaseContext):
    """Runs in worker processes; control plane over the head socket.

    ``remote=True`` marks a process on a DIFFERENT host than the head: all
    object payloads travel inline over the socket (the head's shm segments
    are unreachable), and the head converts in both directions.
    """

    def __init__(
        self,
        conn,
        node_id_bin: bytes,
        remote: bool = False,
        authkey: Optional[bytes] = None,
        head_host: Optional[str] = None,
    ):
        super().__init__()
        self.conn = conn
        self.node_id_bin = node_id_bin
        self.remote = remote
        self.authkey = authkey
        if head_host:
            self.head_host = head_host
        self._seq = itertools.count(1)
        self._send_lock = threading.Lock()
        self._pending: dict[int, list] = {}
        self._pending_lock = threading.Lock()

    # message pump (run by worker_main's receiver thread)
    def on_response(self, seq, ok, payload):
        with self._pending_lock:
            slot = self._pending.get(seq)
        if slot is not None:
            slot[1] = (ok, payload)
            slot[0].set()

    def call(self, method: str, **payload):
        if method == "free_ref_async":
            # fire-and-forget decrement; workers never block on GC
            try:
                self._send(("req", 0, "free_ref", {"obj_id": payload["obj_id"]}))
            except Exception:
                pass
            return None
        seq = next(self._seq)
        ev = threading.Event()
        # slot[2] records the conn this call actually went out on (set by
        # _send UNDER the send lock): after a reconnect swap, slots tied to
        # the OLD conn are failed retriably — a send into a dying socket
        # can land in the kernel buffer without error, and without this the
        # caller would wait forever for a reply the head never saw
        slot = [ev, None, None]
        with self._pending_lock:
            self._pending[seq] = slot
        try:
            self._send(("req", seq, method, payload), slot=slot)
        except Exception as e:
            # reap the slot (seqs never repeat — a leaked slot lives
            # forever) and surface a retriable error: send failures are
            # ROUTINE during a client reconnect window
            with self._pending_lock:
                self._pending.pop(seq, None)
            raise rex.RayError(
                f"connection to the cluster lost while sending {method!r}; "
                f"retry the call ({e})"
            ) from e
        ev.wait()
        with self._pending_lock:
            self._pending.pop(seq, None)
        ok, result = slot[1]
        if not ok:
            raise result
        return result

    def _send(self, msg, slot=None):
        with self._send_lock:
            if slot is not None:
                if slot[1] is not None:
                    # a reconnect sweep failed this call BEFORE its send:
                    # transmitting now would execute a request whose caller
                    # was already told "retry" (double-submit). Surface the
                    # recorded error instead.
                    ok, err = slot[1]
                    if not ok:
                        raise err
                slot[2] = self.conn  # the conn the bytes actually ride
            self.conn.send(msg)

    def send_raw(self, msg):
        self._send(msg)

    def put_serialized(self, sv, is_error=False, take_ref=False) -> bytes:
        obj_id = ObjectID.for_put().binary()
        kind, payload, err = self.store_value(sv, is_error)
        small, shm = (payload, None) if kind == "inline" else (None, payload)
        self.call(
            "put", obj_id=obj_id, small=small, shm=shm, is_error=err,
            take_ref=take_ref,
        )
        return obj_id


class RemoteDriverContext(WorkerContext):
    """A driver attached to a head in ANOTHER process/host over TCP
    (reference: ``ray.init(address=...)`` connecting to a running cluster;
    with a session token this is the ``ray://`` client protocol —
    reference ``util/client/``). Same RPC surface as a worker, plus its own
    response pump (workers get theirs from worker_main's recv loop).

    Reconnect-with-resume: on connection loss the pump redials the head
    presenting ``session_token`` for up to the reconnect grace. The head
    resumes the session (same namespace, refs intact — ClientSession in
    head.py); calls in flight AT the drop fail with a retriable RayError
    (resending them blindly could double-submit tasks), later calls ride
    the new connection transparently."""

    def __init__(
        self,
        conn,
        node_id_bin: bytes,
        authkey: Optional[bytes] = None,
        head_host: Optional[str] = None,
        address: Optional[str] = None,
        session_token: Optional[str] = None,
    ):
        super().__init__(conn, node_id_bin, remote=True, authkey=authkey, head_host=head_host)
        self.address = address
        self.session_token = session_token
        self._pump = threading.Thread(
            target=self._pump_loop, name="driver-pump", daemon=True
        )
        self._pump.start()

    def _fail_pending(self, not_on=None):
        """Fail pending calls retriably. ``not_on``: spare slots already
        sent on that (fresh) connection — used by the post-reconnect sweep
        so a call that raced onto the new conn keeps waiting for its real
        reply.

        The whole sweep holds ``_send_lock``: collection reads slot[2] and
        writes slot[1], which _send's pre-send guard reads/writes under the
        same lock — without it, a caller could pass the guard while the
        sweep dooms its (unsent) slot, then transmit a request whose caller
        was told to retry (double-submit)."""
        with self._send_lock:
            with self._pending_lock:
                doomed = [
                    (seq, s)
                    for seq, s in self._pending.items()
                    if not_on is None or s[2] is not not_on
                ]
                for seq, _ in doomed:
                    self._pending.pop(seq, None)
            for _seq, slot in doomed:
                slot[1] = (
                    False,
                    rex.RayError(
                        "connection to the cluster was lost mid-call; the "
                        "session was resumed — retry the call"
                    ),
                )
        for _seq, slot in doomed:
            slot[0].set()

    def _try_reconnect(self) -> bool:
        if self.address is None or self.session_token is None:
            return False
        import time as _time

        from ray_tpu._private.config import GLOBAL_CONFIG
        from ray_tpu._private.worker_main import connect_head

        deadline = _time.monotonic() + GLOBAL_CONFIG.client_reconnect_grace_s
        while _time.monotonic() < deadline and not self.closed:
            try:
                conn = connect_head(self.address, self.authkey, retries=1)
                conn.send(
                    ("register_driver", {"session_token": self.session_token})
                )
                kind, info = conn.recv()
                if kind != "driver_ack" or info.get("session_token") != self.session_token:
                    raise OSError("session not resumed")
                with self._send_lock:
                    self.conn = conn
                # calls that raced into the dying socket's kernel buffer
                # produced no error yet got no reply: fail everything not
                # already sent on the FRESH conn (they retry; a silent hang
                # would be the alternative)
                self._fail_pending(not_on=conn)
                # head-side pubsub routing died with the old conn: re-send
                # subscribes for every channel with live sinks. Raw seq-0
                # requests — a blocking call() here would deadlock (this IS
                # the pump thread that processes replies).
                with self._pub_lock:
                    channels = [c for c, sinks in self._pub_sinks.items() if sinks]
                for channel in channels:
                    try:
                        self._send(("req", 0, "subscribe", {"channel": channel}))
                    except Exception:
                        break  # fresh conn died already: next loop retries
                return True
            except Exception:
                _time.sleep(0.5)
        return False

    def _pump_loop(self):
        while not self.closed:
            try:
                msg = self.conn.recv()
            except (EOFError, OSError):
                # fail in-flight calls FIRST (they will never get replies;
                # failing after the swap could catch a call already sent on
                # the fresh connection), then redial with the session token
                self._fail_pending()
                if self.closed or not self._try_reconnect():
                    return
                continue
            if msg[0] == "resp":
                _, seq, ok, payload = msg
                self.on_response(seq, ok, payload)
            elif msg[0] == "pub":
                self.on_pub(msg[1], msg[2])

    def shutdown(self):
        super().shutdown()
        from ray_tpu._private.node_agent import shutdown_conn

        shutdown_conn(self.conn)  # interrupts the pump thread's recv too
