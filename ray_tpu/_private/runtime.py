"""Per-process runtime context: driver or worker.

TPU-native counterpart of the reference's core worker (``src/ray/core_worker/
core_worker.h:290`` + the Cython bridge ``python/ray/_raylet.pyx``): every
process participating in the cluster holds exactly one context object through
which ``put/get/wait/submit_task/create_actor/...`` flow. The driver context
calls the in-process Head directly; worker contexts speak the same method
names over the unix-socket control plane, so the API layer above is written
once.
"""

from __future__ import annotations

import hashlib
import itertools
import os
import queue
import threading
import time
from collections import deque
from typing import Any, Optional

from ray_tpu import exceptions as rex
from ray_tpu._private import events
from ray_tpu._private import serialization as ser
from ray_tpu._private.config import GLOBAL_CONFIG
from ray_tpu._private.ids import ObjectID, TaskID
from ray_tpu._private.log_util import warn_throttled
from ray_tpu._private.shm_store import ShmReader

_ctx: Optional["BaseContext"] = None
_ctx_lock = threading.Lock()

#: raylint RL012 registry — the submitter side of the pipelined task plane
#: (ISSUE 14): window credits left before a submit flush blocks for acks;
#: plus the zero-copy data plane (ISSUE 18): bytes written to / read from
#: shared memory by this process, and whether each shm read was served by a
#: same-host arena map (local hit) or a cross-host data-plane pull
METRIC_NAMES = (
    "core_submit_credits",
    "core_shm_put_bytes",
    "core_shm_get_bytes",
    "core_data_local_hits",
    "core_data_remote_pulls",
)

#: flight-recorder events this module emits (raylint RL012 registry) — the
#: consumer/producer half of the ``core.object.*`` lifecycle family
#: (ISSUE 19): a put entering the shm plane, a cross-host pull, and a ref
#: poisoned by window loss (its get will raise a retriable error).
EVENT_NAMES = (
    "core.object.put",
    "core.object.p2p_pull",
    "core.object.poison",
)

#: Canonical lock order of the client-side submit plane (PR 14), outermost
#: first — raylint RL010 checks every acquisition edge against it and
#: RL017 resolves these locks to their owners. ``_flush_submits`` is the
#: shape that fixes the order: the window is built under ``_submit_send``
#: (FIFO end to end) with ``_submit_cv`` taken inside it for buffer/credit
#: state, and the wire write happens under ``_send_lock`` with the cv
#: RELEASED (the recv thread must be able to process submit_acks while a
#: send blocks on a full socket — the PR 14 review-round deadlock).
LOCK_ORDER = (
    "WorkerContext._submit_send",   # window build+send serialization
    "WorkerContext._submit_cv",     # submit buffer / credit window state
    "WorkerContext._send_lock",     # one writer on the conn at a time
    "WorkerContext._pending_lock",  # blocking-call reply slots
)

_CREDIT_GAUGE = None
_DATA_COUNTERS = None

#: gc-queue wake sent by ObjectRef.__del__ on the free buffer's
#: empty→non-empty edge (one futex wake per quiescent burst, never per ref)
_FREE_TICK = object()

#: shared no-arg spec constants (see serialize_args): identity-elided
#: against spec headers so the steady-state no-arg body ships without them
EMPTY_ARGS: tuple = ()
EMPTY_KWARGS: dict = {}


def _credit_gauge():
    global _CREDIT_GAUGE
    if _CREDIT_GAUGE is None:
        from ray_tpu.util.metrics import Gauge

        _CREDIT_GAUGE = Gauge(
            "core_submit_credits",
            "remaining pipelined-submission window credits (tasks) in this process",
        )
    return _CREDIT_GAUGE


def _data_counters():
    """Data-plane counters (ISSUE 18), lazy like _credit_gauge: only
    processes that actually move shm bytes pay the metric objects. Returns
    (put_bytes, get_bytes, local_hits, remote_pulls)."""
    global _DATA_COUNTERS
    if _DATA_COUNTERS is None:
        from ray_tpu.util.metrics import Counter

        _DATA_COUNTERS = (
            Counter(
                "core_shm_put_bytes",
                "serialized bytes this process wrote into shared memory "
                "(locator-only socket traffic)",
            ),
            Counter(
                "core_shm_get_bytes",
                "serialized bytes this process read out of shared memory",
            ),
            Counter(
                "core_data_local_hits",
                "shm reads served zero-copy from a same-host arena/segment map",
            ),
            Counter(
                "core_data_remote_pulls",
                "shm reads that crossed hosts via the p2p data plane",
            ),
        )
    return _DATA_COUNTERS


def _split_for_wire(spec: dict, sent: set, hdrs_out: dict) -> dict:
    """Header-split one spec for a submit window (cheaper per-task bytes):
    static per-function fields already known to the receiver are elided
    (ser.split_spec_body), new headers ride the window's ``hdrs`` map
    exactly once per connection."""
    hdr = spec.get("_hdr")
    if hdr is None:
        return spec
    hid, fields = hdr
    body = ser.split_spec_body(spec, fields)
    body["_hdr_ref"] = hid
    if hid not in sent:
        sent.add(hid)
        hdrs_out[hid] = fields
    return body


def get_ctx() -> "BaseContext":
    if _ctx is None:
        raise rex.RayError("ray_tpu.init() has not been called in this process")
    return _ctx


def set_ctx(ctx: Optional["BaseContext"]):
    global _ctx
    _ctx = ctx


def is_initialized() -> bool:
    return _ctx is not None


# --------------------------------------------------------------------------


class ObjectRef:
    """Handle to a (possibly pending) object (reference: ObjectRef /
    ``ObjectID`` + distributed refcount in ``reference_count.h``).

    GC model: every live ObjectRef instance — including ones that crossed a
    serialization boundary — holds one count at the owner, released on GC.
    Serialization uses a borrow protocol (``reference_count.h:61-115``
    borrower bookkeeping, simplified): ``__reduce__`` takes a nonce-tagged
    transit count (``borrow_begin``); the first deserialization claims it
    (``borrow_claim`` — no double count), later deserializations of the same
    pickle (e.g. a retried task's args) each add their own. A serialized ref
    that is never deserialized leaks its transit count — bounded by dropped
    messages, vs. the reference's full borrower-death tracking.
    """

    __slots__ = ("_id", "_owned", "__weakref__")

    def __init__(self, id_bytes: bytes, owned: bool = False):
        self._id = id_bytes
        self._owned = owned

    def binary(self) -> bytes:
        return self._id

    def hex(self) -> str:
        return self._id.hex()

    def __hash__(self):
        return hash(self._id)

    def __eq__(self, other):
        return isinstance(other, ObjectRef) and other._id == self._id

    def __repr__(self):
        return f"ObjectRef({self._id.hex()})"

    def __del__(self):
        # GC-safety: __del__ can fire at ANY allocation point, including in a
        # thread that holds (or is awaited by a holder of) the head lock or a
        # connection send lock. The only safe operations here are a reentrant,
        # lock-free deque append and a reentrant SimpleQueue.put; the gc
        # drain thread ships the buffered ids as coalesced free batches
        # (reference: reference_count.h posts decrements to the io_context
        # for the same reason — never block in a destructor). Only the
        # empty→non-empty EDGE wakes the drain: at task rates one futex
        # wake per dead ref was a measurable share of the sync round trip,
        # and a busy drain coalesces every append that lands meanwhile.
        ctx = _ctx
        if self._owned and ctx is not None and not ctx.closed:
            if ctx._poisoned:
                # a poisoned (failed fire-and-forget) ref's error entry
                # lives exactly as long as the ref: dropping the last
                # handle drops the entry, so repeated reconnect storms
                # cannot grow the dict forever (dict.pop is reentrant-safe)
                ctx._poisoned.pop(self._id, None)
            buf = ctx._free_buf
            buf.append(self._id)
            if len(buf) == 1:
                try:
                    ctx._gc_q.put(_FREE_TICK)
                except Exception:
                    pass

    def __reduce__(self):
        nonce = None
        if _ctx is not None and not _ctx.closed:
            try:
                import os as _os

                nonce = _os.urandom(8)
                _ctx.call("borrow_begin", obj_id=self._id, nonce=nonce)
            except Exception:
                nonce = None
        return (_deserialized_ref, (self._id, nonce))

    def future(self):
        """concurrent.futures.Future view of this ref."""
        import concurrent.futures

        fut: concurrent.futures.Future = concurrent.futures.Future()

        def _poll():
            try:
                fut.set_result(get_ctx().get([self], timeout=None)[0])
            except BaseException as e:  # noqa: BLE001
                fut.set_exception(e)

        threading.Thread(target=_poll, daemon=True).start()
        return fut


def _deserialized_ref(id_bytes: bytes, nonce: bytes = None) -> ObjectRef:
    if nonce is None:
        return ObjectRef(id_bytes, owned=False)  # pre-borrow pickles / no ctx
    ref = ObjectRef(id_bytes, owned=True)  # this holder releases on GC
    if _ctx is not None and not _ctx.closed:
        try:
            _ctx.call("borrow_claim", obj_id=id_bytes, nonce=nonce)
        except Exception:
            ref._owned = False
    else:
        ref._owned = False
    return ref


# --------------------------------------------------------------------------


class ObjectRefGenerator:
    """Iterator over a streaming task's per-item ObjectRefs
    (``num_returns="streaming"``; reference: ``ObjectRefGenerator`` in
    _raylet.pyx:1230 + streaming bookkeeping in task_manager.cc).

    Each ``next()`` blocks until the producer has yielded that item, then
    returns an owned ObjectRef resolving to the yielded value — items arrive
    while the task is still running, with a consumer-acked backpressure
    window on the producer. A mid-stream producer exception is raised from
    ``next()`` once the already-produced items are drained. Dropping the
    generator cancels a still-running producer and frees unconsumed items.
    """

    def __init__(self, task_id: bytes, completion_ref: "ObjectRef", ctx):
        self._task_id = task_id
        self._completion_ref = completion_ref  # holds the error carrier alive
        self._ctx = ctx
        self._i = 0
        self._done = False
        self._disposed = False

    def __iter__(self):
        return self

    def __next__(self) -> "ObjectRef":
        return self._next(timeout=None)

    def _next(self, timeout: Optional[float]) -> "ObjectRef":
        if self._done or self._disposed:
            raise StopIteration
        kind, payload = self._ctx.call(
            "stream_next", task_id=self._task_id, index=self._i, timeout=timeout
        )
        if kind == "end":
            self._done = True
            raise StopIteration
        if kind == "error":
            self._done = True
            # the completion object carries the producer's exception;
            # resolving it raises with proper cause chaining
            self._ctx.get([ObjectRef(payload)], timeout=30)
            raise rex.RayError("stream failed but completion held no error")
        self._i += 1
        return ObjectRef(payload, owned=True)

    def close(self) -> None:
        self._dispose(blocking=True)

    def _dispose(self, blocking: bool) -> None:
        """Single dispose path: explicit close() blocks; the GC path may only
        enqueue (a blocking RPC from a GC tick can deadlock against a thread
        holding the head lock — see ObjectRef.__del__)."""
        if self._disposed:
            return
        self._disposed = True
        try:
            if blocking:
                self._ctx.call("stream_dispose", task_id=self._task_id)
            elif not self._ctx.closed:
                self._ctx.enqueue_gc(
                    "call", ("stream_dispose", {"task_id": self._task_id})
                )
        except Exception:
            pass

    def __del__(self):
        self._dispose(blocking=False)

    def __repr__(self):
        return f"ObjectRefGenerator({self._task_id.hex()[:8]}, next={self._i})"


class BaseContext:
    def __init__(self):
        self.closed = False
        self.remote = False  # True = different host than the head (no shm)
        # test hook, read once per context (not per get): skip the same-host
        # shm shortcut so same-machine tests exercise the real network path
        self._force_dp = os.environ.get("RAY_TPU_FORCE_DATA_PLANE") == "1"
        self.authkey: Optional[bytes] = None  # data-plane auth (set by subclasses)
        self.head_host: str = "127.0.0.1"  # host we reach the control plane on
        self._data_addrs: dict = {}  # node bin -> (host, port) cache
        # func_id -> the INTERNED id bytes: returning one object per id lets
        # spec headers elide func_id by identity (_split_for_wire)
        self._uploaded_funcs: dict[bytes, bytes] = {}
        self._readers: dict[bytes, ShmReader] = {}
        self._readers_lock = threading.Lock()
        # task-id source (see new_task_returns): nonce drawn once per context
        self._task_nonce = os.urandom(6)
        self._task_seq = itertools.count(1)
        self.current_actor = None  # set in actor workers
        self.node_id_bin: Optional[bytes] = None
        self.task_depth = 0
        # named-actor namespace this context creates/looks up in ("default"
        # for local drivers and workers; ray:// clients get their session's
        # — usually anonymous — namespace from the driver_ack handshake)
        self.namespace: str = "default"
        # pubsub: channel -> local callbacks fed by head "pub" pushes
        # (reference: src/ray/pubsub subscriber channels)
        self._pub_sinks: dict[str, list] = {}
        self._pub_lock = threading.Lock()
        # GC drain: __del__ methods (ObjectRef, generators, actor handles,
        # compiled DAGs) may ONLY touch this queue — SimpleQueue.put is
        # C-implemented and reentrant-safe, so a GC tick inside a lock-held
        # critical section can never re-enter head/connection locks. The
        # drain thread performs the real (possibly blocking) calls.
        self._gc_q: "queue.SimpleQueue" = queue.SimpleQueue()
        # dead ObjectRef ids awaiting a coalesced free (ObjectRef.__del__
        # appends, the gc drain tick ships): a C-level deque, so the
        # destructor path is one append — no lock, no wake, no allocation
        self._free_buf: deque = deque()
        # refs whose fire-and-forget submission died with the connection
        # (un-acked window / unsent outbox at a reconnect): obj_id -> the
        # retriable error get() raises. The head may never learn these ids,
        # so resolving them locally is what keeps a ref from hanging.
        self._poisoned: dict[bytes, Exception] = {}
        self._thunk_threads: list[threading.Thread] = []
        self._gc_thread = threading.Thread(
            target=self._gc_drain_loop, name="gc-drain", daemon=True
        )
        self._gc_thread.start()

    def enqueue_gc(self, kind: str, payload) -> None:
        """The ONLY operation a __del__ may perform against the runtime.
        kind: "call" -> (method, kwargs) executed via self.call;
        "thunk" -> zero-arg callable run on the drain thread."""
        self._gc_q.put((kind, payload))

    def _gc_drain_loop(self) -> None:
        free_buf = self._free_buf

        def flush_free() -> None:
            # ref drops dominate GC work at high task rates (one per
            # consumed result): ship whatever __del__ buffered as chunked
            # free batches — one head call / one socket write per chunk
            # instead of a lock round trip per dead ref
            while free_buf:
                ids: list[bytes] = []
                try:
                    while len(ids) < 8192:
                        ids.append(free_buf.popleft())
                except IndexError:
                    pass
                if not ids:
                    return
                try:
                    self._free_refs_rpc(ids)
                except Exception as e:
                    # transient failure (reconnect blip): put the popped
                    # chunk BACK so the next tick retries — dropping it
                    # would pin these objects' head refcounts (and their
                    # shm bytes) for the session's life
                    free_buf.extendleft(reversed(ids))
                    warn_throttled("gc drain loop", e)
                    return

        while True:
            try:
                # near-IDLE when the free buffer is empty (0.5Hz fallback —
                # 1000 workers polling at 100Hz once saturated a 1-core box,
                # test_envelope_1k_actors); while ids are buffered, the 5ms
                # timeout is the coalescing tick: refs dropped since the
                # last pass ship a few ms late, and a busy submit loop never
                # pays a gc wakeup per dead ref. __del__'s empty→non-empty
                # edge tick wakes us promptly; the 2s fallback covers the
                # tick's benign race (two concurrent appends can both see
                # len==2 and neither tick) so a lost wake self-heals
                item = self._gc_q.get(timeout=0.005 if free_buf else 2.0)
            except queue.Empty:
                if not self.closed:
                    flush_free()
                continue
            if item is None:
                flush_free()  # shutdown drains queued work BEFORE closing
                return
            if self.closed:
                continue  # keep draining so shutdown's sentinel is reached
            if item is _FREE_TICK:
                continue  # buffer went non-empty: re-enter the timed get
            kind, payload = item
            if kind == "call" and payload[0] == "free_ref_async":
                free_buf.append(payload[1]["obj_id"])
                continue
            flush_free()  # non-free work: frees precede blocking thunks
            try:
                if kind == "call":
                    method, kwargs = payload
                    self.call(method, **kwargs)
                elif kind == "thunk":
                    # thunks may block for seconds (e.g. CompiledDAG teardown
                    # joins its exec loops): run off-thread so queued ref
                    # frees aren't stalled behind them; tracked so shutdown's
                    # drain can join them (they unlink shm channels)
                    try:
                        t = threading.Thread(target=payload, daemon=True)
                        self._thunk_threads = [
                            x for x in self._thunk_threads if x.is_alive()
                        ]
                        self._thunk_threads.append(t)
                        t.start()
                    except RuntimeError:
                        payload()
            except Exception as e:
                # best-effort: the process may be tearing down
                warn_throttled("gc drain loop", e)

    # -- transport: subclasses implement call() --------------------------------
    def call(self, method: str, **payload) -> Any:
        raise NotImplementedError

    # -- pubsub ------------------------------------------------------------
    def on_pub(self, channel: str, payload) -> None:
        with self._pub_lock:
            sinks = list(self._pub_sinks.get(channel, ()))
        for fn in sinks:
            try:
                fn(channel, payload)
            except Exception as e:
                warn_throttled(f"pubsub callback on {channel}", e)

    def pub_register(self, channel: str, fn) -> None:
        with self._pub_lock:
            first = not self._pub_sinks.get(channel)  # missing OR emptied
            self._pub_sinks.setdefault(channel, []).append(fn)
        if first:
            self.call("subscribe", channel=channel)

    def pub_unregister(self, channel: str, fn) -> None:
        with self._pub_lock:
            sinks = self._pub_sinks.get(channel, [])
            if fn in sinks:
                sinks.remove(fn)
            empty = not sinks
        if empty:
            try:
                self.call("unsubscribe", channel=channel)
            except Exception:
                pass

    # -- objects ----------------------------------------------------------
    def put(self, value: Any) -> ObjectRef:
        if isinstance(value, ObjectRef):
            raise TypeError("Calling put() on an ObjectRef is not allowed.")
        sv = ser.serialize(value)
        # take_ref: the returned ObjectRef holds one refcount, taken inside
        # the put itself (one head round trip, not put + add_ref — without
        # the count, a single use as a task arg would unpin and evict).
        obj_id = self.put_serialized(sv, take_ref=True)
        return ObjectRef(obj_id, owned=True)

    def put_serialized(
        self, sv: ser.SerializedValue, is_error=False, take_ref=False
    ) -> bytes:
        raise NotImplementedError

    def _free_refs_rpc(self, ids: list) -> None:
        """Ship a coalesced ref-free batch, RAISING on transport failure —
        the gc drain's re-queue-and-retry path depends on seeing the error
        (the generic ``call`` fire-and-forget branches swallow it, which
        would silently drop up to a whole chunk of decrements and pin those
        objects' head refcounts for the session's life)."""
        if len(ids) == 1:
            self.call("free_ref_async", obj_id=ids[0])
        else:
            self.call("free_refs_async", obj_ids=ids)

    def get(self, refs: list[ObjectRef], timeout: Optional[float]) -> list[Any]:
        if self._poisoned:
            for r in refs:
                err = self._poisoned.get(r.binary())
                if err is not None:
                    # asking the head would hang forever: it may never have
                    # seen this id (failed fire-and-forget submission).
                    # Raise a FRESH instance: raising the stored one would
                    # attach a traceback whose frames pin this refs list,
                    # so the entry (cleared by the ref's __del__) could
                    # never drop — a poison-dict leak the audit would flag
                    raise err.__class__(*err.args)
        deadline = None if timeout is None else time.monotonic() + timeout
        locators = self.call("get", obj_ids=[r.binary() for r in refs], timeout=timeout)
        out = []
        for r, loc in zip(refs, locators):
            value = self._materialize(r.binary(), loc, deadline=deadline)
            kind, payload, is_err = loc
            if is_err:
                if isinstance(value, rex.RayTaskError):
                    raise value.as_instanceof_cause()
                raise value
            out.append(value)
        return out

    def store_value(self, sv: "ser.SerializedValue", is_error: bool = False):
        """Locator for a freshly serialized value. Large payloads go into
        THIS host's shared memory (arena or dedicated segment) and only the
        locator travels — on agent hosts the bytes are then served
        peer-to-peer by the agent's data server (data_plane.py). A remote
        process without a local store (a ``ray://`` driver) ships inline."""
        from ray_tpu._private.shm_store import _current_write_arena, write_shm

        arena = _current_write_arena()
        # ISSUE 18 zero-copy plane: with an arena attached the inline cutoff
        # drops to core_shm_inline_threshold — mid-size values (the
        # (threshold, 100KB] band that used to ride the socket twice: reply
        # in, get out) become one arena write plus a locator. Without an
        # arena the old 100KB cutoff stands: a dedicated POSIX segment per
        # mid-size object would cost more than the copy it saves.
        threshold = (
            GLOBAL_CONFIG.core_shm_inline_threshold
            if arena is not None
            else GLOBAL_CONFIG.max_direct_call_object_size
        )
        if sv.total_size <= threshold:
            return ("inline", sv.to_bytes(), is_error)
        if self.remote:
            if arena is None:
                # no host-local store to serve from (remote driver, or agent
                # without the native arena): the head re-lays these into its
                # shm and its spill watermark owns the lifetime
                return ("inline", sv.to_bytes(), is_error)
            if (
                sv.total_size <= GLOBAL_CONFIG.arena_max_object_bytes
                and arena.used + sv.total_size > 0.9 * arena.capacity
            ):
                # agent arena under pressure: agents have no spill of their
                # own (the head owns object lifetimes), so degrade to the
                # head-mediated path where the spill machinery applies
                # instead of running the agent host out of /dev/shm
                return ("inline", sv.to_bytes(), is_error)
        loc = write_shm(sv)
        loc.node = self.node_id_bin
        _data_counters()[0].inc(sv.total_size)
        return ("shm", loc, is_error)

    def _data_address_for(self, node_bin) -> Optional[tuple]:
        cached = self._data_addrs.get(node_bin)
        now = time.monotonic()
        if cached is not None and (cached[0] is not None or now < cached[1]):
            addr = cached[0]
        else:
            try:
                addr = self.call("data_address", node_id=node_bin)
            except Exception:
                addr = None
            # a negative result is transient (control hiccup, node still
            # registering): cache it briefly only, or one bad lookup would
            # disable the data plane for this node forever
            self._data_addrs[node_bin] = (
                addr, now + GLOBAL_CONFIG.object_location_negative_cache_s
            )
        if addr is None:
            return None
        host, port = addr
        return (host or self.head_host, port)

    def _fetch_via_data_plane(self, obj_id: bytes, payload, deadline=None):
        """Pull an object's bytes straight from its owning host (reference:
        pull_manager.cc chunked pulls). Returns (True, value) or (False,
        None) when the object is gone / the data plane can't serve it —
        callers then run the lost-object recovery path. ``deadline``
        (monotonic) bounds the head-mediated fallback; None = the caller
        had no timeout, so the fallback may block like get does."""
        from ray_tpu._private import data_plane

        if self.authkey is None:
            return False, None
        addr = self._data_address_for(payload.node)
        if addr is None:
            return False, None
        try:
            mv = data_plane.fetch(addr, self.authkey, payload)
        except data_plane.ObjectGone:
            return False, None
        except OSError:
            # owner unreachable (died? network?): drop the cached address
            # and try the head-mediated inline fallback before declaring
            # loss. The fallback honors the caller's REMAINING budget — a
            # timeout=0 poll here used to declare loss on a locator the
            # head was still re-laying (spill restore, lineage rebuild)
            remaining = None
            if deadline is not None:
                remaining = max(0.0, deadline - time.monotonic())
            try:
                loc = self.call(
                    "get_inline", obj_ids=[obj_id], timeout=remaining
                )[0]
            except Exception:
                return False, None
            if loc[0] == "inline":
                return True, ser.deserialize_value(
                    ser.SerializedValue.from_bytes(loc[1])
                )
            return False, None
        _data_counters()[3].inc()
        events.emit(
            "core.object.p2p_pull",
            obj_id=obj_id,
            size=payload.total_size,
            node=payload.node,
        )
        return True, data_plane.read_layout(mv, payload)

    def _materialize(self, obj_id: bytes, locator, _retry: bool = True,
                     deadline=None):
        kind, payload, is_err = locator
        if kind == "inline":
            if payload == ser.NONE_BYTES:
                return None  # one bytes compare beats a full deserialize
            return ser.deserialize_value(ser.SerializedValue.from_bytes(payload))
        force_dp = (
            self._force_dp
            and payload.node is not None
            and payload.node != self.node_id_bin
        )
        reader = None
        if not force_dp:
            with self._readers_lock:
                reader = self._readers.get(obj_id)
                if reader is None:
                    try:
                        # local-first: on the owning host (or any same-host
                        # simulated node) the shm attaches by name, zero-copy
                        reader = ShmReader(payload)
                    except FileNotFoundError:
                        # not on this host — or spilled/unlinked under us
                        reader = None
        if reader is None:
            # the data plane must get its shot even on the recovery retry:
            # a lineage rebuild can land the fresh copy on a REMOTE host
            ok, value = self._fetch_via_data_plane(obj_id, payload, deadline)
            if ok:
                return value
            if not _retry:
                raise FileNotFoundError(f"object {obj_id.hex()} unavailable")
        if reader is None:
            # tell the head the backing is gone so it can restore from spill
            # or rebuild via lineage (reference: object recovery manager),
            # then block in get until a fresh copy lands
            try:
                self.call("report_lost", obj_ids=[obj_id])
            except Exception:
                pass
            fresh = self.call("get", obj_ids=[obj_id], timeout=None)[0]
            value = self._materialize(obj_id, fresh, _retry=False)
            if fresh[2]:
                # the object resolved to an error AFTER the caller already
                # checked its (stale) locator — raise here, matching the
                # caller-side error semantics
                if isinstance(value, rex.RayTaskError):
                    raise value.as_instanceof_cause()
                raise value
            return value
        value = reader.read()
        ctrs = _data_counters()
        ctrs[1].inc(payload.total_size)
        ctrs[2].inc()
        self._sweep_readers()
        return value

    def _sweep_readers(self, limit: int = 256):
        if len(self._readers) <= limit:
            return
        with self._readers_lock:
            for oid in list(self._readers)[: len(self._readers) - limit]:
                self._readers.pop(oid).close()

    def wait(self, refs, num_returns, timeout, fetch_local=True):
        ids = [r.binary() for r in refs]
        # a poisoned ref is RESOLVED (get raises its retriable error): count
        # it ready UP FRONT and only ask the head about the rest — the head
        # never learned these ids, so including them would park the wait for
        # its whole timeout even when poisoned refs already make the count
        ready_ids = {i for i in ids if i in self._poisoned} if self._poisoned else set()
        remaining = [i for i in ids if i not in ready_ids]
        need = min(num_returns - len(ready_ids), len(remaining))
        if need > 0:
            ready_ids.update(
                self.call("wait", obj_ids=remaining, num_returns=need, timeout=timeout)
            )
        ready, not_ready = [], []
        for r in refs:
            (ready if r.binary() in ready_ids and len(ready) < num_returns else not_ready).append(r)
        return ready, not_ready

    # -- functions --------------------------------------------------------
    def upload_function(self, blob: bytes, func_id: Optional[bytes] = None) -> bytes:
        if func_id is None:
            func_id = hashlib.sha1(blob).digest()[:16]
        cached = self._uploaded_funcs.get(func_id)
        if cached is not None:
            return cached
        self.call("put_function", func_id=func_id, blob=blob)
        self._uploaded_funcs[func_id] = func_id
        return func_id

    # -- spec building ----------------------------------------------------
    def serialize_args(self, args, kwargs):
        if not args and not kwargs:
            # SHARED empty constants (never mutated downstream — all spec
            # arg access is read-only): a no-arg call's args/kwargs then
            # match its spec header by IDENTITY and drop off the wire
            # entirely (_split_for_wire / _wire_spec)
            return EMPTY_ARGS, EMPTY_KWARGS

        def one(v):
            if isinstance(v, ObjectRef):
                return ("r", v.binary())
            sv = ser.serialize(v)
            if sv.total_size > GLOBAL_CONFIG.max_direct_call_object_size:
                # big by-value arg: implicit put (reference: dependency
                # resolver promotes >100KB args to plasma)
                return ("r", self.put_serialized(sv))
            return ("v", sv.to_bytes())

        return [one(a) for a in args], {k: one(v) for k, v in kwargs.items()}

    def submit_task(self, spec: dict) -> list[ObjectRef]:
        # the head takes the submitter's refs on the return ids at receive
        # time — one message (or one SHARE of a batched window), never
        # 1 + num_returns round trips. Submission is fire-and-forget: the
        # refs are minted client-side and submit-time errors surface on
        # them asynchronously (_enqueue_submit per context).
        refs = [ObjectRef(rid, owned=True) for rid in spec["return_ids"]]
        self._enqueue_submit("task", spec)
        return refs

    def submit_actor_task(self, spec: dict) -> list[ObjectRef]:
        refs = [ObjectRef(rid, owned=True) for rid in spec["return_ids"]]
        self._enqueue_submit("actor_method", spec)
        return refs

    def _enqueue_submit(self, kind: str, spec: dict) -> None:
        raise NotImplementedError

    def new_task_returns(self, num_returns: int):
        # Task ids end in 4 zero bytes so a return ObjectID's 12-byte prefix
        # uniquely reconstructs its task id (used by ray_tpu.cancel()).
        # 6-byte per-process nonce + 6-byte counter instead of a per-task
        # urandom syscall: uniqueness across submitters comes from the nonce
        # (48 bits — birthday-safe for any realistic process count), and the
        # counter never wraps in practice (2^48 submissions).
        prefix = self._task_nonce + next(self._task_seq).to_bytes(6, "big")
        # raw bytes on purpose: this runs once per .remote() and the
        # TaskID/ObjectID wrappers would be built only to call .binary()
        # (layout must match ObjectID.for_task_return: prefix + LE index)
        return prefix + b"\x00\x00\x00\x00", [
            prefix + i.to_bytes(4, "little") for i in range(num_returns)
        ]

    def shutdown(self):
        # drain already-queued GC work (ref frees, stream disposes, DAG
        # teardowns) while the control plane is still up, THEN mark closed —
        # the reverse order would silently discard them. Bounded join: a
        # drain item wedged on a dying head must not hang shutdown.
        self._gc_q.put(None)
        if threading.current_thread() is not self._gc_thread:
            self._gc_thread.join(timeout=5.0)
        for t in self._thunk_threads:  # DAG teardowns must finish their
            if t is not threading.current_thread():  # channel unlinks
                t.join(timeout=5.0)
        self.closed = True
        with self._readers_lock:
            for reader in self._readers.values():
                reader.close()
            self._readers.clear()


class DriverContext(BaseContext):
    """Runs in the driver process; owns the Head."""

    def __init__(self, head, node_id_bin: bytes):
        super().__init__()
        self.head = head
        self.node_id_bin = node_id_bin
        self.authkey = head.authkey

    def _enqueue_submit(self, kind: str, spec: dict) -> None:
        """In-process submission: the head call IS the 'socket write' (no
        round trip exists to pipeline away), but the worker-bound dispatch
        it queued stays in the head outbox until ``core_dispatch_coalesce``
        messages gather — an async submit burst then ships per worker as
        one ``run_task_batch`` write. Any blocking call (get/wait flush at
        entry, ``_pump_or_wait`` re-checks) or the outbox backstop bounds
        how long a dispatch can sit."""
        wf = spec.get("wf")
        if wf is not None:
            # deferred import (util package ↔ runtime cycle); only the
            # sampled-and-stamped path pays the sys.modules lookup
            from ray_tpu.util import waterfall as _waterfall

            _waterfall.stamp(wf)  # socket_write: entering the head
        head = self.head
        was_idle = not head._outbox
        try:
            if kind == "task":
                head.submit_task(spec)
            else:
                head.submit_actor_task(spec)
        finally:
            if (was_idle and head._outbox) or len(
                head._outbox
            ) >= GLOBAL_CONFIG.core_dispatch_coalesce:
                # idle-plane submit (the sync round-trip pattern): the
                # dispatch rides out NOW — deferring it to the caller's
                # next head RPC charges that RPC's entry path to the
                # head_dispatch leg. A burst (outbox already non-empty)
                # keeps coalescing until the batch fills.
                head.flush_outbox()

    def get(self, refs, timeout: Optional[float]) -> list:
        if len(refs) == 1 and not self._poisoned:
            # sync round-trip fast path: the call() indirection and the
            # id-list/zip machinery drop out of the reply-side corridor
            head = self.head
            if head._outbox:
                head.flush_outbox()
            oid = refs[0]._id
            deadline = None if timeout is None else time.monotonic() + timeout
            loc = head.get_locators([oid], timeout)[0]
            value = self._materialize(oid, loc, deadline=deadline)
            if loc[2]:  # error locator: raise, never return
                if isinstance(value, rex.RayTaskError):
                    raise value.as_instanceof_cause()
                raise value
            return [value]
        return super().get(refs, timeout)

    def call(self, method: str, **payload):
        if self.head._outbox:
            # deferred dispatches (coalesced submits) ride out before any
            # other head interaction — get/wait must never park behind an
            # unflushed run_task they are waiting on
            self.head.flush_outbox()
        if method == "get":  # hottest two first (once per ray.get/wait)
            return self.head.get_locators(payload["obj_ids"], payload.get("timeout"))
        if method == "wait":
            return self.head.wait_objects(payload["obj_ids"], payload["num_returns"], payload.get("timeout"))
        if method == "subscribe":
            return self.head.subscribe_local(payload["channel"], self.on_pub)
        if method == "unsubscribe":
            return self.head.unsubscribe_local(payload["channel"], self.on_pub)
        if method == "free_ref_async":
            # runs on the gc-drain thread (never from __del__ directly):
            # blocking on the head lock here is safe, and eviction may queue
            # agent sends that need flushing like any other in-process call
            try:
                return self.head.remove_ref(payload["obj_id"])
            finally:
                self.head.flush_outbox()
        if method == "free_refs_async":
            try:
                return self.head.remove_refs(payload["obj_ids"])
            finally:
                self.head.flush_outbox()
        if method == "add_ref":
            return self.head.add_ref(payload["obj_id"])
        try:
            return getattr(self.head, "rpc_" + method)(**payload)
        finally:
            # in-process calls bypass _run_request: drain any worker sends
            # this call queued (head.flush_outbox docstring)
            self.head.flush_outbox()

    def put_serialized(self, sv, is_error=False, take_ref=False) -> bytes:
        try:
            return self.head.put_serialized(sv, is_error, take_ref=take_ref)
        finally:
            self.head.flush_outbox()


class WorkerContext(BaseContext):
    """Runs in worker processes; control plane over the head socket.

    ``remote=True`` marks a process on a DIFFERENT host than the head: all
    object payloads travel inline over the socket (the head's shm segments
    are unreachable), and the head converts in both directions.
    """

    def __init__(
        self,
        conn,
        node_id_bin: bytes,
        remote: bool = False,
        authkey: Optional[bytes] = None,
        head_host: Optional[str] = None,
    ):
        super().__init__()
        self.conn = conn
        self.node_id_bin = node_id_bin
        self.remote = remote
        self.authkey = authkey
        if head_host:
            self.head_host = head_host
        self._seq = itertools.count(1)
        self._send_lock = threading.Lock()
        self._pending: dict[int, list] = {}
        self._pending_lock = threading.Lock()
        # pipelined submission (ISSUE 14): .remote() buffers here and a
        # whole burst ships as ONE submit_batch message — no send+reply
        # rendezvous per task. The head acks WINDOWS; _submit_inflight
        # counts tasks in un-acked windows against the credit limit.
        # _submit_send serializes window build+send end to end (FIFO);
        # the cv itself is never held across a socket write.
        self._submit_send = threading.Lock()
        self._submit_cv = threading.Condition()
        # the thread that processes submit_acks (worker recv loop / driver
        # pump): it must NEVER park in _flush_submits — it is the only
        # thread that can replenish credits, and an exec thread in the
        # credit wait holds _submit_send, so blocking here is a self-
        # deadlock. send_raw/call skip the flush on this thread.
        self._recv_ident: Optional[int] = None
        self._submit_buf: list = []  # (kind, spec) in submission order
        self._submit_wid = 0
        self._submit_unacked: dict[int, tuple] = {}  # wid -> (ids, conn)
        self._submit_inflight = 0
        self._submit_last_flush = 0.0
        self._submit_backstop: Optional[threading.Event] = None
        self._sent_hdrs: set = set()

    # message pump (run by worker_main's receiver thread)
    def on_response(self, seq, ok, payload):
        with self._pending_lock:
            slot = self._pending.get(seq)
        if slot is not None:
            slot[1] = (ok, payload)
            slot[0].set()

    # ---------------------------------------------------------- submission
    def _enqueue_submit(self, kind: str, spec: dict) -> None:
        """Fire-and-forget submission with burst coalescing: the first
        submit after a quiet period flushes immediately (a lone nested
        task must not sit in the buffer), while submits arriving on the
        heels of a flush are a burst — they buffer and ship as one window
        when the batch fills, before the next head RPC (every call()/
        send_raw flushes first), or at the 5ms backstop."""
        now = time.monotonic()
        with self._submit_cv:
            self._submit_buf.append((kind, spec))
            defer = (
                now - self._submit_last_flush
                < GLOBAL_CONFIG.core_submit_flush_backstop_s / 8
                and len(self._submit_buf) < GLOBAL_CONFIG.core_submit_batch_max
            )
        if defer:
            evt = self._submit_backstop
            if evt is None:
                evt = self._ensure_submit_backstop()
            evt.set()  # backstop bounds the burst tail's sit time
            return
        self._flush_submits()

    def _ensure_submit_backstop(self) -> threading.Event:
        with self._submit_cv:
            if self._submit_backstop is not None:
                return self._submit_backstop
            evt = self._submit_backstop = threading.Event()

        def loop():
            period = GLOBAL_CONFIG.core_submit_flush_backstop_s
            while not self.closed:
                evt.wait()
                evt.clear()
                while not self.closed:
                    time.sleep(period)
                    if not self._submit_buf:
                        break  # quiet again: park on the event
                    try:
                        self._flush_submits()
                    except Exception as e:
                        warn_throttled("submit backstop flush", e)

        threading.Thread(target=loop, name="submit-backstop", daemon=True).start()
        return evt

    def _flush_submits(self) -> None:
        """Ship every buffered spec as one submit_batch window. Window
        ORDER is the FIFO contract (per-actor FIFO is submission order):
        the outer ``_submit_send`` lock serializes build+send end to end.
        The wire write itself happens OUTSIDE ``_submit_cv`` — the recv
        thread must be able to process submit_acks (which take the cv)
        even while a send is blocked on a full socket, or head and worker
        wedge against each other's full buffers (each blocked writing,
        neither reading)."""
        while True:
            with self._submit_send:
                with self._submit_cv:
                    if not self._submit_buf or self.closed:
                        return
                    while (
                        self._submit_inflight
                        >= GLOBAL_CONFIG.core_submit_window_tasks
                    ):
                        # window credits exhausted: the head is behind —
                        # park until acks return credits (recv loop fills
                        # them; a reconnect sweep resets them)
                        if self.closed:
                            return
                        self._submit_cv.wait(timeout=0.1)
                    if not self._submit_buf:
                        continue  # a reconnect sweep drained it while we waited
                    items = self._submit_buf
                    self._submit_buf = []
                    self._submit_wid += 1
                    wid = self._submit_wid
                    ids = [rid for _k, s in items for rid in s["return_ids"]]
                    # capture the conn the window will ACTUALLY ride: the
                    # send below must use this same object, or a reconnect
                    # between build and send makes _fail_submits(not_on=
                    # fresh) poison a window that was delivered on the
                    # fresh conn — and the caller's retry double-submits
                    conn0 = self.conn
                    puts = [s for k, s in items if k == "put"]
                    self._submit_unacked[wid] = (ids, conn0, puts)
                    self._submit_inflight += len(ids)
                    self._submit_last_flush = time.monotonic()
                    self._set_credit_gauge()
                    hdrs: dict = {}
                    wire = []
                    stamped = False
                    for kind, spec in items:
                        wf = spec.get("wf")
                        if wf is not None:
                            if not stamped:
                                from ray_tpu.util import waterfall as _waterfall

                                stamped = True
                            _waterfall.stamp(wf)  # socket_write: batch write begins
                        wire.append((kind, _split_for_wire(spec, self._sent_hdrs, hdrs)))
                    payload = {"wid": wid, "items": wire}
                    if hdrs:
                        payload["hdrs"] = hdrs
                try:
                    with self._send_lock:
                        ser.conn_send(conn0, ("submit_batch", payload))
                except Exception as e:
                    # the window never reached the head: resolve its TASK
                    # refs locally with a retriable error (fail, never
                    # replay — at-most-once is the pinned reconnect
                    # semantic for tasks). Puts are idempotent (id minted
                    # once per op; head dedupes replays) so they re-queue
                    # for the next connection instead.
                    with self._submit_cv:
                        ent = self._submit_unacked.pop(wid, None)
                        if ent is not None:
                            # a reconnect sweep may have raced us here and
                            # already failed this window — decrementing
                            # again would drive the credit counter negative
                            # and quietly widen the flow-control window
                            self._submit_inflight -= len(ids)
                            # header definitions riding this (or any
                            # earlier) window may be lost with the conn:
                            # future windows must re-ship them (idempotent
                            # receiver-side)
                            self._sent_hdrs.clear()
                            err = rex.RayError(
                                "connection to the cluster was lost while "
                                "submitting a task window; the tasks did "
                                f"not run — retry ({e})"
                            )
                            put_ids = {s["obj_id"] for s in puts}
                            for rid in ids:
                                if rid not in put_ids:
                                    self._poisoned[rid] = err
                                    events.emit(
                                        "core.object.poison",
                                        obj_id=rid,
                                        reason="submit-window-lost",
                                    )
                            if puts:
                                self._submit_buf = [
                                    ("put", {**s, "replay": True})
                                    for s in puts
                                ] + self._submit_buf
                            self._set_credit_gauge()
                    return

    def _on_submit_ack(self, wid: int) -> None:
        with self._submit_cv:
            ent = self._submit_unacked.pop(wid, None)
            if ent is not None:
                self._submit_inflight -= len(ent[0])
                self._set_credit_gauge()
                self._submit_cv.notify_all()

    def _set_credit_gauge(self) -> None:
        _credit_gauge().set(
            max(0, GLOBAL_CONFIG.core_submit_window_tasks - self._submit_inflight)
        )

    def _fail_submits(self, not_on=None, replay_puts=True) -> None:
        """Connection died: resolve every TASK ref in un-acked windows (the
        head may or may not have processed them — the ack was lost with
        the socket) and every unsent buffered task spec to a retriable
        error. FAIL, never replay, is the pinned choice for tasks: blind
        replay of a window the head DID process would double-submit them.
        PUTS are the exception (ISSUE 18): a put id is minted exactly once
        per op, so redelivery is idempotent — the head dedupes
        replay-flagged puts — and un-acked/unsent put items re-queue for
        the fresh connection instead of poisoning their refs.
        ``replay_puts=False`` is the give-up sweep (reconnect failed or
        the context is closing): poison puts too, or their refs would
        hang. ``not_on`` spares windows already sent on the fresh
        post-reconnect conn."""
        err = rex.RayError(
            "connection to the cluster was lost before this submit window "
            "was acknowledged; it may not have run — retry the call"
        )
        with self._submit_cv:
            doomed: list[bytes] = []
            requeue: list = []
            for wid, ent in list(self._submit_unacked.items()):
                ids, conn0 = ent[0], ent[1]
                puts = ent[2] if len(ent) > 2 else []
                if not_on is None or conn0 is not not_on:
                    self._submit_unacked.pop(wid, None)
                    self._submit_inflight -= len(ids)
                    if replay_puts and puts:
                        put_ids = {s["obj_id"] for s in puts}
                        doomed.extend(i for i in ids if i not in put_ids)
                        requeue.extend(
                            ("put", {**s, "replay": True}) for s in puts
                        )
                    else:
                        doomed.extend(ids)
            if not_on is None:
                # full-failure sweep (reconnect not yet attempted or gave
                # up): unsent buffered task specs would otherwise sit
                # forever — fail them too. A post-reconnect sweep
                # (not_on=fresh) KEEPS the buffer: those specs never
                # touched any conn (shipping them on the fresh one cannot
                # double-submit), and some may postdate the reconnect.
                kept: list = []
                for _kind, spec in self._submit_buf:
                    if _kind == "put" and replay_puts:
                        kept.append((_kind, spec))  # never sent: no flag
                    else:
                        doomed.extend(spec["return_ids"])
                self._submit_buf = requeue + kept
            else:
                # replayed puts go to the FRONT: they predate everything
                # currently buffered
                self._submit_buf = requeue + self._submit_buf
            # header defs sent on the dead conn may not have survived
            # receiver-side (a fresh WorkerHandle starts with empty
            # submit_hdrs): re-ship every header on the next window —
            # idempotent for receivers that did keep them
            self._sent_hdrs.clear()
            for rid in doomed:
                self._poisoned[rid] = err
                # give-up sweeps (replay_puts=False) poison PUT ids too —
                # the forensic trail test_zero_copy_plane reads back
                events.emit(
                    "core.object.poison", obj_id=rid, reason="conn-lost"
                )
            self._set_credit_gauge()
            self._submit_cv.notify_all()

    def call(self, method: str, **payload):
        if self._submit_buf and threading.get_ident() != self._recv_ident:
            # buffered fire-and-forget submits precede every other RPC —
            # a get on their refs must find the head already owning them.
            # Never from the ack-processing thread: it parks in the credit
            # wait that only it can un-park (see _recv_ident)
            self._flush_submits()
        if method == "free_ref_async":
            # fire-and-forget decrement; workers never block on GC
            try:
                self._send(("req", 0, "free_ref", {"obj_id": payload["obj_id"]}))
            except Exception:
                pass
            return None
        if method == "free_refs_async":
            try:
                self._send(("req", 0, "free_refs", {"obj_ids": payload["obj_ids"]}))
            except Exception:
                pass
            return None
        return self._call_blocking(method, payload)

    def _free_refs_rpc(self, ids: list) -> None:
        # seq-0 send WITHOUT the fire-and-forget swallow: the gc drain
        # re-queues the chunk on failure (a raise means the kernel never
        # took the bytes — no double-decrement on retry). Routed through
        # send_raw, which flushes buffered submits first: a free racing
        # ahead of the submit window that CREATES its ref would be
        # consumed as a no-op and leave the ref pinned forever.
        if len(ids) == 1:
            self.send_raw(("req", 0, "free_ref", {"obj_id": ids[0]}))
        else:
            self.send_raw(("req", 0, "free_refs", {"obj_ids": ids}))

    def _call_blocking(self, method: str, payload: dict):
        seq = next(self._seq)
        ev = threading.Event()
        # slot[2] records the conn this call actually went out on (set by
        # _send UNDER the send lock): after a reconnect swap, slots tied to
        # the OLD conn are failed retriably — a send into a dying socket
        # can land in the kernel buffer without error, and without this the
        # caller would wait forever for a reply the head never saw
        slot = [ev, None, None]
        with self._pending_lock:
            self._pending[seq] = slot
        try:
            self._send(("req", seq, method, payload), slot=slot)
        except Exception as e:
            # reap the slot (seqs never repeat — a leaked slot lives
            # forever) and surface a retriable error: send failures are
            # ROUTINE during a client reconnect window
            with self._pending_lock:
                self._pending.pop(seq, None)
            raise rex.RayError(
                f"connection to the cluster lost while sending {method!r}; "
                f"retry the call ({e})"
            ) from e
        ev.wait()
        with self._pending_lock:
            self._pending.pop(seq, None)
        ok, result = slot[1]
        if not ok:
            raise result
        return result

    def _send(self, msg, slot=None):
        with self._send_lock:
            if slot is not None:
                if slot[1] is not None:
                    # a reconnect sweep failed this call BEFORE its send:
                    # transmitting now would execute a request whose caller
                    # was already told "retry" (double-submit). Surface the
                    # recorded error instead.
                    ok, err = slot[1]
                    if not ok:
                        raise err
                slot[2] = self.conn  # the conn the bytes actually ride
            ser.conn_send(self.conn, msg)

    def send_raw(self, msg):
        if self._submit_buf and threading.get_ident() != self._recv_ident:
            # completions/stream items must not overtake the submits that
            # preceded them (nested fan-out: parent's task_done after its
            # children's submit window). The recv thread is exempt (see
            # _recv_ident): its sends — exit-flush, header-miss errors —
            # have no causal order against exec threads' buffered submits,
            # and parking it wedges the worker permanently
            self._flush_submits()
        self._send(msg)

    # Pipelined put (ISSUE 18): puts ride the submit_batch window plane
    # instead of blocking a round trip each — a put burst coalesces into
    # one socket frame (bytes, or just the locator for arena-resident
    # values) and is bounded by head processing, not N RTTs. Ordering is
    # the window FIFO + the head consuming each connection in order: any
    # later use of the ref (submit, get, task_done carrying it out) rides
    # the same conn after the put. The window machinery supplies the
    # failure contract for free: an un-acked or unsendable window poisons
    # its ids (put ids included, via ``return_ids``) with a retriable
    # error — which also makes async puts safe across a ray:// driver's
    # reconnect — and head-side store failures land ON the object id as
    # an error locator (rpc_put never raises), so get() raises either way
    # instead of parking in the not-yet-arrived wait. Window credits
    # double as put backpressure: a burst cannot buffer unbounded bytes.
    _put_async = True

    def put_serialized(self, sv, is_error=False, take_ref=False) -> bytes:
        obj_id = ObjectID.for_put().binary()
        kind, payload, err = self.store_value(sv, is_error)
        if kind == "shm":
            events.emit(
                "core.object.put",
                obj_id=obj_id,
                size=payload.total_size,
                node=payload.node,
                seg=payload.name,
            )
        small, shm = (payload, None) if kind == "inline" else (None, payload)
        req = {
            "obj_id": obj_id, "small": small, "shm": shm, "is_error": err,
            "take_ref": take_ref,
        }
        if self._put_async and GLOBAL_CONFIG.core_put_pipeline:
            # return_ids: the window plane's unit of accounting — credits,
            # acks, and loss-poisoning all key off it
            req["return_ids"] = [obj_id]
            self._enqueue_submit("put", req)
            return obj_id
        self.call("put", **req)
        return obj_id


class RemoteDriverContext(WorkerContext):
    """A driver attached to a head in ANOTHER process/host over TCP
    (reference: ``ray.init(address=...)`` connecting to a running cluster;
    with a session token this is the ``ray://`` client protocol —
    reference ``util/client/``). Same RPC surface as a worker, plus its own
    response pump (workers get theirs from worker_main's recv loop).

    Reconnect-with-resume: on connection loss the pump redials the head
    presenting ``session_token`` for up to the reconnect grace. The head
    resumes the session (same namespace, refs intact — ClientSession in
    head.py); calls in flight AT the drop fail with a retriable RayError
    (resending them blindly could double-submit tasks), later calls ride
    the new connection transparently. Pipelined puts survive the
    reconnect: unlike tasks, a put id is minted exactly once per op, so a
    put in an un-acked window at the drop is REPLAYED on the fresh conn
    (the head dedupes replay-flagged puts) and unsent buffered puts ship
    there too; only when the reconnect itself gives up are put refs
    poisoned, so gets raise instead of hanging."""

    def __init__(
        self,
        conn,
        node_id_bin: bytes,
        authkey: Optional[bytes] = None,
        head_host: Optional[str] = None,
        address: Optional[str] = None,
        session_token: Optional[str] = None,
    ):
        super().__init__(conn, node_id_bin, remote=True, authkey=authkey, head_host=head_host)
        self.address = address
        self.session_token = session_token
        self._pump = threading.Thread(
            target=self._pump_loop, name="driver-pump", daemon=True
        )
        self._pump.start()

    def _fail_pending(self, not_on=None):
        """Fail pending calls retriably. ``not_on``: spare slots already
        sent on that (fresh) connection — used by the post-reconnect sweep
        so a call that raced onto the new conn keeps waiting for its real
        reply.

        The whole sweep holds ``_send_lock``: collection reads slot[2] and
        writes slot[1], which _send's pre-send guard reads/writes under the
        same lock — without it, a caller could pass the guard while the
        sweep dooms its (unsent) slot, then transmit a request whose caller
        was told to retry (double-submit)."""
        with self._send_lock:
            with self._pending_lock:
                doomed = [
                    (seq, s)
                    for seq, s in self._pending.items()
                    if not_on is None or s[2] is not not_on
                ]
                for seq, _ in doomed:
                    self._pending.pop(seq, None)
            for _seq, slot in doomed:
                slot[1] = (
                    False,
                    rex.RayError(
                        "connection to the cluster was lost mid-call; the "
                        "session was resumed — retry the call"
                    ),
                )
        for _seq, slot in doomed:
            slot[0].set()

    def _try_reconnect(self) -> bool:
        if self.address is None or self.session_token is None:
            return False
        import time as _time

        from ray_tpu._private.config import GLOBAL_CONFIG
        from ray_tpu._private.worker_main import connect_head

        deadline = _time.monotonic() + GLOBAL_CONFIG.client_reconnect_grace_s
        while _time.monotonic() < deadline and not self.closed:
            try:
                conn = connect_head(self.address, self.authkey, retries=1)
                conn.send(
                    ("register_driver", {"session_token": self.session_token})
                )
                kind, info = conn.recv()
                if kind != "driver_ack" or info.get("session_token") != self.session_token:
                    raise OSError("session not resumed")
                with self._send_lock:
                    self.conn = conn
                # calls that raced into the dying socket's kernel buffer
                # produced no error yet got no reply: fail everything not
                # already sent on the FRESH conn (they retry; a silent hang
                # would be the alternative). Same contract for submit
                # windows: un-acked ones fail retriably — their acks died
                # with the old socket and a blind replay could double-submit
                self._fail_pending(not_on=conn)
                self._fail_submits(not_on=conn)
                # head-side pubsub routing died with the old conn: re-send
                # subscribes for every channel with live sinks. Raw seq-0
                # requests — a blocking call() here would deadlock (this IS
                # the pump thread that processes replies).
                with self._pub_lock:
                    channels = [c for c, sinks in self._pub_sinks.items() if sinks]
                for channel in channels:
                    try:
                        self._send(("req", 0, "subscribe", {"channel": channel}))
                    except Exception:
                        break  # fresh conn died already: next loop retries
                return True
            except Exception:
                _time.sleep(0.5)
        return False

    def _pump_loop(self):
        # this thread processes submit_acks (see _recv_ident): the
        # send_raw/call flush guards exempt it from the credit wait
        self._recv_ident = threading.get_ident()
        while not self.closed:
            try:
                msg = self.conn.recv()
            # TypeError: a concurrent local close (chaos shutdown_conn, a
            # reconnect swap losing the race) nulls the Connection's handle
            # mid-_recv and CPython raises it instead of OSError — without
            # catching it here the pump thread dies silently and the session
            # never redials (every later call fails for the session's life)
            except (EOFError, OSError, ValueError, TypeError):
                # fail in-flight calls FIRST (they will never get replies;
                # failing after the swap could catch a call already sent on
                # the fresh connection), then redial with the session token
                self._fail_pending()
                self._fail_submits()
                if self.closed or not self._try_reconnect():
                    # giving up for good: re-queued puts will never ship —
                    # poison them so pending gets raise instead of hanging
                    self._fail_submits(replay_puts=False)
                    return
                continue
            if msg[0] == "resp":
                _, seq, ok, payload = msg
                self.on_response(seq, ok, payload)
            elif msg[0] == "pub":
                self.on_pub(msg[1], msg[2])
            elif msg[0] == "submit_ack":
                self._on_submit_ack(msg[1]["wid"])

    def shutdown(self):
        super().shutdown()
        from ray_tpu._private.node_agent import shutdown_conn

        shutdown_conn(self.conn)  # interrupts the pump thread's recv too
