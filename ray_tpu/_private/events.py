"""Flight recorder: an always-on, bounded ring of structured events.

Reference shape: the GCS task-event stream + Ray's debug-state dumps — but
process-local and always armed, so a postmortem of a killed replica or a
preemption storm needs no re-run.

Hot-path architecture (the PR-11 rebuild; OBSERVABILITY.md "hot-path
architecture & overhead budget"):

* **Per-thread SPSC rings.** Every emitting thread owns a private
  bounded ring (``_Ring``: one ``deque`` + counters). ``record()`` is
  thread-local append only — no shared lock, no cross-thread mutation,
  ever. The ring's ``dropped`` counter has exactly ONE writer (the
  owning thread), so overflow accounting is exact, not advisory.
* **Background collector.** A daemon thread (``events-collector``)
  folds rings whose owner thread has exited into a bounded ``_retired``
  deque (memory stays bounded by live threads + one capacity's worth of
  history from dead ones) and publishes the aggregate drop count as the
  ``events_dropped`` metric — created lazily, off the emit path.
* **Merge order.** Every event carries a process-global monotonic
  ``seq`` (``itertools.count`` — a single atomic C call, not a lock), so
  ``snapshot()`` merges the per-thread rings back into the exact global
  emission order and ``rpc_collect_events`` / crash-flush consumers see
  the same stream the one-ring design produced.
* **Signal safety.** ``snapshot()``/``flush()`` take no locks at all:
  the SIGTERM crash handler runs them from a signal frame that may have
  interrupted ``record()`` mid-append on the same thread, where any
  non-reentrant lock would deadlock the dying process. Ring creation is
  a plain dict store (atomic under the GIL) for the same reason.

Three consumers:

* **Live drain** — :func:`collect_cluster_events` gathers every live
  worker's rings through the head (same broadcast/mailbox machinery as
  the worker stack dumps), so ``python -m ray_tpu.obs events`` / ``obs
  req <id>`` can reconstruct a request's life across processes.
* **Crash flush** — :func:`install_crash_handlers` arms ``sys.excepthook``
  / ``threading.excepthook`` / ``SIGTERM`` to dump the rings as JSONL into
  ``RAY_TPU_EVENTS_DIR`` before the process dies.  Workers are killed by
  SIGTERM (proc_handles), so a replica shot mid-stream still leaves its
  last ``capacity`` events per thread on disk.
* **Chrome trace** — ``util.tracing.export_chrome_trace`` renders events
  carrying a ``request_id`` as one per-request lane.

Knobs (environment, read at import):

* ``RAY_TPU_EVENTS`` — ``0`` disables recording entirely (bench A/B).
* ``RAY_TPU_EVENTS_CAPACITY`` — ring size per thread (default 8192).
* ``RAY_TPU_EVENTS_DIR`` — crash-flush directory (default
  ``<tempdir>/ray_tpu_events``).
"""

from __future__ import annotations

import itertools
import json
import os
import sys
import tempfile
import threading
import time
from collections import deque
from typing import Any, Optional

# the one metric this module exports (raylint RL012 registry): total
# events evicted by ring overflow, across all per-thread rings
METRIC_NAMES = ("events_dropped",)

#: raylint RL017 registry — the PR 11 zero-lock hot path, DECLARED so the
#: cross-thread-race analysis verifies the design instead of flagging it
#: (':atomic' = every write is one GIL-atomic operation):
#:
#: - _rings: id(ring) -> ring; registration is a plain dict store from the
#:   owning thread (atomic under the GIL — the module doc's signal-safety
#:   argument), the collector pops dead entries; snapshot() reads an
#:   atomic list() copy. The whole point of the rebuild is NO shared lock
#:   on first emit.
#: - _retired: rebuilt by the collector as ONE deque swap (publish-before-
#:   unregister, PR 11 review round); clear() is a tests/tools reset.
LOCKFREE = (
    "_rings: atomic",
    "_retired: atomic",
)


def _env_enabled() -> bool:
    return os.environ.get("RAY_TPU_EVENTS", "1").lower() not in ("0", "false", "off")


def _env_capacity() -> int:
    try:
        return max(16, int(os.environ.get("RAY_TPU_EVENTS_CAPACITY", "8192")))
    except ValueError:
        return 8192


class _Ring:
    """One thread's private event ring (SPSC: the owning thread appends,
    the collector and snapshot() only read). ``dropped`` is written by
    the owner thread alone — exact accounting, no read-modify-write race."""

    __slots__ = ("dq", "dropped", "thread", "ident")

    def __init__(self, capacity: int):
        self.dq: deque = deque(maxlen=capacity)
        self.dropped = 0
        self.thread = threading.current_thread()
        self.ident = self.thread.ident


_enabled = _env_enabled()
_capacity = _env_capacity()
_tls = threading.local()
# id(ring) -> ring. Registration is a plain dict store (atomic under the
# GIL) so first-emit from ANY frame — including a signal handler — takes
# no lock; keying by object id means a signal-frame re-entry during ring
# creation registers a second ring instead of clobbering the first.
_rings: dict[int, _Ring] = {}
# rings of exited threads, folded here by the collector (bounded); its
# counters are collector-owned (single writer)
_retired: deque = deque(maxlen=_capacity)
_retired_dropped = 0
_seq = itertools.count()  # per-process monotonic id: stable merge order
_installed = False
_node: Optional[str] = None  # this process's node id (workers set it at boot)
_collector_started = False
_collector_gate = itertools.count()  # lock-free single-start gate
_drop_metric = None  # lazy metrics.Counter, created by the collector only
_drop_published = 0  # drops already forwarded to the metric (collector-owned)
_COLLECT_INTERVAL_S = 1.0


def set_node(node: Optional[str]) -> None:
    """Tag this process's events with its node id at the SOURCE (workers
    call this at boot). The live drain infers origin from the reply route,
    but crash-flush files and OTLP resources need it carried in-band."""
    global _node
    _node = node


def get_node() -> Optional[str]:
    return _node


def enabled() -> bool:
    return _enabled


def set_enabled(flag: bool) -> None:
    """Toggle recording (benchmark A/B; tests). Always-on by default."""
    global _enabled
    _enabled = bool(flag)


def configure(capacity: Optional[int] = None) -> None:
    """Resize the rings (keeps the newest events; tests/tuning only —
    a producer racing the swap can lose one in-flight append)."""
    global _capacity, _retired
    if capacity is not None:
        _capacity = max(16, int(capacity))
        for ring in list(_rings.values()):
            ring.dq = deque(ring.dq, maxlen=_capacity)
        _retired = deque(_retired, maxlen=_capacity)


def record(etype: str, request_id: Optional[str] = None, **fields: Any) -> None:
    """Append one event. Hot path: a thread-local ring append — no shared
    lock, no serialization, no I/O; cost is paid only when a consumer
    drains.  Signal-safe: the crash handlers call this from signal frames
    that may have interrupted another ``record`` on the same thread, so
    every step here must be reentrant (deque.append and the counter
    increment below are single-writer or atomic C calls)."""
    if not _enabled:
        return
    ring = getattr(_tls, "ring", None)
    if ring is None:
        ring = _new_ring()
    dq = ring.dq
    if len(dq) == dq.maxlen:
        # only this thread appends to dq: the len check and the bump are
        # single-writer, so the overflow count is exact
        ring.dropped += 1
    dq.append((next(_seq), time.time(), etype, request_id, fields or None))


def _new_ring() -> _Ring:
    ring = _Ring(_capacity)
    _rings[id(ring)] = ring  # atomic dict store — no lock (see module doc)
    _tls.ring = ring
    _ensure_collector()
    return ring


_current_request_id = None  # lazily bound tracing.current_request_id


def active_request_id() -> Optional[str]:
    """The tracing request id bound to this thread, or ``None``. The cheap
    gate for per-read emits: ``core.object.map``/``unmap`` ride every
    zero-copy get, so they fire only inside a traced request (mint-time
    sampling alignment — the same deal spans get). An untraced bulk loop
    pays one thread-local read here instead of a ring append per read."""
    global _current_request_id
    rid_fn = _current_request_id
    if rid_fn is None:
        from ray_tpu.util.tracing import current_request_id as rid_fn

        _current_request_id = rid_fn
    return rid_fn()


def emit(
    etype: str,
    obj_id: Optional[bytes] = None,
    size: Optional[int] = None,
    node: Optional[bytes] = None,
    request_id: Optional[str] = None,
    **fields: Any,
) -> None:
    """Object-plane emit: :func:`record` plus the ``core.object.*`` field
    conventions (ISSUE 19). ``obj_id``/``node`` accept the binary ids the
    runtime carries and land hex-encoded as ``oid``/``node`` (an explicit
    ``node`` field overrides this process's node in ``snapshot()`` — owner
    provenance, not emitter provenance). When no ``request_id`` is passed
    the active one is read from the tracing thread-local, so a request's
    data-plane hops line up under ``obs req <id>`` next to its waterfall.

    Hot path: same zero-lock budget as ``record``, and cheaper — the
    raw (obj_id, size, node, extras) tuple goes into the ring as-is and
    the hex encodes + field-dict build are deferred to :func:`snapshot`,
    so the emitting thread pays only the append (PR 11's rule: cost is
    paid when a consumer drains, not on the path)."""
    if not _enabled:
        return
    if request_id is None:
        global _current_request_id
        rid_fn = _current_request_id
        if rid_fn is None:
            from ray_tpu.util.tracing import current_request_id as rid_fn

            _current_request_id = rid_fn
        request_id = rid_fn()
    # record()'s ring append, inlined (delegating would repack **fields a
    # second time); item[4] is a TUPLE here, not a dict — snapshot()
    # expands it. Nothing else looks inside item[4]: the collector folds
    # and configure() re-deques ring items opaquely.
    ring = getattr(_tls, "ring", None)
    if ring is None:
        ring = _new_ring()
    dq = ring.dq
    if len(dq) == dq.maxlen:
        ring.dropped += 1
    dq.append(
        (next(_seq), time.time(), etype, request_id,
         (obj_id, size, node, fields or None))
    )


def _iter_raw() -> list[tuple]:
    """All events currently held (retired + live rings), merged into
    global emission order by seq. Lock-free: list() over a deque and
    dict.values() are atomic snapshots under the GIL. De-duplicated by
    seq: a snapshot racing the collector's fold can see a just-folded
    ring's events in BOTH the new retired deque and the not-yet-popped
    ring (the fold publishes before unregistering so nothing is ever
    lost — the cheap side of that trade is dropping dups here)."""
    items = list(_retired)
    for ring in list(_rings.values()):
        items.extend(ring.dq)
    items.sort(key=lambda t: t[0])
    out = []
    last_seq = -1
    for item in items:
        if item[0] != last_seq:
            out.append(item)
            last_seq = item[0]
    return out


def snapshot(request_id: Optional[str] = None) -> list[dict]:
    """Events currently held (oldest first, exact emission order), as
    dicts. Optionally filtered to one request.

    Deliberately LOCK-FREE — the SIGTERM crash handler calls this from a
    signal frame that may have interrupted ``record()`` mid-append ON
    THIS THREAD, where taking any non-reentrant lock would deadlock a
    dying worker instead of flushing it."""
    pid = os.getpid()
    out = []
    node = _node
    for seq, ts, etype, rid, fields in _iter_raw():
        if request_id is not None and rid != request_id:
            continue
        ev = {"seq": seq, "ts": ts, "type": etype, "pid": pid}
        if node is not None:
            ev["node"] = node
        if rid is not None:
            ev["request_id"] = rid
        if type(fields) is tuple:
            # deferred emit() payload: (obj_id, size, node, extras) raw
            # off the hot path — format here, on the consumer's dime
            obj_id, size, onode, extras = fields
            if obj_id is not None:
                ev["oid"] = obj_id.hex() if isinstance(obj_id, bytes) else obj_id
            if size is not None:
                ev["size"] = size
            if onode is not None:
                # owner provenance overrides emitter provenance
                ev["node"] = onode.hex() if isinstance(onode, bytes) else onode
            if extras:
                ev.update(extras)
        elif fields:
            ev.update(fields)
        out.append(ev)
    return out


def stats() -> dict:
    # lock-free for the same signal-safety reason as snapshot(): every
    # read here is an atomic snapshot
    rings = list(_rings.values())
    return {
        "enabled": _enabled,
        "capacity": _capacity,
        "size": len(_retired) + sum(len(r.dq) for r in rings),
        "dropped": _retired_dropped + sum(r.dropped for r in rings),
        "rings": len(rings),
    }


def ring_stats() -> list[dict]:
    """Per-ring view (``obs overhead`` / tests): one row per live ring
    plus the retired fold."""
    rows = [
        {
            "thread": r.thread.name,
            "alive": r.thread.is_alive(),
            "size": len(r.dq),
            "dropped": r.dropped,
        }
        for r in list(_rings.values())
    ]
    rows.append(
        {
            "thread": "<retired>",
            "alive": False,
            "size": len(_retired),
            "dropped": _retired_dropped,
        }
    )
    return rows


def clear() -> None:
    """Reset contents + counters (tests/tools). Also rewinds the metric
    publication watermark: after a clear, total drops restart at 0, and
    without the rewind the collector would withhold the events_dropped
    counter until drops re-exceeded the pre-clear total."""
    global _retired_dropped, _drop_published
    for ring in list(_rings.values()):
        ring.dq.clear()
        ring.dropped = 0
    _retired.clear()
    _retired_dropped = 0
    _drop_published = 0


# ---------------------------------------------------------------------------
# background collector
# ---------------------------------------------------------------------------


def _ensure_collector() -> None:
    # lock-free single-start: record() reaches here on a thread's FIRST
    # emit, and the no-shared-lock hot-path contract (tests/test_raylint
    # hot-path check) forbids a lock even on this slow path — the count
    # gate hands exactly one caller the start
    global _collector_started
    if _collector_started or next(_collector_gate) != 0:
        return
    _collector_started = True
    try:
        threading.Thread(
            target=_collector_loop, name="events-collector", daemon=True
        ).start()
    except RuntimeError:
        pass  # interpreter tearing down: stats()/snapshot() still work


def _collect_once() -> None:
    """One collector pass: fold dead-thread rings into the retired deque
    (preserving seq order) and forward the aggregate drop count into the
    lazy ``events_dropped`` metric. Runs ONLY on the collector thread —
    its writes to ``_retired``/``_retired_dropped`` are single-writer."""
    global _retired, _retired_dropped, _drop_metric, _drop_published
    dead = [
        (rid_, ring)
        for rid_, ring in list(_rings.items())
        if not ring.thread.is_alive()
    ]
    if dead:
        # PUBLISH BEFORE UNREGISTERING: build the merged retired deque
        # (old retired + every dead ring, seq-interleaved) and install it
        # as ONE atomic global swap while the dead rings are still in
        # _rings. A crash-flush snapshot racing this pass therefore sees
        # every event at least once — possibly twice for a moment (new
        # retired + not-yet-popped ring), which _iter_raw de-dups by seq
        # — and never a half-built state that loses a dead thread's ring.
        items = list(_retired)
        for _rid, ring in dead:
            items.extend(ring.dq)
        items.sort(key=lambda t: t[0])
        keep = items[-_capacity:]
        # collector-owned counter (single writer); clear() is a tests/tools
        # reset documented to race only advisory state — the next pass
        # re-derives totals from the rings
        _retired_dropped += len(items) - len(keep) + sum(  # raylint: disable=RL017
            ring.dropped for _rid, ring in dead
        )
        _retired = deque(keep, maxlen=_capacity)
        for rid_, _ring in dead:
            _rings.pop(rid_, None)
    total_dropped = _retired_dropped + sum(
        r.dropped for r in list(_rings.values())
    )
    if total_dropped > _drop_published:
        delta = total_dropped - _drop_published
        _drop_published = total_dropped
        if _drop_metric is None:
            from ray_tpu.util.metrics import safe_counter

            # False (not None) when unavailable: stats() still counts
            _drop_metric = safe_counter(
                "events_dropped",
                "flight-recorder events evicted by ring overflow",
            ) or False
        if _drop_metric:
            try:
                _drop_metric.inc(delta)
            except Exception:
                pass


def _collector_loop() -> None:
    while True:
        time.sleep(_COLLECT_INTERVAL_S)
        try:
            _collect_once()
        except Exception:  # raylint: disable=RL007
            # the collector must never take the process down, and the
            # only shared state it touches is advisory
            pass


def collector_pass_for_tests() -> None:
    """Run one synchronous collector pass (deterministic tests)."""
    _collect_once()


# ---------------------------------------------------------------------------
# crash flush
# ---------------------------------------------------------------------------


def events_dir() -> str:
    return os.environ.get(
        "RAY_TPU_EVENTS_DIR",
        os.path.join(tempfile.gettempdir(), "ray_tpu_events"),
    )


def load_crash_files(directory: Optional[str] = None) -> list[dict]:
    """Read back every crash-flush JSONL in ``directory`` (default: the
    events dir) — the postmortem half of the recorder: a killed worker
    can't answer the live drain, but its flushed rings are on disk.
    Events gain ``crash_flush`` (their source file) and the header's
    ``node`` when the event itself carries none."""
    d = directory or events_dir()
    out: list[dict] = []
    if not os.path.isdir(d):
        return out
    for fname in sorted(os.listdir(d)):
        if not fname.endswith(".jsonl"):
            continue
        node = None
        try:
            with open(os.path.join(d, fname)) as f:
                for line in f:
                    rec = json.loads(line)
                    if rec.get("_flight_recorder"):
                        node = rec.get("node")
                        continue  # header line
                    rec.setdefault("crash_flush", fname)
                    if node is not None:
                        rec.setdefault("node", node)
                    out.append(rec)
        except (OSError, ValueError):
            continue
    return out


def flush(path: Optional[str] = None, reason: str = "manual") -> Optional[str]:
    """Dump all rings as JSONL (one event per line in global seq order,
    preceded by a header line with process metadata). Returns the path,
    or None when nothing was recorded. Never raises — a flush failing
    must not mask the crash that triggered it."""
    try:
        events = snapshot()
        if not events:
            return None
        if path is None:
            d = events_dir()
            os.makedirs(d, exist_ok=True)
            path = os.path.join(d, f"events-{os.getpid()}.jsonl")
        with open(path, "w") as f:
            header = {
                "_flight_recorder": 1,
                "pid": os.getpid(),
                "node": _node,
                "reason": reason,
                "time": time.time(),
                **stats(),
            }
            f.write(json.dumps(header) + "\n")
            for ev in events:
                f.write(json.dumps(ev, default=repr) + "\n")
        return path
    except Exception:
        return None


def install_crash_handlers() -> None:
    """Arm flush-on-death (idempotent): unhandled exceptions in any thread
    and SIGTERM (how workers are killed). The previous hooks/handlers are
    chained, and SIGTERM re-raises the default action after flushing so
    the process still dies."""
    global _installed
    if _installed:
        return
    _installed = True

    prev_except = sys.excepthook

    def _excepthook(tp, val, tb):
        record("crash.exception", error=f"{tp.__name__}: {val}")
        flush(reason="excepthook")
        prev_except(tp, val, tb)

    sys.excepthook = _excepthook

    prev_thread = threading.excepthook

    def _thread_hook(args):
        # daemon-thread crashes (engine loops, flushers) matter most here
        record(
            "crash.thread_exception",
            thread=getattr(args.thread, "name", None),
            error=f"{getattr(args.exc_type, '__name__', args.exc_type)}: {args.exc_value}",
        )
        flush(reason="threading.excepthook")
        prev_thread(args)

    threading.excepthook = _thread_hook

    if threading.current_thread() is threading.main_thread():
        import signal

        prev_term = signal.getsignal(signal.SIGTERM)

        def _on_term(signum, frame):
            record("crash.sigterm")
            flush(reason="sigterm")
            if prev_term is signal.SIG_IGN:
                return  # the process chose to ignore SIGTERM: honor that
            if callable(prev_term) and prev_term is not signal.SIG_DFL:
                prev_term(signum, frame)
            else:
                # restore the default action and re-deliver so the process
                # dies with the conventional SIGTERM status
                signal.signal(signal.SIGTERM, signal.SIG_DFL)
                os.kill(os.getpid(), signal.SIGTERM)

        try:
            signal.signal(signal.SIGTERM, _on_term)
        except (ValueError, OSError):
            pass  # non-main interpreter / restricted env: hooks still armed


# ---------------------------------------------------------------------------
# cluster drain (head broadcast — same mailbox as worker stack dumps)
# ---------------------------------------------------------------------------


def collect_cluster_events(
    request_id: Optional[str] = None, timeout: float = 5.0
) -> list[dict]:
    """This process's rings + every live worker's, via the head broadcast
    (``rpc_collect_events``). Events gain a ``node``/``pid`` origin; order
    is (ts, seq) across processes. Best-effort: an unreachable cluster
    returns local events only."""
    out = list(snapshot(request_id))
    try:
        from ray_tpu._private.runtime import get_ctx

        ctx = get_ctx()
        remote = ctx.call("collect_events", timeout=timeout)
    except Exception:
        remote = None
    if remote:
        # the caller's own rings come back through the drain too (as a
        # worker reply, or as the head's "head" entry for an in-process
        # driver) — de-dup by event identity, not by pid: a bare pid
        # check would silently drop a REMOTE node's worker that happens
        # to share the caller's pid
        seen = {(e["pid"], e["seq"], e["ts"]) for e in out}
        for node, per_pid in remote.items():
            for pid, evs in per_pid.items():
                if pid == "_errors" or not isinstance(evs, list):
                    continue
                for ev in evs:
                    if request_id is not None and ev.get("request_id") != request_id:
                        continue
                    key = (ev.get("pid"), ev.get("seq"), ev.get("ts"))
                    if key in seen:
                        continue
                    seen.add(key)
                    ev.setdefault("node", node)
                    out.append(ev)
    out.sort(key=lambda e: (e.get("ts", 0.0), e.get("seq", 0)))
    return out
