"""Flight recorder: an always-on, bounded ring buffer of structured events.

Reference shape: the GCS task-event stream + Ray's debug-state dumps — but
process-local and always armed, so a postmortem of a killed replica or a
preemption storm needs no re-run.  Every process (driver, head, workers)
appends typed events into a fixed-size deque; the steady-state cost is one
lock + tuple append (~sub-microsecond), and memory is bounded by
``capacity`` regardless of uptime.

Three consumers:

* **Live drain** — :func:`collect_cluster_events` gathers every live
  worker's ring through the head (same broadcast/mailbox machinery as the
  worker stack dumps), so ``python -m ray_tpu.obs events`` / ``obs req
  <id>`` can reconstruct a request's life across processes.
* **Crash flush** — :func:`install_crash_handlers` arms ``sys.excepthook``
  / ``threading.excepthook`` / ``SIGTERM`` to dump the ring as JSONL into
  ``RAY_TPU_EVENTS_DIR`` before the process dies.  Workers are killed by
  SIGTERM (proc_handles), so a replica shot mid-stream still leaves its
  last ``capacity`` events on disk.
* **Chrome trace** — ``util.tracing.export_chrome_trace`` renders events
  carrying a ``request_id`` as one per-request lane.

Knobs (environment, read at import):

* ``RAY_TPU_EVENTS`` — ``0`` disables recording entirely (bench A/B).
* ``RAY_TPU_EVENTS_CAPACITY`` — ring size per process (default 8192).
* ``RAY_TPU_EVENTS_DIR`` — crash-flush directory (default
  ``<tempdir>/ray_tpu_events``).
"""

from __future__ import annotations

import itertools
import json
import os
import sys
import tempfile
import threading
import time
from collections import deque
from typing import Any, Optional


def _env_enabled() -> bool:
    return os.environ.get("RAY_TPU_EVENTS", "1").lower() not in ("0", "false", "off")


def _env_capacity() -> int:
    try:
        return max(16, int(os.environ.get("RAY_TPU_EVENTS_CAPACITY", "8192")))
    except ValueError:
        return 8192


_enabled = _env_enabled()
_capacity = _env_capacity()
_lock = threading.Lock()
_ring: deque = deque(maxlen=_capacity)
_seq = itertools.count()  # per-process monotonic id: stable merge order
_installed = False
_dropped = 0  # events recorded before the current ring window (wraparound)
_node: Optional[str] = None  # this process's node id (workers set it at boot)


def set_node(node: Optional[str]) -> None:
    """Tag this process's events with its node id at the SOURCE (workers
    call this at boot). The live drain infers origin from the reply route,
    but crash-flush files and OTLP resources need it carried in-band."""
    global _node
    _node = node


def get_node() -> Optional[str]:
    return _node


def enabled() -> bool:
    return _enabled


def set_enabled(flag: bool) -> None:
    """Toggle recording (benchmark A/B; tests). Always-on by default."""
    global _enabled
    _enabled = bool(flag)


def configure(capacity: Optional[int] = None) -> None:
    """Resize the ring (drops recorded events; tests/tuning only)."""
    global _ring, _capacity
    if capacity is not None:
        with _lock:
            _capacity = max(16, int(capacity))
            _ring = deque(_ring, maxlen=_capacity)


def record(etype: str, request_id: Optional[str] = None, **fields: Any) -> None:
    """Append one event. Hot path: one tuple append, no serialization, no
    I/O — cost is paid only when a consumer drains.

    LOCK-FREE on purpose: ``deque.append`` (bounded) and ``next(count)``
    are single atomic C calls under the GIL, and the crash handlers call
    this from signal frames that may have interrupted another ``record``
    on the same thread — a lock here would deadlock the dying process.
    The ``_dropped`` read-modify-write is the one racy piece; it is an
    advisory wraparound counter and may undercount under contention."""
    global _dropped
    if not _enabled:
        return
    if len(_ring) == _capacity:
        _dropped += 1
    _ring.append((next(_seq), time.time(), etype, request_id, fields or None))


def snapshot(request_id: Optional[str] = None) -> list[dict]:
    """Events currently in the ring (oldest first), as dicts. Optionally
    filtered to one request.

    Deliberately LOCK-FREE: ``list(deque)`` is a single C call, atomic
    under the GIL even while other threads append.  It must stay that
    way — the SIGTERM crash handler calls this from a signal frame that
    may have interrupted ``record()`` mid-append ON THIS THREAD, where
    taking the (non-reentrant) recorder lock would deadlock a dying
    worker instead of flushing it."""
    items = list(_ring)
    pid = os.getpid()
    out = []
    node = _node
    for seq, ts, etype, rid, fields in items:
        if request_id is not None and rid != request_id:
            continue
        ev = {"seq": seq, "ts": ts, "type": etype, "pid": pid}
        if node is not None:
            ev["node"] = node
        if rid is not None:
            ev["request_id"] = rid
        if fields:
            ev.update(fields)
        out.append(ev)
    return out


def stats() -> dict:
    # lock-free for the same signal-safety reason as snapshot(): every
    # read here is a single atomic operation
    return {
        "enabled": _enabled,
        "capacity": _capacity,
        "size": len(_ring),
        "dropped": _dropped,
    }


def clear() -> None:
    global _dropped
    _ring.clear()
    _dropped = 0


# ---------------------------------------------------------------------------
# crash flush
# ---------------------------------------------------------------------------


def events_dir() -> str:
    return os.environ.get(
        "RAY_TPU_EVENTS_DIR",
        os.path.join(tempfile.gettempdir(), "ray_tpu_events"),
    )


def load_crash_files(directory: Optional[str] = None) -> list[dict]:
    """Read back every crash-flush JSONL in ``directory`` (default: the
    events dir) — the postmortem half of the recorder: a killed worker
    can't answer the live drain, but its flushed ring is on disk. Events
    gain ``crash_flush`` (their source file) and the header's ``node``
    when the event itself carries none."""
    d = directory or events_dir()
    out: list[dict] = []
    if not os.path.isdir(d):
        return out
    for fname in sorted(os.listdir(d)):
        if not fname.endswith(".jsonl"):
            continue
        node = None
        try:
            with open(os.path.join(d, fname)) as f:
                for line in f:
                    rec = json.loads(line)
                    if rec.get("_flight_recorder"):
                        node = rec.get("node")
                        continue  # header line
                    rec.setdefault("crash_flush", fname)
                    if node is not None:
                        rec.setdefault("node", node)
                    out.append(rec)
        except (OSError, ValueError):
            continue
    return out


def flush(path: Optional[str] = None, reason: str = "manual") -> Optional[str]:
    """Dump the ring as JSONL (one event per line, preceded by a header
    line with process metadata). Returns the path, or None when the ring
    is empty. Never raises — a flush failing must not mask the crash that
    triggered it."""
    try:
        events = snapshot()
        if not events:
            return None
        if path is None:
            d = events_dir()
            os.makedirs(d, exist_ok=True)
            path = os.path.join(d, f"events-{os.getpid()}.jsonl")
        with open(path, "w") as f:
            header = {
                "_flight_recorder": 1,
                "pid": os.getpid(),
                "node": _node,
                "reason": reason,
                "time": time.time(),
                **stats(),
            }
            f.write(json.dumps(header) + "\n")
            for ev in events:
                f.write(json.dumps(ev, default=repr) + "\n")
        return path
    except Exception:
        return None


def install_crash_handlers() -> None:
    """Arm flush-on-death (idempotent): unhandled exceptions in any thread
    and SIGTERM (how workers are killed). The previous hooks/handlers are
    chained, and SIGTERM re-raises the default action after flushing so
    the process still dies."""
    global _installed
    if _installed:
        return
    _installed = True

    prev_except = sys.excepthook

    def _excepthook(tp, val, tb):
        record("crash.exception", error=f"{tp.__name__}: {val}")
        flush(reason="excepthook")
        prev_except(tp, val, tb)

    sys.excepthook = _excepthook

    prev_thread = threading.excepthook

    def _thread_hook(args):
        # daemon-thread crashes (engine loops, flushers) matter most here
        record(
            "crash.thread_exception",
            thread=getattr(args.thread, "name", None),
            error=f"{getattr(args.exc_type, '__name__', args.exc_type)}: {args.exc_value}",
        )
        flush(reason="threading.excepthook")
        prev_thread(args)

    threading.excepthook = _thread_hook

    if threading.current_thread() is threading.main_thread():
        import signal

        prev_term = signal.getsignal(signal.SIGTERM)

        def _on_term(signum, frame):
            record("crash.sigterm")
            flush(reason="sigterm")
            if prev_term is signal.SIG_IGN:
                return  # the process chose to ignore SIGTERM: honor that
            if callable(prev_term) and prev_term is not signal.SIG_DFL:
                prev_term(signum, frame)
            else:
                # restore the default action and re-deliver so the process
                # dies with the conventional SIGTERM status
                signal.signal(signal.SIGTERM, signal.SIG_DFL)
                os.kill(os.getpid(), signal.SIGTERM)

        try:
            signal.signal(signal.SIGTERM, _on_term)
        except (ValueError, OSError):
            pass  # non-main interpreter / restricted env: hooks still armed


# ---------------------------------------------------------------------------
# cluster drain (head broadcast — same mailbox as worker stack dumps)
# ---------------------------------------------------------------------------


def collect_cluster_events(
    request_id: Optional[str] = None, timeout: float = 5.0
) -> list[dict]:
    """This process's ring + every live worker's, via the head broadcast
    (``rpc_collect_events``). Events gain a ``node``/``pid`` origin; order
    is (ts, seq) across processes. Best-effort: an unreachable cluster
    returns local events only."""
    out = list(snapshot(request_id))
    try:
        from ray_tpu._private.runtime import get_ctx

        ctx = get_ctx()
        remote = ctx.call("collect_events", timeout=timeout)
    except Exception:
        remote = None
    if remote:
        # the caller's own ring comes back through the drain too (as a
        # worker reply, or as the head's "head" entry for an in-process
        # driver) — de-dup by event identity, not by pid: a bare pid
        # check would silently drop a REMOTE node's worker that happens
        # to share the caller's pid
        seen = {(e["pid"], e["seq"], e["ts"]) for e in out}
        for node, per_pid in remote.items():
            for pid, evs in per_pid.items():
                if pid == "_errors" or not isinstance(evs, list):
                    continue
                for ev in evs:
                    if request_id is not None and ev.get("request_id") != request_id:
                        continue
                    key = (ev.get("pid"), ev.get("seq"), ev.get("ts"))
                    if key in seen:
                        continue
                    seen.add(key)
                    ev.setdefault("node", node)
                    out.append(ev)
    out.sort(key=lambda e: (e.get("ts", 0.0), e.get("seq", 0)))
    return out
