"""Top-level API: init/shutdown/remote/get/put/wait/kill/cancel/...

Counterpart of the reference's ``python/ray/_private/worker.py`` public
surface (``ray.init`` :1225, ``get`` :2553, ``put`` :2685, ``wait`` :2750)
minus the daemon zoo: ``init()`` stands up the in-driver Head, registers this
host as the first node (auto-detecting CPUs and TPU chips), and installs the
driver context.
"""

from __future__ import annotations

import atexit
import os
import tempfile
import time
from typing import Any, Optional, Sequence, Union

from ray_tpu import exceptions as rex
from ray_tpu._private import runtime
from ray_tpu._private.config import GLOBAL_CONFIG
from ray_tpu._private.head import Head
from ray_tpu._private.runtime import DriverContext, ObjectRef

_head: Optional[Head] = None
_session_dir: Optional[str] = None


def init(
    address: Optional[str] = None,
    *,
    num_cpus: Optional[int] = None,
    num_tpus: Optional[int] = None,
    num_gpus: Optional[int] = None,
    resources: Optional[dict[str, float]] = None,
    labels: Optional[dict[str, str]] = None,
    object_store_memory: Optional[int] = None,
    ignore_reinit_error: bool = False,
    log_to_driver: bool = True,
    namespace: Optional[str] = None,
    _system_config: Optional[dict[str, Any]] = None,
    _head: Optional[Head] = None,
    _node_id=None,
):
    """Start (or attach to) a cluster and install the driver context.

    With no arguments this host becomes a single-node cluster, like the
    reference's ``ray.init()`` auto-start path. ``_head``/``_node_id`` are the
    attach path used by cluster_utils test clusters.
    """
    global _session_dir
    if runtime.is_initialized():
        if ignore_reinit_error:
            return _context_info()
        raise rex.RayError("ray_tpu.init() called twice; pass ignore_reinit_error=True to ignore")
    GLOBAL_CONFIG.apply_overrides(_system_config)
    if object_store_memory:
        # sizes both the node "memory" resource and the spill watermark
        GLOBAL_CONFIG.object_store_memory = int(object_store_memory)
    if address is not None and address.startswith("ray://"):
        # client mode (reference: ray:// gRPC proxy, util/client/) — here the
        # remote-driver TCP attach IS the client protocol, so the scheme is
        # an alias for it
        address = address[len("ray://"):]
    if (
        address is not None
        and _head is None
        and ":" in address
        and not address.startswith("ray-tpu://")
    ):
        # remote attach over TCP (reference: ray.init(address="host:port"))
        from ray_tpu._private.config import resolve_authkey
        from ray_tpu._private.runtime import RemoteDriverContext
        from ray_tpu._private.worker_main import connect_head

        authkey = resolve_authkey()
        conn = connect_head(address, authkey)
        conn.send(
            (
                "register_driver",
                {
                    "namespace": namespace,
                    "session_token": os.environ.get("RAY_TPU_SESSION_TOKEN"),
                },
            )
        )
        kind, info = conn.recv()
        if kind != "driver_ack":
            raise rex.RayError(f"unexpected handshake reply {kind!r}")
        ctx = RemoteDriverContext(
            conn,
            info["node_id"],
            authkey=authkey,
            head_host=address.rsplit(":", 1)[0],
            address=address,
            session_token=info.get("session_token"),
        )
        resumed_ns = info.get("namespace")
        if namespace and resumed_ns and resumed_ns != namespace:
            # a stale RAY_TPU_SESSION_TOKEN must not silently put the
            # driver's named actors in the wrong namespace
            ctx.shutdown()
            raise rex.RayError(
                f"session token resumed namespace {resumed_ns!r} but "
                f"namespace={namespace!r} was requested; unset "
                f"RAY_TPU_SESSION_TOKEN or drop the namespace argument"
            )
        ctx.namespace = resumed_ns or namespace or "default"
        runtime.set_ctx(ctx)
        from ray_tpu._private import events as _events

        _events.install_crash_handlers()
        atexit.register(_atexit_shutdown)
        return _context_info()
    if address is not None and _head is None:
        from ray_tpu.cluster_utils import resolve_address

        cluster = resolve_address(address)
        if cluster.head_node is None:
            raise rex.RayError("Cluster has no head node")
        _head, _node_id = cluster.head, cluster.head_node
    if _head is not None:
        head = _head
        node_id = _node_id
    else:
        _session_dir = tempfile.mkdtemp(prefix="ray_tpu_session_")
        sock = os.path.join(_session_dir, "head.sock")
        # RAY_TPU_AUTHKEY makes this cluster attachable from other
        # processes/hosts (scripts.py head path uses the same secret);
        # without it, a fresh random key isolates the session
        from ray_tpu._private.config import resolve_authkey as _rk

        head = Head(
            sock,
            authkey=_rk() if os.environ.get("RAY_TPU_AUTHKEY") else os.urandom(16),
        )
        head.start()
        res = dict(resources or {})
        res.setdefault("CPU", float(num_cpus if num_cpus is not None else os.cpu_count() or 1))
        if num_gpus is not None:
            res.setdefault("GPU", float(num_gpus))
        tpu_chips = num_tpus
        if tpu_chips is None:
            from ray_tpu.accelerators import tpu as tpu_accel

            tpu_chips = tpu_accel.detect_num_chips()
        if tpu_chips:
            res.setdefault("TPU", float(tpu_chips))
            from ray_tpu.accelerators import tpu as tpu_accel

            for k, v in tpu_accel.extra_resources(tpu_chips).items():
                res.setdefault(k, v)
        res.setdefault("memory", _default_memory(object_store_memory))
        node_id = head.add_node(res, labels=labels)
    ctx = DriverContext(head, node_id.binary())
    if namespace:
        ctx.namespace = namespace
    runtime.set_ctx(ctx)
    _set_head(head)
    # flight recorder: the driver's event ring flushes to JSONL on
    # unhandled exceptions / SIGTERM too (events.py; workers arm theirs
    # in worker_main) — postmortems cover the whole process tree
    from ray_tpu._private import events as _events

    _events.install_crash_handlers()
    atexit.register(_atexit_shutdown)
    return _context_info()


def _set_head(head):
    global _head
    _head = head


def _default_memory(object_store_memory):
    if object_store_memory:
        return float(object_store_memory)
    if GLOBAL_CONFIG.object_store_memory:
        return float(GLOBAL_CONFIG.object_store_memory)
    try:
        import psutil

        return float(psutil.virtual_memory().total * 0.3)
    except Exception:
        return float(8 << 30)


def _context_info():
    return {"node_id": runtime.get_ctx().node_id_bin.hex(), "session_dir": _session_dir}


def _atexit_shutdown():
    try:
        shutdown()
    except Exception:
        pass


def is_initialized() -> bool:
    return runtime.is_initialized()


def shutdown():
    global _head
    if not runtime.is_initialized():
        return
    ctx = runtime.get_ctx()
    ctx.shutdown()
    runtime.set_ctx(None)
    if _head is not None:
        _head.shutdown()
        _head = None


def put(value: Any) -> ObjectRef:
    return runtime.get_ctx().put(value)


def get(refs: Union[ObjectRef, Sequence[ObjectRef]], *, timeout: Optional[float] = None):
    ctx = runtime.get_ctx()
    if isinstance(refs, ObjectRef):
        return ctx.get([refs], timeout)[0]
    if isinstance(refs, (list, tuple)):
        for r in refs:
            if not isinstance(r, ObjectRef):
                raise TypeError(f"ray_tpu.get() takes ObjectRefs, got {type(r)}")
        return ctx.get(list(refs), timeout)
    raise TypeError(f"ray_tpu.get() takes an ObjectRef or a list, got {type(refs)}")


def wait(
    refs: Sequence[ObjectRef],
    *,
    num_returns: int = 1,
    timeout: Optional[float] = None,
    fetch_local: bool = True,
):
    if isinstance(refs, ObjectRef):
        raise TypeError("ray_tpu.wait() takes a list of ObjectRefs")
    refs = list(refs)
    if len(set(refs)) != len(refs):
        raise ValueError("wait() got duplicate ObjectRefs")
    if num_returns > len(refs):
        raise ValueError("num_returns > number of refs")
    if num_returns <= 0:
        raise ValueError("num_returns must be > 0")
    return runtime.get_ctx().wait(refs, num_returns, timeout, fetch_local)


def remote(*args, **kwargs):
    from ray_tpu.remote_function import remote_decorator

    return remote_decorator(args, kwargs)


def kill(actor, *, no_restart: bool = True):
    from ray_tpu.actor import ActorHandle

    if not isinstance(actor, ActorHandle):
        raise TypeError("ray_tpu.kill() takes an actor handle")
    runtime.get_ctx().call("kill_actor", actor_id=actor._actor_id, no_restart=no_restart)


def cancel(ref: ObjectRef, *, force: bool = False, recursive: bool = True):
    if not isinstance(ref, ObjectRef):
        raise TypeError("ray_tpu.cancel() takes an ObjectRef")
    # task id = first 12 bytes of a return object id + index; the head keys
    # tasks by full task_id, so reconstruct it
    from ray_tpu._private.ids import TaskID

    task_id = ref.binary()[:12] + b"\x00\x00\x00\x00"
    runtime.get_ctx().call("cancel_task", task_id=task_id, force=force)


def nodes():
    return runtime.get_ctx().call("nodes")


def cluster_resources() -> dict[str, float]:
    return runtime.get_ctx().call("cluster_resources")


def available_resources() -> dict[str, float]:
    return runtime.get_ctx().call("available_resources")


class RuntimeContext:
    """Reference: ``ray.runtime_context.RuntimeContext``."""

    def __init__(self, ctx):
        self._ctx = ctx

    def get_node_id(self) -> str:
        return self._ctx.node_id_bin.hex()

    def get_actor_id(self) -> Optional[str]:
        inst = getattr(self._ctx, "current_actor", None)
        return None if inst is None else inst

    @property
    def was_current_actor_reconstructed(self) -> bool:
        return False

    def get_assigned_resources(self) -> dict:
        return {}


def get_runtime_context() -> RuntimeContext:
    return RuntimeContext(runtime.get_ctx())


def timeline() -> list[dict]:
    """Task state-transition events (reference: ``ray.timeline`` Chrome trace
    from the GCS task-event table)."""
    return runtime.get_ctx().call("task_events")
