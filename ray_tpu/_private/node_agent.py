"""Node agent: the per-host daemon that joins a remote machine to a cluster.

Counterpart of the reference's raylet + ``ray start --address=`` node
launcher (``python/ray/scripts/scripts.py:566``, ``_private/services.py:1485``
— the raylet registers the node with GCS and owns the local worker pool).
TPU-first simplification: the agent is a thin spawn proxy — scheduling stays
centralized in the head; the agent's only jobs are (a) registering this
host's resources and (b) exec'ing worker processes when the head asks, each
of which dials the head's TCP control plane itself.

Run via ``python -m ray_tpu start --address=HOST:PORT``.
"""

from __future__ import annotations

import os
import socket
import subprocess
import sys
import threading
from typing import Optional


def shutdown_conn(conn) -> None:
    """Force-close a multiprocessing Connection that another thread may be
    blocked recv'ing on. ``conn.close()`` alone only drops the fd-table
    entry — the in-flight read keeps the kernel file description open, so no
    FIN is sent and BOTH sides block forever. SHUT_RDWR interrupts the read
    and tears the TCP stream down immediately."""
    try:
        s = socket.socket(fileno=conn.fileno())
    except OSError:
        return
    try:
        s.shutdown(socket.SHUT_RDWR)
    except OSError:
        pass
    finally:
        s.detach()
    try:
        conn.close()
    except OSError:
        pass


#: raylint RL017 — _procs is appended/pruned-by-rebind ONLY on the agent's
#: run thread; the stack-dump thread takes a GIL-atomic list snapshot
#: (iteration over either the old or the rebound list is correct — dumps
#: are best-effort diagnostics)
LOCKFREE = ("NodeAgent._procs: atomic",)


class NodeAgent:
    def __init__(
        self,
        address: str,
        authkey: bytes,
        resources: Optional[dict] = None,
        labels: Optional[dict] = None,
    ):
        from ray_tpu._private.worker_main import connect_head

        self.address = address
        self.authkey = authkey
        self.resources = dict(resources or {"CPU": float(os.cpu_count() or 1)})
        self.labels = dict(labels or {})
        self._procs: list[subprocess.Popen] = []
        self._by_token: dict[str, subprocess.Popen] = {}
        # template-forked workers: token -> ForkedProc (pidfd-pinned).
        # Written by the template's report thread, read by kill/dump/
        # shutdown paths — always under _forked_lock.
        self._forked: dict[str, object] = {}
        self._forked_lock = threading.Lock()
        self._template = None
        self._stop = threading.Event()
        self.conn = connect_head(address, authkey)
        # This host's slice of the object plane: a local arena for workers'
        # writes plus a data server from which ANY node pulls this host's
        # objects directly (reference: each raylet's plasma store + object
        # manager; the head keeps only the directory — data_plane.py).
        from ray_tpu._private import shm_store
        from ray_tpu._private.config import GLOBAL_CONFIG
        from ray_tpu._private.data_plane import DataServer

        self.arena_name = None
        if GLOBAL_CONFIG.object_store_arena_bytes > 0:
            self.arena_name = shm_store.create_arena(
                GLOBAL_CONFIG.object_store_arena_bytes
            )
        import uuid as _uuid

        self._seg_prefix = f"rtps-{_uuid.uuid4().hex[:8]}-"
        self.data_server = DataServer(authkey)
        data_address = (self._my_ip(), self.data_server.port)
        self.conn.send(
            (
                "register_agent",
                {
                    "resources": self.resources,
                    "labels": self.labels,
                    "pid": os.getpid(),
                    "data_address": data_address,
                    "arena_name": self.arena_name,
                },
            )
        )
        kind, info = self.conn.recv()
        assert kind == "agent_ack", kind
        self.node_id_bin: bytes = info["node_id"]
        self._apply_shipped_config(info)

    def _apply_shipped_config(self, ack_info: dict) -> None:
        """Head-shipped ``_system_config`` overrides apply to THIS agent
        process and (via env) to every worker it spawns — a local
        ``RAY_TPU_*`` env var set by the operator still wins on this host."""
        from ray_tpu._private import config as _cfg

        shipped = ack_info.get("config") or {}
        _cfg.apply_shipped(shipped)
        self._config_env = {
            f"RAY_TPU_{k.upper()}": str(getattr(_cfg.GLOBAL_CONFIG, k))
            for k in shipped
            if hasattr(_cfg.GLOBAL_CONFIG, k)
        }

    # -- serve loop --------------------------------------------------------

    def run(self) -> None:
        """Blocks serving spawn requests until the head hangs up for good
        (a restarted head is retried for head_reconnect_grace_s; the agent
        re-registers under its ORIGINAL node id so restored object
        locators stay routable — reference: raylet reconnect window,
        ray_config_def.h:56-60)."""
        self._send_lock = threading.Lock()
        threading.Thread(target=self._stats_loop, daemon=True).start()
        try:
            while not self._stop.is_set():
                try:
                    msg = self.conn.recv()
                except (EOFError, OSError):
                    if self._stop.is_set() or not self._reconnect():
                        break
                    continue
                if msg[0] == "spawn_worker":
                    self._spawn(msg[1])
                elif msg[0] == "free_shm":
                    # the head routed a free of an object living on THIS
                    # host (head._release_loc)
                    from ray_tpu._private.log_util import warn_throttled
                    from ray_tpu._private.shm_store import free_location

                    try:
                        free_location(msg[1])
                    except Exception as e:  # noqa: BLE001 - frees are best-effort
                        warn_throttled("node agent: free_shm", e)
                elif msg[0] == "dump_workers":
                    # on-demand stack dumps of THIS host's workers
                    # (reporter.py SIGUSR1 machinery) — off-thread, or the
                    # ~2s dump poll would stall spawn/kill/free handling
                    threading.Thread(
                        target=self._dump_workers, args=(msg[1]["req_id"],), daemon=True
                    ).start()
                elif msg[0] == "kill_worker":
                    # registration-timeout path: the head gave up on this
                    # spawn; kill it here so a wedged interpreter doesn't
                    # leak on the host (head.py _respawn_timed_out)
                    tok = msg[1].get("token", "")
                    p = self._by_token.pop(tok, None)
                    if p is not None and p.poll() is None:
                        p.terminate()
                    with self._forked_lock:
                        fp = self._forked.pop(tok, None)
                    if fp is not None:
                        fp.terminate()
                elif msg[0] == "exit":
                    break
        finally:
            self.shutdown()

    def start(self) -> "NodeAgent":
        threading.Thread(target=self.run, daemon=True).start()
        return self

    def _worker_env(self) -> tuple[dict, str]:
        import ray_tpu

        pkg_root = os.path.dirname(os.path.dirname(os.path.abspath(ray_tpu.__file__)))
        env = dict(os.environ)
        env["PYTHONPATH"] = pkg_root + os.pathsep + env.get("PYTHONPATH", "")
        for k, v in getattr(self, "_config_env", {}).items():
            env.setdefault(k, v)  # operator's explicit env still wins
        if self.arena_name:
            # workers write their objects into THIS host's arena; the head
            # receives only the locator (see WorkerContext.put_serialized)
            env["RAY_TPU_ARENA"] = self.arena_name
        else:
            env.pop("RAY_TPU_ARENA", None)
        # over-arena-cap objects get dedicated segments tagged with this
        # agent's prefix, so shutdown can sweep any the head never freed
        env["RAY_TPU_SEG_PREFIX"] = self._seg_prefix
        return env, pkg_root

    def _ensure_template(self):
        """This host's forkserver template (head._ensure_template analog;
        shared spawn_template helper). Replaced if it died."""
        from ray_tpu._private.config import GLOBAL_CONFIG

        if not GLOBAL_CONFIG.worker_forkserver_enabled:
            return None
        tmpl = getattr(self, "_template", None)
        if tmpl is not None and tmpl.alive():
            return tmpl
        from ray_tpu._private.proc_handles import spawn_template

        env, _ = self._worker_env()
        self._template = spawn_template(
            self.address,
            self.authkey,
            self.node_id_bin,
            env,
            remote=True,
            on_spawn=self._on_template_spawn,
        )
        return self._template

    def _on_template_spawn(self, token: str, proc) -> None:
        with self._forked_lock:
            self._forked[token] = proc

    def _spawn(self, info: dict) -> None:
        token = info.get("token", "")
        if not info.get("container"):
            tmpl = self._ensure_template()
            if tmpl is not None and tmpl.fork(token):
                self._prune_forked()  # every spawn path sweeps, or the
                return  # token->handle map (and its pidfds) grows forever
        env, pkg_root = self._worker_env()
        argv = [
            sys.executable,
            "-m",
            "ray_tpu._private.worker_main",
            self.address,
            self.authkey.hex(),
            self.node_id_bin.hex(),
            token,
            "--remote",
        ]
        if info.get("container"):
            from ray_tpu._private.runtime_env import container_wrap

            argv, env = container_wrap(argv, env, pkg_root, info["container"])
        popen = subprocess.Popen(argv, env=env)
        self._procs.append(popen)
        if token:
            self._by_token[token] = popen
        from ray_tpu._private.reporter import reap_stack_file

        for p in self._procs:
            if p.poll() is not None:
                reap_stack_file(p.pid)
        self._procs = [p for p in self._procs if p.poll() is None]
        self._by_token = {t: p for t, p in self._by_token.items() if p.poll() is None}
        self._prune_forked()

    def _prune_forked(self) -> None:
        from ray_tpu._private.reporter import reap_stack_file

        with self._forked_lock:
            dead = [t for t, fp in self._forked.items() if not fp.is_alive()]
            for t in dead:
                fp = self._forked.pop(t)
                reap_stack_file(fp.pid)
                fp.close()

    def _dump_workers(self, req_id: str) -> None:
        from ray_tpu._private.reporter import dump_pids

        pids = [p.pid for p in self._procs if p.poll() is None]
        with self._forked_lock:
            pids += [fp.pid for fp in self._forked.values() if fp.is_alive()]
        try:
            stacks = dump_pids(pids)
            with self._send_lock:
                self.conn.send(("worker_stacks", {"req_id": req_id, "stacks": stacks}))
        except Exception:
            pass  # conn died: the head's dump call times out gracefully

    def _stats_loop(self) -> None:
        """Ship /proc node stats to the head every few seconds (reference:
        reporter_agent.py's periodic psutil report)."""
        import time as _time

        from ray_tpu._private.reporter import node_stats

        from ray_tpu._private.config import GLOBAL_CONFIG
        from ray_tpu._private.log_util import warn_throttled

        while not self._stop.is_set():
            _time.sleep(GLOBAL_CONFIG.node_stats_report_interval_s)
            try:
                stats = node_stats()
                with self._send_lock:
                    self.conn.send(("agent_stats", stats))
            except Exception as e:
                # conn mid-reconnect: next tick retries
                warn_throttled("node agent: stats report", e)

    def _reconnect(self) -> bool:
        import time

        from ray_tpu._private.config import GLOBAL_CONFIG
        from ray_tpu._private.worker_main import connect_head

        deadline = time.monotonic() + GLOBAL_CONFIG.head_reconnect_grace_s
        while time.monotonic() < deadline and not self._stop.is_set():
            try:
                conn = connect_head(self.address, self.authkey, retries=1)
                conn.send(
                    (
                        "register_agent",
                        {
                            "resources": self.resources,
                            "labels": self.labels,
                            "pid": os.getpid(),
                            "data_address": (self._my_ip(conn), self.data_server.port),
                            "arena_name": self.arena_name,
                            "node_id": self.node_id_bin,
                        },
                    )
                )
                kind, info = conn.recv()
                if kind != "agent_ack":
                    raise OSError(f"unexpected reattach reply {kind!r}")
                self.conn = conn
                self.node_id_bin = info["node_id"]
                self._apply_shipped_config(info)  # restarted head may differ
                return True
            except Exception:
                time.sleep(0.5)
        return False

    def _my_ip(self, conn=None) -> str:
        """The IP other hosts can reach this agent's data server on: the
        local address of the control connection to the head (routable by
        construction; '127.0.0.1' stays loopback for same-host tests)."""
        import socket as _socket

        try:
            s = _socket.socket(fileno=os.dup((conn or self.conn).fileno()))
            try:
                return s.getsockname()[0]
            finally:
                s.close()  # closes only the dup'd fd
        except OSError:
            return "127.0.0.1"

    def shutdown(self) -> None:
        self._stop.set()
        if self._template is not None:
            self._template.shutdown()
            self._template = None
        for p in self._procs:
            if p.poll() is None:
                p.terminate()
        with self._forked_lock:
            for fp in self._forked.values():
                fp.terminate()
            self._forked.clear()
        for p in self._procs:
            try:
                p.wait(timeout=3)
            except Exception:
                p.kill()
        self.data_server.shutdown()
        if self.arena_name:
            from ray_tpu._private import shm_store

            shm_store.unlink_arena(self.arena_name)
        # sweep worker segments the head never freed (crashed producers,
        # refs alive at shutdown) — identifiable by this agent's prefix
        import glob as _glob

        for path in _glob.glob(f"/dev/shm/{self._seg_prefix}*"):
            try:
                os.unlink(path)
            except OSError:
                pass
        shutdown_conn(self.conn)
