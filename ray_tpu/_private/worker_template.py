"""Forkserver template: per-node warm process that forks workers on demand.

Reference: the raylet's worker pool pre-starts idle language workers so a
lease never pays interpreter boot (``src/ray/raylet/worker_pool.h:152``,
``maximum_startup_concurrency``). The TPU-native build goes one step
further: instead of keeping N warm *idle* processes around, each node keeps
ONE warm template process with the worker module graph already imported,
and every worker (plain or actor) is an ``os.fork()`` of it — ~5-10ms
instead of a ~300ms+ cold ``python -m`` boot, with memory shared
copy-on-write. This is the same design as CPython's own
``multiprocessing.forkserver``, specialised for our worker entrypoint.

Protocol: the spawner (head or node agent) writes one line per spawn
request to this process's stdin — the worker's startup token — and the
template forks a child that becomes a normal worker (connects to the head,
registers with that token). Lines are < PIPE_BUF so concurrent writers
can't interleave. stdin EOF (spawner died) exits the template.

Fork safety: the template stays single-threaded for its whole life (the
import of worker_main starts no threads — asserted below), so a fork can
never inherit a held lock. Children reset SIGCHLD (the template sets
SIG_IGN so the kernel auto-reaps workers; a worker running user code that
uses ``subprocess`` needs default semantics back) and close the command
pipe so only the template ever reads it.
"""

from __future__ import annotations

import os
import signal
import sys


def main(
    socket_path: str,
    authkey_hex: str,
    node_id_hex: str,
    remote: bool,
    report_fd: int = 0,
) -> None:
    # The point of the template: pay the import graph ONCE, before any fork.
    import ray_tpu._private.worker_main as worker_main  # noqa: PLC0415

    # Modules workers otherwise lazy-import at their first task/actor —
    # cold-spawned workers defer these to keep boot light, but a forked
    # worker gets them free via copy-on-write (none start threads, which
    # the active_count() guard below would catch):
    import asyncio  # noqa: F401  (async actor event loops)
    import concurrent.futures  # noqa: F401  (threaded actors / io pools)
    import inspect  # noqa: F401  (actor engine selection)

    import ray_tpu._private.data_plane  # noqa: F401  (remote arg fetches)
    import ray_tpu._private.runtime_env  # noqa: F401  (renv.applied per task)

    import threading

    if threading.active_count() != 1:  # pragma: no cover - fork-safety guard
        print(
            "[ray_tpu] worker_template: import started threads; forked workers "
            "may inherit held locks",
            file=sys.stderr,
        )
    signal.signal(signal.SIGCHLD, signal.SIG_IGN)  # kernel reaps forked workers
    authkey = bytes.fromhex(authkey_hex)
    node_id = bytes.fromhex(node_id_hex)
    stdin = sys.stdin.buffer.raw if hasattr(sys.stdin.buffer, "raw") else sys.stdin.buffer
    buf = b""
    while True:
        try:
            chunk = stdin.read(4096)
        except OSError:
            return
        if not chunk:
            return  # spawner closed the pipe: shut down
        buf += chunk
        while b"\n" in buf:
            line, buf = buf.split(b"\n", 1)
            token = line.decode().strip()
            if not token:
                continue
            try:
                pid = os.fork()
            except OSError as e:
                # EAGAIN/ENOMEM under pressure: fail THIS spawn (its
                # registration timeout covers the loss), keep the template
                # alive for the requests still buffered behind it
                print(
                    f"[ray_tpu] worker_template: fork failed: {e}",
                    file=sys.stderr,
                )
                continue
            if pid == 0:
                # -- child: become a worker ---------------------------------
                signal.signal(signal.SIGCHLD, signal.SIG_DFL)
                for fd in (0, report_fd) if report_fd else (0,):
                    try:
                        os.close(fd)  # command + report pipes stay with the
                    except OSError:  # template only
                        pass
                try:
                    worker_main.main(
                        socket_path, authkey, node_id, token, remote=remote
                    )
                except (ConnectionError, EOFError, FileNotFoundError):
                    # cluster died while this worker forked: quiet exit.
                    # Deliberately NOT all OSError — ENOSPC/EMFILE are real
                    # faults that must keep their traceback below.
                    pass
                except BaseException:  # noqa: BLE001 - worker must not fall
                    import traceback  # back into the template's read loop

                    traceback.print_exc()
                os._exit(0)
            if report_fd:
                # token -> pid report: the spawner's kill/reap paths need the
                # child pid before the worker ever registers with the head
                try:
                    os.write(report_fd, f"{token} {pid}\n".encode())
                except OSError:
                    pass


if __name__ == "__main__":
    main(
        sys.argv[1],
        sys.argv[2],
        sys.argv[3],
        sys.argv[4] == "remote" if len(sys.argv) > 4 else False,
        int(sys.argv[5]) if len(sys.argv) > 5 else 0,
    )
