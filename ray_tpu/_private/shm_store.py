"""Shared-memory object store (plasma equivalent).

The reference runs a plasma store inside each raylet: an mmap + dlmalloc arena
with a unix-socket flatbuffer protocol, LRU eviction and create-backpressure
(``src/ray/object_manager/plasma/store.h:55``). On a TPU host the picture is
simpler: every process that needs zero-copy access is on the same machine, and
device-resident arrays live in HBM addressed by sharding specs — the host
store only carries host-side payloads (batches, checkpending state, small
tensors, control data). So instead of a separate daemon we use one POSIX shm
segment per large object, created by whichever process produced the value and
owned (for unlink purposes) by the head:

* producer lays out [header][buffer0][buffer1...] with 64-byte alignment,
* consumers attach by name and reconstruct the pickled value with pickle-5
  out-of-band buffers pointing straight into the mapping (zero copy),
* the head records {object_id -> ShmLocation} and unlinks on free/shutdown.

Small objects (<= max_direct_call_object_size) never touch shm; they ride the
control-plane socket inline, like the reference's in-process memory store
(``store_provider/memory_store/memory_store.cc``).
"""

from __future__ import annotations

import dataclasses
import itertools
import os
import pickle
import sys
import threading
import time
import uuid
from multiprocessing import resource_tracker, shared_memory
from typing import Optional

from ray_tpu._private import events
from ray_tpu._private.serialization import SerializedValue

_ALIGN = 64

# flight-recorder events this module emits (raylint RL012 registry): a
# consumer attaching to an object's bytes and releasing them again. Both
# carry segment/offset provenance (this layer doesn't know object ids —
# the put/locator events tie object id to segment).
EVENT_NAMES = (
    "core.object.map",
    "core.object.unmap",
)

#: raylint RL017 registry — the pin ledger is written only via the two
#: GIL-atomic helpers below (dict store / dict pop), so arena pin/unpin
#: stays on the PR 11 zero-lock hot path:
#:
#: - _pins: token -> (segment, offset, size, ts); note_pin is a plain
#:   dict store from the pinning thread, drop_pin a plain pop (either the
#:   same thread or the GC finalizer thread — one writer per token, so no
#:   read-modify-write race). pin_stats() reads an atomic list() copy.
LOCKFREE = ("_pins: atomic",)

# Process-local arena pin ledger: every live ``_PinnedBlock`` (= one
# arena pin) registers here so the cluster leak audit can prove "every
# pin is held by a live reader" and flag pinned-forever consumers by age
# (``head.rpc_object_audit`` read-lease threshold). Token is a process
# counter; store/pop are single GIL-atomic dict ops (no lock — __del__
# may run from any thread).
_pins: dict[int, tuple[str, int, int, float]] = {}
_pin_ids = itertools.count(1)


def note_pin(token: int, name: str, offset: int, size: int) -> None:
    """Register a live arena pin (hot path: one atomic dict store)."""
    _pins[token] = (name, offset, size, time.time())


def drop_pin(token: int) -> None:
    """Release a pin's ledger entry (hot path: one atomic dict pop)."""
    _pins.pop(token, None)


def pin_stats() -> dict:
    """This process's live arena pins (leak-audit input): total pinned
    bytes, count, and per-pin provenance with age. Lock-free snapshot."""
    now = time.time()
    rows = [
        {"seg": name, "offset": off, "size": size, "age_s": now - ts}
        for name, off, size, ts in list(_pins.values())
    ]
    return {
        "pinned_bytes": sum(r["size"] for r in rows),
        "count": len(rows),
        "oldest_age_s": max((r["age_s"] for r in rows), default=0.0),
        "pins": rows,
    }


def _align(n: int) -> int:
    return (n + _ALIGN - 1) & ~(_ALIGN - 1)


@dataclasses.dataclass
class ShmLocation:
    name: str
    header_len: int
    buffer_lens: list[int]
    total_size: int
    #: Set for objects living in the native arena (``_native/arena.cc``):
    #: ``name`` is then the arena segment and ``offset``/``gen`` identify the
    #: allocation for pin/free. None = dedicated POSIX segment (legacy path).
    offset: Optional[int] = None
    gen: int = 0
    #: Binary NodeID of the node whose host holds the bytes (object
    #: directory role — reference: object_manager's object location). The
    #: head routes frees to the owning host and consumers on other hosts
    #: pull via the data plane (``data_plane.py``). None = pre-directory
    #: writer (treated as head-host).
    node: Optional[bytes] = None


# ---------------------------------------------------------------------------
# native arena (plasma-equivalent allocator; see ray_tpu/_native/arena.cc)
# ---------------------------------------------------------------------------

_ARENA_ENV = "RAY_TPU_ARENA"
_arena_lock = threading.Lock()
_arenas: dict[str, "object"] = {}  # name -> Arena (attached mappings, cached)
_write_arena_name: Optional[str] = None


def create_arena(size: int) -> Optional[str]:
    """Head-side: create this host's arena. Returns its name (for worker env
    + later unlink) or None when the native library is unavailable.

    The requested size is clamped to 80% of the shm filesystem's FREE space:
    the segment is sparse, so ftruncate would happily "succeed" past the
    tmpfs limit and the first write into an uncommittable page then SIGBUSes
    the writer (common in containers with a small --shm-size). Clamping
    keeps the 90%-of-capacity degrade watermark (runtime.store_value)
    meaningful."""
    global _write_arena_name
    from ray_tpu import _native

    try:
        st = os.statvfs("/dev/shm")
        free = st.f_bavail * st.f_frsize
        size = max(min(size, int(free * 0.8)), 1024 * 1024)
    except OSError:
        pass
    name = f"/rta-{os.getpid():x}-{uuid.uuid4().hex[:8]}"
    arena = _native.Arena.create(name, size)
    if arena is None:
        return None
    with _arena_lock:
        _arenas[name] = arena
        _write_arena_name = name
    return name


def attach_arena(name: str) -> Optional["object"]:
    """Attach (once per process, cached) to an arena by segment name."""
    with _arena_lock:
        a = _arenas.get(name)
    if a is not None:
        return a
    from ray_tpu import _native

    a = _native.Arena.attach(name)
    if a is not None:
        with _arena_lock:
            _arenas.setdefault(name, a)
            a = _arenas[name]
    return a


def set_write_arena(name: Optional[str]) -> None:
    """Select the arena new objects are written into (worker startup reads
    the head-provided ``RAY_TPU_ARENA`` env; the head/driver sets directly)."""
    global _write_arena_name
    _write_arena_name = name


def _current_write_arena():
    global _write_arena_name
    name = _write_arena_name
    if name is None:
        name = os.environ.get(_ARENA_ENV) or None
        if name is None:
            return None
        _write_arena_name = name
    return attach_arena(name)


def unlink_arena(name: str) -> None:
    with _arena_lock:
        arena = _arenas.pop(name, None)
    if arena is not None:
        arena.unlink()
    global _write_arena_name
    if _write_arena_name == name:
        _write_arena_name = None


def _untrack(shm: shared_memory.SharedMemory) -> None:
    # Python's resource_tracker unlinks segments created by a process when
    # that process exits, which would tear objects out from under other
    # readers. Lifetime is owned by the head instead (explicit unlink).
    try:
        resource_tracker.unregister(shm._name, "shared_memory")  # type: ignore[attr-defined]
    except Exception:
        pass


def write_shm(sv: SerializedValue) -> ShmLocation:
    """Lay a serialized value out in shared memory.

    Small/medium values go into the native arena when one is attached (a
    single allocation under the arena lock — no per-object syscalls); large
    values, or everything when the native path is unavailable, get a
    dedicated POSIX segment (zero-copy reads, mapping outlives unlink)."""
    from ray_tpu._private.config import GLOBAL_CONFIG

    if sv.total_size <= GLOBAL_CONFIG.arena_max_object_bytes:
        arena = _current_write_arena()
        if arena is not None:
            loc = _write_arena(arena, sv)
            if loc is not None:
                return loc  # else: arena full — fall through to a segment
    return _write_segment(sv)


def layout_views(mv, header_len: int, buffer_lens: list[int]):
    """Split a laid-out object ([header][buf0][buf1...], 64-byte aligned —
    the inverse of ``_layout``) into (header view, [PickleBuffer views]).
    THE one place the layout walk lives; shm readers, the data plane, and
    the head's inline fallback all deserialize through it."""
    header = mv[:header_len]
    bufs = []
    off = _align(header_len)
    for n in buffer_lens:
        bufs.append(pickle.PickleBuffer(mv[off : off + n]))
        off = _align(off + n)
    return header, bufs


def _layout(sv: SerializedValue) -> tuple[list[int], int]:
    """Aligned buffer offsets + total size for [header][buf0][buf1...]."""
    hlen = len(sv.header)
    offs = [_align(hlen)]
    for b in sv.buffers[:-1] if sv.buffers else []:
        offs.append(_align(offs[-1] + b.raw().nbytes))
    total = (offs[-1] + sv.buffers[-1].raw().nbytes) if sv.buffers else hlen
    return offs, max(total, 1)


def _copy_into(mv, sv: SerializedValue, offs: list[int]) -> list[int]:
    """Lay the serialized value out in ``mv``; returns buffer lengths."""
    mv[: len(sv.header)] = sv.header
    lens = []
    for off, b in zip(offs, sv.buffers):
        raw = b.raw()
        n = raw.nbytes
        mv[off : off + n] = raw.cast("B") if raw.format != "B" or raw.ndim != 1 else raw
        lens.append(n)
    return lens


def _write_arena(arena, sv: SerializedValue) -> Optional[ShmLocation]:
    offs, total = _layout(sv)
    r = arena.alloc(total)
    if r is None:
        return None
    base, gen = r
    lens = _copy_into(arena.view(base, total), sv, offs)
    return ShmLocation(arena.name, len(sv.header), lens, total, offset=base, gen=gen)


def _write_segment(sv: SerializedValue) -> ShmLocation:
    offs, total = _layout(sv)
    # On agent hosts, segments carry a per-agent prefix so the agent can
    # sweep orphans at shutdown (segment names are otherwise random and
    # unattributable; the head only frees objects it was told about).
    prefix = os.environ.get("RAY_TPU_SEG_PREFIX")
    if prefix:
        shm = shared_memory.SharedMemory(
            name=f"{prefix}{uuid.uuid4().hex[:12]}", create=True, size=total
        )
    else:
        shm = shared_memory.SharedMemory(create=True, size=total)
    _untrack(shm)
    try:
        lens = _copy_into(shm.buf, sv, offs)
        loc = ShmLocation(shm.name, len(sv.header), lens, total)
    finally:
        shm.close()
    return loc


def _quiet_close(shm: shared_memory.SharedMemory) -> None:
    try:
        shm.close()
    except BufferError:
        shm._buf = None
        shm._mmap = None


class _PinnedBlock:
    """Zero-copy buffer exporter over a pinned arena block (PEP 688).

    Every view a deserialized value holds (numpy bases, PickleBuffers)
    keeps this exporter — and therefore the arena pin — alive; the pin
    drops when the last view dies, letting the allocator recycle the block.
    This is plasma's client-side release semantics
    (``plasma/client.cc`` Release on buffer destruction) done with the
    buffer protocol instead of client bookkeeping: a free racing a live
    reader defers to the last unpin (arena.cc zombie protocol), so reads
    are safe without copying out.
    """

    def __init__(self, arena, offset: int, size: int, rid=None):
        self._arena = arena  # also keeps the mapping alive until released
        self._offset = offset
        self._size = size
        self._rid = rid  # request that mapped us; unmap pairs with it
        self._mv = arena.view(offset, size)
        self._token = next(_pin_ids)
        note_pin(self._token, arena.name, offset, size)

    def __buffer__(self, flags):
        return self._mv

    def __del__(self):
        try:
            self._arena.unpin(self._offset)
        except Exception:  # noqa: BLE001 - interpreter-exit teardown
            pass
        try:
            drop_pin(self._token)
            if self._rid is not None:
                events.emit(
                    "core.object.unmap",
                    size=self._size,
                    seg=self._arena.name,
                    offset=self._offset,
                    request_id=self._rid,
                )
        except Exception:  # noqa: BLE001 - interpreter-exit teardown
            pass


class ShmReader:
    """Read a stored object back.

    Dedicated segments expose zero-copy out-of-band buffers: the mapping must
    outlive any views handed to the deserialized value, so we keep the
    SharedMemory open and let a weak registry close it when the value is
    garbage collected. Arena objects are zero-copy too: views go through a
    ``_PinnedBlock`` exporter whose arena pin lives exactly as long as the
    views do. A vanished object (freed, spilled, or arena gone) raises
    FileNotFoundError, which callers treat as "re-fetch from the head"
    (see runtime._materialize).
    """

    def __init__(self, loc: ShmLocation):
        self.loc = loc
        self.shm = None
        self._block = None
        # map/unmap ride EVERY zero-copy read, so they fire only inside a
        # traced request (mint-time sampling alignment, like spans) — the
        # pin ledger below stays unconditional, so the leak audit never
        # depends on this gate. Unmap reuses the rid captured here: the
        # exporter's __del__ runs under whatever request GC interrupts.
        rid = events.active_request_id()
        if loc.offset is not None:
            arena = attach_arena(loc.name)
            if arena is None or not arena.pin(loc.offset, loc.gen):
                raise FileNotFoundError(f"arena object gone: {loc.name}+{loc.offset}")
            if rid is not None:
                events.emit(
                    "core.object.map",
                    size=loc.total_size,
                    seg=loc.name,
                    offset=loc.offset,
                    request_id=rid,
                )
            if sys.version_info >= (3, 12):
                self._block = _PinnedBlock(arena, loc.offset, loc.total_size, rid)
            else:
                # pre-PEP 688 interpreters can't export a buffer from a
                # Python class, so views could not keep the pin alive —
                # copy the block out and release the pin immediately.
                # Correct (views reference the private copy), not zero-copy.
                try:
                    self._block = bytes(arena.view(loc.offset, loc.total_size))
                finally:
                    arena.unpin(loc.offset)
                if rid is not None:
                    events.emit(
                        "core.object.unmap",
                        size=loc.total_size,
                        seg=loc.name,
                        offset=loc.offset,
                        request_id=rid,
                    )
            return
        self._rid = rid
        self.shm = shared_memory.SharedMemory(name=loc.name)
        _untrack(self.shm)
        if rid is not None:
            events.emit(
                "core.object.map", size=loc.total_size, seg=loc.name,
                request_id=rid,
            )
        # If this reader is GC'd while deserialized values still hold views
        # into the mapping, SharedMemory.__del__ would raise BufferError as
        # an unraisable error (noisy at exit; pytest's unraisable capture
        # even retains the raising frame, pinning ObjectRefs). Close quietly
        # first, disarming on failure — the segment is unlinked by the head,
        # so a leaked mapping dies with the last process.
        import weakref

        weakref.finalize(self, _quiet_close, self.shm)

    def _mv(self):
        return memoryview(self._block) if self.shm is None else self.shm.buf

    def read(self):
        loc = self.loc
        header, bufs = layout_views(self._mv(), loc.header_len, loc.buffer_lens)
        return pickle.loads(header, buffers=bufs)

    def read_serialized_bytes(self) -> bytes:
        """Copy back into wire format (for shipping an object to a REMOTE
        node over the control socket — no shm across hosts)."""
        from ray_tpu._private.serialization import SerializedValue

        loc = self.loc
        header, bufs = layout_views(self._mv(), loc.header_len, loc.buffer_lens)
        return SerializedValue(bytes(header), bufs).to_bytes()

    def close(self):
        if self.shm is None:
            # drop our reference; the pin releases when the last value view
            # over the block dies (PEP 688 exporter lifetime — the exporter
            # emits the unmap event when it finally lets go)
            self._block = None
            return
        if self._rid is not None:
            events.emit(
                "core.object.unmap", size=self.loc.total_size,
                seg=self.loc.name, request_id=self._rid,
            )
        try:
            self.shm.close()
        except BufferError:
            # Views into the mapping are still alive; leak the mapping (it is
            # unlinked by the head, so it dies with the last process). Disarm
            # SharedMemory.__del__ so it doesn't retry and print at exit.
            self.shm._buf = None
            self.shm._mmap = None


def free_location(loc: ShmLocation) -> None:
    """Free a stored object's backing on THIS host: arena blocks go back to
    the allocator (deferred to last unpin if readers are active), dedicated
    segments are unlinked. Used by node agents when the head routes a free
    of an agent-host object (``head._release_loc``)."""
    if loc.offset is not None:
        arena = attach_arena(loc.name)
        if arena is not None:
            arena.free(loc.offset, loc.gen)
        return
    try:
        shm = shared_memory.SharedMemory(name=loc.name)
        shm.close()
        shm.unlink()
    except FileNotFoundError:
        pass


class ShmOwner:
    """Head-side registry of live objects; frees on release/shutdown.

    Dedicated segments are unlinked; arena blocks are freed back to the
    native allocator (a free racing a pinned reader defers to the last
    unpin — arena.cc zombie protocol)."""

    def __init__(self):
        self._lock = threading.Lock()
        # (segment name, arena offset or None) -> (size, gen)
        self._objects: dict[tuple[str, Optional[int]], tuple[int, int]] = {}
        self.bytes_used = 0

    def register(self, loc: ShmLocation) -> None:
        key = (loc.name, loc.offset)
        with self._lock:
            if key not in self._objects:
                self._objects[key] = (loc.total_size, loc.gen)
                self.bytes_used += loc.total_size

    def snapshot(self) -> dict:
        """Atomic copy of the ledger — ``(name, offset) -> (size, gen)`` —
        for the head's leak audit (every registered byte must be owned by
        a live directory locator)."""
        with self._lock:
            return dict(self._objects)

    def unlink(self, loc: ShmLocation) -> None:
        key = (loc.name, loc.offset)
        with self._lock:
            ent = self._objects.pop(key, None)
            if ent is not None:
                self.bytes_used -= ent[0]
        if loc.offset is not None:
            arena = attach_arena(loc.name)
            if arena is not None:
                arena.free(loc.offset, loc.gen)
            return
        try:
            # attach registers with the resource tracker; unlink() unregisters
            # again, so no explicit _untrack here (it would double-unregister).
            shm = shared_memory.SharedMemory(name=loc.name)
            shm.close()
            shm.unlink()
        except FileNotFoundError:
            pass

    def shutdown(self) -> None:
        with self._lock:
            keys = list(self._objects)
            self._objects.clear()
            self.bytes_used = 0
        for name, offset in keys:
            if offset is not None:
                continue  # the arena segment itself is unlinked by its owner
            try:
                shm = shared_memory.SharedMemory(name=name)
                shm.close()
                shm.unlink()
            except FileNotFoundError:
                pass
