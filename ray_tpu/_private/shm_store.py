"""Shared-memory object store (plasma equivalent).

The reference runs a plasma store inside each raylet: an mmap + dlmalloc arena
with a unix-socket flatbuffer protocol, LRU eviction and create-backpressure
(``src/ray/object_manager/plasma/store.h:55``). On a TPU host the picture is
simpler: every process that needs zero-copy access is on the same machine, and
device-resident arrays live in HBM addressed by sharding specs — the host
store only carries host-side payloads (batches, checkpending state, small
tensors, control data). So instead of a separate daemon we use one POSIX shm
segment per large object, created by whichever process produced the value and
owned (for unlink purposes) by the head:

* producer lays out [header][buffer0][buffer1...] with 64-byte alignment,
* consumers attach by name and reconstruct the pickled value with pickle-5
  out-of-band buffers pointing straight into the mapping (zero copy),
* the head records {object_id -> ShmLocation} and unlinks on free/shutdown.

Small objects (<= max_direct_call_object_size) never touch shm; they ride the
control-plane socket inline, like the reference's in-process memory store
(``store_provider/memory_store/memory_store.cc``).
"""

from __future__ import annotations

import dataclasses
import pickle
import threading
from multiprocessing import resource_tracker, shared_memory

from ray_tpu._private.serialization import SerializedValue

_ALIGN = 64


def _align(n: int) -> int:
    return (n + _ALIGN - 1) & ~(_ALIGN - 1)


@dataclasses.dataclass
class ShmLocation:
    name: str
    header_len: int
    buffer_lens: list[int]
    total_size: int


def _untrack(shm: shared_memory.SharedMemory) -> None:
    # Python's resource_tracker unlinks segments created by a process when
    # that process exits, which would tear objects out from under other
    # readers. Lifetime is owned by the head instead (explicit unlink).
    try:
        resource_tracker.unregister(shm._name, "shared_memory")  # type: ignore[attr-defined]
    except Exception:
        pass


def write_shm(sv: SerializedValue) -> ShmLocation:
    """Lay a serialized value out in a fresh shm segment."""
    hlen = len(sv.header)
    offs = [_align(hlen)]
    for b in sv.buffers[:-1] if sv.buffers else []:
        offs.append(_align(offs[-1] + len(b.raw())))
    total = (offs[-1] + len(sv.buffers[-1].raw())) if sv.buffers else hlen
    total = max(total, 1)
    shm = shared_memory.SharedMemory(create=True, size=total)
    _untrack(shm)
    try:
        shm.buf[:hlen] = sv.header
        lens = []
        for off, b in zip(offs, sv.buffers):
            raw = b.raw()
            n = raw.nbytes
            shm.buf[off : off + n] = raw.cast("B") if raw.format != "B" or raw.ndim != 1 else raw
            lens.append(n)
        loc = ShmLocation(shm.name, hlen, lens, total)
    finally:
        shm.close()
    return loc


def _quiet_close(shm: shared_memory.SharedMemory) -> None:
    try:
        shm.close()
    except BufferError:
        shm._buf = None
        shm._mmap = None


class ShmReader:
    """Attach to a segment and expose zero-copy out-of-band buffers.

    The mapping must outlive any views handed to the deserialized value, so we
    keep the SharedMemory open and let a weak registry close it when the value
    is garbage collected (readers pin via ``hold``).
    """

    def __init__(self, loc: ShmLocation):
        self.shm = shared_memory.SharedMemory(name=loc.name)
        _untrack(self.shm)
        self.loc = loc
        # If this reader is GC'd while deserialized values still hold views
        # into the mapping, SharedMemory.__del__ would raise BufferError as
        # an unraisable error (noisy at exit; pytest's unraisable capture
        # even retains the raising frame, pinning ObjectRefs). Close quietly
        # first, disarming on failure — the segment is unlinked by the head,
        # so a leaked mapping dies with the last process.
        import weakref

        weakref.finalize(self, _quiet_close, self.shm)

    def read(self):
        loc = self.loc
        mv = self.shm.buf
        header = mv[: loc.header_len]
        bufs = []
        off = _align(loc.header_len)
        for n in loc.buffer_lens:
            bufs.append(pickle.PickleBuffer(mv[off : off + n]))
            off = _align(off + n)
        value = pickle.loads(header, buffers=bufs)
        return value

    def read_serialized_bytes(self) -> bytes:
        """Copy the segment back into wire format (for shipping an object to
        a REMOTE node over the control socket — no shm across hosts)."""
        from ray_tpu._private.serialization import SerializedValue

        loc = self.loc
        mv = self.shm.buf
        header = bytes(mv[: loc.header_len])
        bufs = []
        off = _align(loc.header_len)
        for n in loc.buffer_lens:
            bufs.append(pickle.PickleBuffer(bytes(mv[off : off + n])))
            off = _align(off + n)
        return SerializedValue(header, bufs).to_bytes()

    def close(self):
        try:
            self.shm.close()
        except BufferError:
            # Views into the mapping are still alive; leak the mapping (it is
            # unlinked by the head, so it dies with the last process). Disarm
            # SharedMemory.__del__ so it doesn't retry and print at exit.
            self.shm._buf = None
            self.shm._mmap = None


class ShmOwner:
    """Head-side registry of live segments; unlinks on free/shutdown."""

    def __init__(self):
        self._lock = threading.Lock()
        self._segments: dict[str, int] = {}  # name -> size
        self.bytes_used = 0

    def register(self, loc: ShmLocation) -> None:
        with self._lock:
            if loc.name not in self._segments:
                self._segments[loc.name] = loc.total_size
                self.bytes_used += loc.total_size

    def unlink(self, name: str) -> None:
        with self._lock:
            size = self._segments.pop(name, None)
            if size is not None:
                self.bytes_used -= size
        try:
            # attach registers with the resource tracker; unlink() unregisters
            # again, so no explicit _untrack here (it would double-unregister).
            shm = shared_memory.SharedMemory(name=name)
            shm.close()
            shm.unlink()
        except FileNotFoundError:
            pass

    def shutdown(self) -> None:
        with self._lock:
            names = list(self._segments)
            self._segments.clear()
            self.bytes_used = 0
        for name in names:
            try:
                shm = shared_memory.SharedMemory(name=name)
                shm.close()
                shm.unlink()
            except FileNotFoundError:
                pass
