"""Process handles for forkserver-spawned workers + the template itself.

Shared by the head (local nodes) and node agents (remote hosts) — see
``worker_template.py`` for the forkserver design. Reference: the raylet's
worker pool process bookkeeping (``src/ray/raylet/worker_pool.h:152``).
"""

from __future__ import annotations

import os
import signal
import threading
import time
from typing import Optional


class ForkedProc:
    """Handle for a worker forked by the template. Not our child (the
    template's ``SIGCHLD=SIG_IGN`` lets the kernel reap it), so liveness
    and termination use a **pidfd** where the platform has one: a raw pid
    can be recycled the moment the kernel reaps, and ``os.kill`` on a
    recycled pid signals an innocent process. The pidfd pins the identity —
    it refers to this exact process forever, and polls readable once it
    exits. Raw-pid fallback only where pidfd_open is unavailable."""

    __slots__ = ("pid", "_pidfd", "_exited")

    def __init__(self, pid: int):
        self.pid = pid
        self._pidfd: Optional[int] = None
        self._exited = False
        try:
            self._pidfd = os.pidfd_open(pid)
        except AttributeError:
            self._pidfd = None  # platform without pidfd: raw fallback
        except OSError as e:
            import errno

            self._pidfd = None
            # ESRCH = already reaped (the pid may ALREADY be recycled —
            # never signal it). Anything else (ENOSYS on pre-5.3 kernels,
            # EPERM in sandboxes) means THIS PLATFORM can't pidfd at all:
            # the process is fine, fall back to raw-pid liveness. Treating
            # those as "exited" made every forked worker read as dead, so
            # the health loop killed its actor at the first tick of any
            # task longer than the health interval.
            self._exited = e.errno == errno.ESRCH

    def _close(self) -> None:
        if self._pidfd is not None:
            try:
                os.close(self._pidfd)
            except OSError:
                pass
            self._pidfd = None

    close = _close

    def __del__(self):
        # plain os.close: safe from a finalizer (no locks, no RPC — see the
        # __del__ rule in runtime.py). Without this, every dropped handle
        # (kill paths, agent shutdown clear) leaks one fd.
        self._close()

    def _poll_exit(self, timeout_ms) -> bool:
        """True once the process has exited. poll(), NOT select(): pidfds
        on a busy head can exceed FD_SETSIZE (1024) and select raises."""
        import select

        p = select.poll()
        p.register(self._pidfd, select.POLLIN)
        try:
            return bool(p.poll(timeout_ms))
        except OSError:
            return True

    def is_alive(self) -> bool:
        if self._exited:
            return False
        if self._pidfd is not None:
            if self._poll_exit(0):  # pidfd readable = process exited
                self._exited = True  # pid may be recycled from here on:
                self._close()  # terminate/join must become no-ops
                return False
            return True
        try:
            os.kill(self.pid, 0)
            return True
        except PermissionError:
            # EPERM = the process EXISTS but we may not signal it (sandbox
            # seccomp/LSM — the same environments that deny pidfd_open).
            # Only ESRCH means gone; treating EPERM as death re-creates
            # the kill-every-live-worker bug this path exists to avoid.
            return True
        except OSError:
            self._exited = True
            return False

    def terminate(self) -> None:
        if self._exited:
            return  # exit observed: the raw pid may belong to a stranger now
        if self._pidfd is not None:
            try:
                signal.pidfd_send_signal(self._pidfd, signal.SIGTERM)
            except OSError:
                pass
            return
        try:
            os.kill(self.pid, signal.SIGTERM)
        except OSError:
            pass

    def join(self, timeout=None) -> None:
        if self._exited:
            return
        if self._pidfd is not None:
            if self._poll_exit(None if timeout is None else int(timeout * 1000)):
                self._exited = True
                self._close()
            return
        deadline = None if timeout is None else time.monotonic() + timeout
        while self.is_alive():
            if deadline is not None and time.monotonic() >= deadline:
                return
            time.sleep(0.005)


class TemplateProc:
    """Spawner-side handle of one node's forkserver template. ``fork``
    writes one token line to the template's stdin (atomic under PIPE_BUF;
    the lock orders writers in THIS process); False means the template is
    unusable and the caller should fall back to a cold Popen spawn.

    The template reports ``token pid`` lines over a dedicated pipe (fd
    passed via ``pass_fds``, NOT stdout — workers inherit the template's
    stdout for user prints); ``on_spawn(token, ForkedProc)`` fires from a
    reader thread so kill/reap paths know forked pids before registration."""

    def __init__(self, popen, report_r=None, on_spawn=None):
        self.popen = popen
        self.lock = threading.Lock()
        if report_r is not None:
            threading.Thread(
                target=self._report_loop,
                args=(report_r, on_spawn),
                name="template-report",
                daemon=True,
            ).start()

    def _report_loop(self, report_r, on_spawn):
        with os.fdopen(report_r, "r") as f:
            for line in f:
                try:
                    token, pid = line.split()
                    if on_spawn is not None:
                        # open the pidfd HERE, as close to the fork as
                        # possible, so the identity pin beats any reap
                        on_spawn(token, ForkedProc(int(pid)))
                except (ValueError, OSError):
                    continue

    def alive(self) -> bool:
        return self.popen.poll() is None

    def fork(self, token: str) -> bool:
        with self.lock:
            if self.popen.poll() is not None:
                return False
            try:
                self.popen.stdin.write((token + "\n").encode())
                self.popen.stdin.flush()
                return True
            except (OSError, ValueError):
                return False

    def shutdown(self):
        try:
            self.popen.stdin.close()  # EOF: template exits on its own
        except (OSError, ValueError):
            pass
        try:
            self.popen.terminate()
        except OSError:
            pass


def spawn_template(
    socket_path: str,
    authkey: bytes,
    node_id_bin: bytes,
    env: dict,
    remote: bool = False,
    on_spawn=None,
) -> Optional[TemplateProc]:
    """Start a forkserver template process (shared by the head for local
    nodes and by node agents for their hosts). None = platform can't."""
    if not hasattr(os, "fork"):  # pragma: no cover - non-posix
        return None
    import subprocess
    import sys

    report_r, report_w = os.pipe()
    os.set_inheritable(report_w, True)
    try:
        popen = subprocess.Popen(
            [
                sys.executable,
                "-m",
                "ray_tpu._private.worker_template",
                socket_path,
                authkey.hex(),
                node_id_bin.hex(),
                "remote" if remote else "local",
                str(report_w),
            ],
            env=env,
            stdin=subprocess.PIPE,
            pass_fds=(report_w,),
            start_new_session=False,
        )
    except OSError:
        os.close(report_r)
        os.close(report_w)
        return None
    os.close(report_w)  # template holds the only write end now
    return TemplateProc(popen, report_r=report_r, on_spawn=on_spawn)
