"""Worker process entry point and task executor.

TPU-native counterpart of the reference's worker side: ``CoreWorkerProcess::
RunTaskExecutionLoop`` (``core_worker_process.cc:63``) plus the Cython task
executor (``_raylet.pyx:2177`` ``task_execution_handler``). One process, one
context; normal workers run tasks one at a time, actor workers hold the actor
instance and execute its methods in arrival order (= submission order, since
the head forwards over a FIFO socket), or on a thread pool when
``max_concurrency > 1`` (reference: threaded actors / concurrency groups).

Workers deliberately import no JAX at startup: on a TPU host the heavy
libraries load lazily inside user functions, keeping worker spawn ~100ms.
"""

from __future__ import annotations

import ctypes
import os
import queue
import threading
import traceback
from typing import Optional

from ray_tpu import exceptions as rex
from ray_tpu._private import serialization as ser
from ray_tpu._private.config import GLOBAL_CONFIG
from ray_tpu._private.runtime import ObjectRef, WorkerContext, set_ctx


class WorkerState:
    def __init__(self, ctx: WorkerContext):
        self.ctx = ctx
        self.task_queue: "queue.Queue" = queue.Queue()
        self.func_cache: dict[bytes, object] = {}
        self.actor_instance = None
        self.actor_id: Optional[bytes] = None
        self.actor_pool = None  # ThreadPoolExecutor for max_concurrency > 1
        self.running = True
        self.exec_thread_id: Optional[int] = None
        self.cancel_requested: set[bytes] = set()
        self.current_task_id: Optional[bytes] = None
        # task_id -> ident of the thread executing it (the exec loop, or a
        # pool thread for max_concurrency>1 actors) — cancel targets THAT
        # thread, never the dispatch loop.
        self.task_threads: dict[bytes, int] = {}


def connect_head(address: str, authkey: bytes, retries: int = 3):
    """Open the head control socket: ``host:port`` → TCP, else AF_UNIX.

    The hmac challenge handshake can spuriously fail under heavy concurrent
    connect churn (observed rarely in CI as ``digest sent was rejected``);
    retry a few times before giving up (reference: worker registration
    retries in worker_pool).
    """
    import time as _time
    from multiprocessing.connection import Client

    last: Exception = RuntimeError("unreachable")
    for attempt in range(retries):
        try:
            if ":" in address and not address.startswith("/"):
                host, port = address.rsplit(":", 1)
                return Client((host, int(port)), authkey=authkey)
            return Client(address, family="AF_UNIX", authkey=authkey)
        except Exception as e:  # noqa: BLE001 - auth/conn races
            last = e
            _time.sleep(0.1 * (attempt + 1))
    raise last


def main(
    socket_path: str,
    authkey: bytes,
    node_id_bin: bytes,
    token: str = "",
    remote: bool = False,
):
    try:
        conn = connect_head(socket_path, authkey)
    except FileNotFoundError:
        # cluster shut down while this worker was spawning — exit quietly
        os._exit(0)
    ctx = WorkerContext(conn, node_id_bin, remote=remote)
    set_ctx(ctx)
    state = WorkerState(ctx)
    ctx.send_raw(
        ("register", {"pid": os.getpid(), "node_id": node_id_bin, "token": token})
    )

    recv = threading.Thread(target=_recv_loop, args=(conn, ctx, state), daemon=True)
    recv.start()
    _exec_loop(state)


def _recv_loop(conn, ctx: WorkerContext, state: WorkerState):
    while state.running:
        try:
            msg = conn.recv()
        except (EOFError, OSError):
            state.running = False
            state.task_queue.put(None)
            return
        kind = msg[0]
        if kind == "resp":
            _, seq, ok, payload = msg
            ctx.on_response(seq, ok, payload)
        elif kind == "run_task":
            state.task_queue.put(msg[1])
        elif kind == "cancel":
            _handle_cancel(state, msg[1])
        elif kind == "exit":
            state.running = False
            state.task_queue.put(None)
            os._exit(0)


def _handle_cancel(state: WorkerState, task_id: bytes):
    state.cancel_requested.add(task_id)
    tid = state.task_threads.get(task_id)
    if tid is not None:
        # best-effort async interrupt (reference: SIGINT into the worker),
        # into the thread running this task only
        ctypes.pythonapi.PyThreadState_SetAsyncExc(
            ctypes.c_ulong(tid), ctypes.py_object(rex.TaskCancelledError)
        )


def _exec_loop(state: WorkerState):
    state.exec_thread_id = threading.get_ident()
    while state.running:
        spec = state.task_queue.get()
        if spec is None:
            break
        if spec["kind"] == "actor_method" and state.actor_pool is not None:
            state.actor_pool.submit(_run_spec, state, spec)
        else:
            _run_spec(state, spec)
    os._exit(0)


def _run_spec(state: WorkerState, spec: dict):
    kind = spec["kind"]
    if kind == "actor_create":
        _run_actor_create(state, spec)
    else:
        _run_task(state, spec)


def _resolve_function(state: WorkerState, func_id: bytes):
    fn = state.func_cache.get(func_id)
    if fn is None:
        blob = state.ctx.call("get_function", func_id=func_id)
        fn = ser.loads(blob)
        state.func_cache[func_id] = fn
    return fn


def _load_args(state: WorkerState, spec: dict):
    """Deserialize by-value args; fetch by-ref args from the store. Errors in
    dependencies propagate (reference: RayTaskError poisoning dependents)."""
    ref_ids = []
    for a in list(spec.get("args", ())) + list(spec.get("kwargs", {}).values()):
        if a[0] == "r":
            ref_ids.append(a[1])
    fetched = {}
    if ref_ids:
        locators = state.ctx.call("get", obj_ids=ref_ids, timeout=None)
        for oid, loc in zip(ref_ids, locators):
            value = state.ctx._materialize(oid, loc)
            if loc[2]:  # dependency failed
                if isinstance(value, rex.RayTaskError):
                    raise value.as_instanceof_cause()
                raise value
            fetched[oid] = value

    def one(a):
        if a[0] == "r":
            return fetched[a[1]]
        return ser.deserialize_value(ser.SerializedValue.from_bytes(a[1]))

    args = [one(a) for a in spec.get("args", ())]
    kwargs = {k: one(v) for k, v in spec.get("kwargs", {}).items()}
    return args, kwargs


def _store_results(state: WorkerState, spec: dict, value, is_error=False):
    """Serialize returns; small ones ride the task_done message, large ones go
    straight to shm from this process (zero extra copies)."""
    return_ids = spec["return_ids"]
    n = len(return_ids)
    if is_error or n == 1:
        values = [value] * n if n else []
    else:
        try:
            values = list(value)
        except TypeError:
            values = [value]
        if len(values) != n:
            err = rex.RayTaskError.from_exception(
                spec.get("name", "task"),
                ValueError(f"Task declared num_returns={n} but returned {type(value)}"),
            )
            return _store_results(state, spec, err, is_error=True)
    results = []
    for rid, v in zip(return_ids, values):
        try:
            sv = ser.serialize(v)
        except Exception as e:  # unserializable return
            sv = ser.serialize(rex.RayTaskError.from_exception(spec.get("name", "task"), e))
            is_error = True
        if sv.total_size <= GLOBAL_CONFIG.max_direct_call_object_size or state.ctx.remote:
            # remote workers always inline: their shm lives on another host;
            # the head re-lays oversized inlines into ITS shm on receipt
            results.append((rid, ("inline", sv.to_bytes(), is_error)))
        else:
            from ray_tpu._private.shm_store import write_shm

            results.append((rid, ("shm", write_shm(sv), is_error)))
    return results


def _run_task(state: WorkerState, spec: dict):
    from ray_tpu._private import runtime_env as renv

    task_id = spec["task_id"]
    state.current_task_id = task_id
    state.task_threads[task_id] = threading.get_ident()
    is_error = False
    try:
        if task_id in state.cancel_requested:
            raise rex.TaskCancelledError()
        if spec["kind"] == "actor_method":
            method = getattr(state.actor_instance, spec["method_name"])
            args, kwargs = _load_args(state, spec)
            value = method(*args, **kwargs)
        else:
            fn = _resolve_function(state, spec["func_id"])
            args, kwargs = _load_args(state, spec)
            with renv.applied(spec.get("runtime_env"), state.ctx):
                value = fn(*args, **kwargs)
    except BaseException as e:  # noqa: BLE001
        if isinstance(e, rex.TaskCancelledError):
            value = e
        elif isinstance(e, rex.RayTaskError):
            value = e
        else:
            value = rex.RayTaskError.from_exception(spec.get("name", "task"), e)
        is_error = True
    finally:
        state.current_task_id = None
        state.task_threads.pop(task_id, None)
        state.cancel_requested.discard(task_id)
    try:
        results = _store_results(state, spec, value, is_error)
    except BaseException:  # noqa: BLE001
        traceback.print_exc()
        results = []
    state.ctx.send_raw(
        ("task_done", {"task_id": task_id, "results": results, "results_error": is_error})
    )


def _cli_main():
    """Entry point for ``python -m ray_tpu._private.worker_main`` — workers
    are exec'd fresh (reference: worker_pool spawning default_worker.py), so
    they never re-import the driver's __main__ module."""
    import sys

    socket_path, authkey_hex, node_id_hex = sys.argv[1], sys.argv[2], sys.argv[3]
    token = sys.argv[4] if len(sys.argv) > 4 else ""
    remote = len(sys.argv) > 5 and sys.argv[5] == "--remote"
    main(
        socket_path,
        bytes.fromhex(authkey_hex),
        bytes.fromhex(node_id_hex),
        token=token,
        remote=remote,
    )


def _run_actor_create(state: WorkerState, spec: dict):
    from ray_tpu._private import runtime_env as renv

    try:
        cls = _resolve_function(state, spec["func_id"])
        args, kwargs = _load_args(state, spec)
        # permanent: the actor owns this worker process for life, so its
        # runtime env applies to every subsequent method call too
        with renv.applied(spec.get("runtime_env"), state.ctx, permanent=True):
            state.actor_instance = cls(*args, **kwargs)
        state.actor_id = spec["actor_id"]
        state.ctx.current_actor = spec["actor_id"].hex()  # for get_runtime_context()
        if spec.get("max_concurrency", 1) > 1:
            from concurrent.futures import ThreadPoolExecutor

            state.actor_pool = ThreadPoolExecutor(max_workers=spec["max_concurrency"])
        state.ctx.send_raw(("actor_ready", {"actor_id": spec["actor_id"], "error": None}))
    except BaseException as e:  # noqa: BLE001
        err = rex.RayTaskError.from_exception(spec.get("name", "actor"), e)
        state.ctx.send_raw(("actor_ready", {"actor_id": spec["actor_id"], "error": err}))


if __name__ == "__main__":
    _cli_main()
