"""Worker process entry point and task executor.

TPU-native counterpart of the reference's worker side: ``CoreWorkerProcess::
RunTaskExecutionLoop`` (``core_worker_process.cc:63``) plus the Cython task
executor (``_raylet.pyx:2177`` ``task_execution_handler``). One process, one
context; normal workers run tasks one at a time, actor workers hold the actor
instance and execute its methods in arrival order (= submission order, since
the head forwards over a FIFO socket), or on a thread pool when
``max_concurrency > 1`` (reference: threaded actors / concurrency groups).

Workers deliberately import no JAX at startup: on a TPU host the heavy
libraries load lazily inside user functions, keeping worker spawn ~100ms.
"""

from __future__ import annotations

import ctypes
import os
import queue
import threading
import traceback
from typing import Optional

from ray_tpu import exceptions as rex
from ray_tpu._private import events
from ray_tpu._private import serialization as ser
from ray_tpu._private.config import GLOBAL_CONFIG
from ray_tpu._private.log_util import warn_throttled
from ray_tpu._private.runtime import ObjectRef, WorkerContext, set_ctx

#: flight-recorder events this module emits (raylint RL012 registry): a
#: task result / stream item entering the shm object plane from this
#: worker (the producer half of ``core.object.*`` for non-put objects).
EVENT_NAMES = ("core.object.put",)

#: raylint RL017 — the worker's recv/exec/cancel hand-off state is
#: deliberately lock-free (':atomic' = every write is one GIL-atomic
#: operation, verified by the linter):
#:
#: - cancel_requested: set.add from the recv thread, membership tests +
#:   discard from the executing thread — a cancel landing one bytecode
#:   after the test is simply delivered on the next check point, which is
#:   the documented best-effort cancel contract.
#: - task_threads: task_id -> executing-thread ident, dict store/pop by
#:   the executor, read by the recv thread to target the async interrupt;
#:   a miss means the task already finished (cancel is then a no-op).
#: - async_tasks: task_id -> asyncio.Task, stored on the loop thread,
#:   read by the recv thread for call_soon_threadsafe cancellation.
#: - group_sems: written ONCE at actor create, before actor_ready ships —
#:   every method dispatch happens-after by protocol order.
LOCKFREE = (
    "WorkerState.cancel_requested: atomic",
    "WorkerState.task_threads: atomic",
    "WorkerState.async_tasks: atomic",
    "WorkerState.group_sems: atomic",
)


class WorkerState:
    def __init__(self, ctx: WorkerContext):
        self.ctx = ctx
        # SimpleQueue: the recv->exec handoff runs once per dispatched task
        # and the C implementation shaves the pure-Python Condition dance
        # off the head_dispatch leg
        self.task_queue: "queue.SimpleQueue" = queue.SimpleQueue()
        self.func_cache: dict[bytes, object] = {}
        # spec headers (cheaper per-task bytes, ISSUE 14): the head ships a
        # function's static spec fields once per worker; steady-state
        # run_task bodies reference them by id and rehydrate here
        self.hdr_cache: dict = {}
        # reply coalescing (ISSUE 14): finished-task payloads buffer here
        # while more work is queued and ship as ONE tasks_done_batch —
        # drained off-path by the reply flusher so a slow follower can
        # never withhold a finished result (an idle worker ships inline)
        self.reply_buf: list = []
        self.reply_lock = threading.Lock()  # guards reply_buf
        self.reply_send = threading.Lock()  # serializes drain+send (FIFO)
        self.reply_evt: Optional[threading.Event] = None
        self.actor_instance = None
        self.actor_id: Optional[bytes] = None
        self.actor_pool = None  # ThreadPoolExecutor for max_concurrency > 1
        # asyncio actors (any ``async def`` method): a dedicated event loop
        # thread runs every method (single-thread state semantics, like the
        # reference's per-concurrency-group asyncio loops, _raylet.pyx:2082);
        # concurrency bounded per group by asyncio.Semaphore.
        self.async_loop = None
        self.group_sems: dict[str, object] = {}
        self.group_pools: dict[str, object] = {}  # threaded actors w/ groups
        self.async_tasks: dict[bytes, object] = {}  # task_id -> asyncio.Task
        self.async_io_pool = None    # ThreadPoolExecutor: blocking arg fetches
        self.async_done_pool = None  # ThreadPoolExecutor: result store/send
        self.running = True
        self.exec_thread_id: Optional[int] = None
        self.cancel_requested: set[bytes] = set()
        self.current_task_id: Optional[bytes] = None
        # task_id -> ident of the thread executing it (the exec loop, or a
        # pool thread for max_concurrency>1 actors) — cancel targets THAT
        # thread, never the dispatch loop.
        self.task_threads: dict[bytes, int] = {}
        # streaming-generator backpressure: task_id -> highest consumer-acked
        # index+1, fed by the head's stream_ack pushes (_recv_loop)
        self.stream_acked: dict[bytes, int] = {}
        self.stream_cv = threading.Condition()


def connect_head(address: str, authkey: bytes, retries: int = 3):
    """Open the head control socket: ``host:port`` → TCP, else AF_UNIX.

    The hmac challenge handshake can spuriously fail under heavy concurrent
    connect churn (observed rarely in CI as ``digest sent was rejected``);
    retry a few times before giving up (reference: worker registration
    retries in worker_pool).
    """
    import time as _time
    from multiprocessing.connection import Client

    last: Exception = RuntimeError("unreachable")
    for attempt in range(retries):
        try:
            if ":" in address and not address.startswith("/"):
                host, port = address.rsplit(":", 1)
                return Client((host, int(port)), authkey=authkey)
            return Client(address, family="AF_UNIX", authkey=authkey)
        except Exception as e:  # noqa: BLE001 - auth/conn races
            last = e
            _time.sleep(0.1 * (attempt + 1))
    raise last


def _install_jax_platform_pin() -> None:
    """Make ``JAX_PLATFORMS`` authoritative in this worker.

    Platform plugins can stomp the env var during ``import jax`` (observed:
    the axon TPU plugin sets ``jax_platforms=axon,cpu`` at registration, so a
    CI worker spawned with ``JAX_PLATFORMS=cpu`` would still compile onto the
    TPU tunnel). Workers import jax lazily inside user functions, so pin the
    config the moment jax first gets imported — then restore __import__ so
    the steady state pays nothing."""
    want = os.environ.get("JAX_PLATFORMS")
    if not want:
        return
    import builtins
    import sys

    orig = builtins.__import__

    def imp(name, *a, **k):
        mod = orig(name, *a, **k)
        # Nested imports during jax's own package init re-enter here with
        # half-initialized modules, and the plugin can stomp the config at
        # any point of that init — so re-assert after EVERY jax import and
        # only disarm once jax is fully loaded with the value verified.
        if name == "jax" or name.startswith("jax."):
            jaxmod = sys.modules.get("jax")
            cfg = getattr(jaxmod, "config", None)
            try:
                if cfg is not None and cfg.jax_platforms != want:
                    cfg.update("jax_platforms", want)
                spec = getattr(jaxmod, "__spec__", None)
                if (
                    cfg is not None
                    and not getattr(spec, "_initializing", False)
                    and cfg.jax_platforms == want
                ):
                    builtins.__import__ = orig  # verified: steady state pays 0
            except Exception:
                pass
        return mod

    builtins.__import__ = imp


def main(
    socket_path: str,
    authkey: bytes,
    node_id_bin: bytes,
    token: str = "",
    remote: bool = False,
):
    # Fault injection for the registration-timeout path (tests): the FIRST
    # process to claim the sentinel wedges pre-registration, like an
    # interpreter that hangs at startup; respawns find the sentinel taken
    # and come up normally. Lives HERE (not _cli_main) so template-forked
    # workers are covered too — the wedge tests exercise the pidfd kill path.
    wedge = os.environ.get("RAY_TPU_TEST_WEDGE_ONCE")
    if wedge:
        try:
            fd = os.open(wedge, os.O_CREAT | os.O_EXCL | os.O_WRONLY)
            os.close(fd)
            import time as _time

            _time.sleep(600.0)
        except FileExistsError:
            pass
    _install_jax_platform_pin()
    try:
        conn = connect_head(socket_path, authkey)
    except (FileNotFoundError, ConnectionError, EOFError):
        # cluster shut down while this worker was spawning — exit quietly
        # (a traceback here is pure teardown noise on every fast driver
        # exit; the reference's worker teardown is silent by design).
        # Other OSErrors (ENOSPC, EMFILE) stay loud: real faults.
        os._exit(0)
    head_host = socket_path.rsplit(":", 1)[0] if remote and ":" in socket_path else None
    ctx = WorkerContext(
        conn, node_id_bin, remote=remote, authkey=authkey, head_host=head_host
    )
    set_ctx(ctx)
    state = WorkerState(ctx)
    state.head_address = socket_path  # for detached-actor reconnect
    state.detached = False
    # SIGUSR1 → all-thread stack dump (C-level handler: fires even when the
    # GIL is held or the process is wedged mid-syscall) — the profiling
    # story for stuck workers (reporter.py; reference: py-spy dumps via
    # dashboard profile_manager)
    from ray_tpu._private.reporter import arm_stack_dumps

    arm_stack_dumps()
    # flight recorder: flush the event ring to JSONL when this worker dies
    # by SIGTERM (how proc_handles kills us) or an unhandled exception —
    # the postmortem story for a replica shot mid-stream (events.py)
    from ray_tpu._private import events as _events

    # in-band node origin: crash-flush files and OTLP resources keep their
    # node attribution even when the head never sees this process again
    _events.set_node(node_id_bin.hex()[:12])
    _events.record("worker.start", node=node_id_bin.hex()[:12])
    _events.install_crash_handlers()
    try:
        ctx.send_raw(
            ("register", {"pid": os.getpid(), "node_id": node_id_bin, "token": token})
        )
    except (ConnectionError, EOFError):
        os._exit(0)  # head died between connect and register: quiet exit

    recv = threading.Thread(target=_recv_loop, args=(conn, ctx, state), daemon=True)
    recv.start()
    prof_dir = os.environ.get("RAY_TPU_WORKER_CPROFILE")
    if prof_dir:
        # debugging hook (reference: py-spy / memray endpoints in
        # dashboard/modules/reporter/profile_manager.py): cProfile this
        # worker's exec loop, dump stats on exit for offline analysis
        import cProfile
        import signal

        pr = cProfile.Profile()

        def _dump(*_a):
            pr.disable()
            pr.dump_stats(os.path.join(prof_dir, f"worker-{os.getpid()}.prof"))
            # this handler REPLACES the flight recorder's SIGTERM hook —
            # flush the event ring here so a profiled worker still leaves
            # its postmortem JSONL (flush never raises)
            _events.flush(reason="sigterm")
            os._exit(0)

        global _prof_exit
        _prof_exit = _dump
        signal.signal(signal.SIGTERM, _dump)  # workers die by SIGTERM
        pr.enable()
        try:
            _exec_loop(state)
        finally:
            _dump()
    else:
        _exec_loop(state)
    os._exit(0)


def _try_reconnect(state: WorkerState, ctx: WorkerContext):
    """Detached-actor worker lost the head: retry the address for the
    reconnect grace window, re-register claiming our actor id, and
    re-announce readiness so the restored head rebinds us (state intact)."""
    import time

    from ray_tpu._private.config import GLOBAL_CONFIG

    addresses = [state.head_address]
    tcp = os.environ.get("RAY_TPU_HEAD_TCP")
    if tcp and tcp not in addresses:
        # a restarted head rebinds its TCP address; the old unix socket
        # died with the old process
        addresses.append(tcp)
    deadline = time.monotonic() + GLOBAL_CONFIG.head_reconnect_grace_s
    attempt = 0
    while time.monotonic() < deadline and state.running:
        address = addresses[attempt % len(addresses)]
        attempt += 1
        try:
            conn = connect_head(address, ctx.authkey, retries=1)
            conn.send(
                (
                    "register",
                    {
                        "pid": os.getpid(),
                        "node_id": ctx.node_id_bin,
                        "token": "",
                        "actor_id": state.actor_id,
                    },
                )
            )
            conn.send(("actor_ready", {"actor_id": state.actor_id, "error": None}))
            ctx.conn = conn
            # un-acked submit windows died with the OLD conn (their acks
            # are unrecoverable and the restored head may never have seen
            # them): fail them retriably and re-ship header definitions on
            # the next window (fail-not-replay, the pinned semantic).
            # not_on=conn spares a window a concurrent exec thread already
            # delivered on the FRESH conn — poisoning that one would make
            # the caller's retry a double-submit
            ctx._fail_submits(not_on=conn)
            return conn
        except Exception:
            time.sleep(0.5)
    return None


def _recv_loop(conn, ctx: WorkerContext, state: WorkerState):
    # this thread processes submit_acks: it must never park in the submit
    # credit wait (runtime._recv_ident — send_raw/call skip the flush here)
    ctx._recv_ident = threading.get_ident()
    # buffered framed reads (ser.ConnReader): one syscall per kernel batch
    # instead of two per message; this loop is the conn's only reader
    reader = ser.ConnReader(conn)
    while state.running:
        try:
            msg = reader.recv()
        # ValueError/TypeError: a concurrent local close nulls the conn's
        # handle mid-read (same contract as the driver pump loop)
        except (EOFError, OSError, ValueError, TypeError):
            if state.actor_id is not None and getattr(state, "detached", False):
                newconn = _try_reconnect(state, ctx)
                if newconn is not None:
                    conn = newconn
                    reader = ser.ConnReader(conn)
                    continue
            state.running = False
            state.task_queue.put(None)
            return
        kind = msg[0]
        if kind == "run_task":  # hottest message first (one per task)
            spec = _rehydrate_spec(state, msg[1])
            if spec is not None:  # None = header miss, already failed
                _stamp_deserialized(spec)
                state.task_queue.put(spec)
        elif kind == "resp":
            _, seq, ok, payload = msg
            ctx.on_response(seq, ok, payload)
        elif kind == "pub":
            ctx.on_pub(msg[1], msg[2])
        elif kind == "run_task_batch":
            # head coalesced dispatches (flush_outbox); FIFO order within
            # the batch is the dispatch order
            for spec in msg[1]:
                spec = _rehydrate_spec(state, spec)
                if spec is not None:
                    _stamp_deserialized(spec)
                    state.task_queue.put(spec)
        elif kind == "submit_ack":
            # window credits for this worker's own pipelined submissions
            ctx._on_submit_ack(msg[1]["wid"])
        elif kind == "cancel":
            _handle_cancel(state, msg[1])
        elif kind == "stream_ack":
            with state.stream_cv:
                tid = msg[1]["task_id"]
                state.stream_acked[tid] = max(
                    state.stream_acked.get(tid, 0), msg[1]["consumed"]
                )
                state.stream_cv.notify_all()
        elif kind == "profile":
            _start_profile(ctx, msg[1])
        elif kind == "events_drain":
            _drain_events(ctx, msg[1])
        elif kind == "object_report":
            _object_report(ctx, msg[1])
        elif kind == "exit":
            state.running = False
            state.task_queue.put(None)
            try:
                _flush_done(state)  # deferred completions must not die with us
            except Exception as e:
                # conn already dead: nothing left to ship them on
                warn_throttled("exit-flush deferred completions", e)
            if _prof_exit is not None:
                _prof_exit()
            os._exit(0)


def _stamp_deserialized(spec: dict) -> None:
    """worker_deserialize stamp, taken in the RECV loop where the spec's
    bytes were actually parsed (ConnReader) and its header rehydrated —
    not at ``_run_task`` entry on the exec thread. The distinction is the
    honest-attribution contract under batching (ISSUE 14): task #64 of a
    ``run_task_batch`` waits its whole queue depth for the exec thread,
    and that wait belongs to the worker_deserialize→exec_start leg (the
    worker's own backlog), not to ``head_dispatch`` (the head+wire hop)."""
    wf = spec.get("wf")
    if wf is not None:
        if _waterfall is None:
            _bind_task_mods()
        _waterfall.stamp(wf)  # worker_deserialize


def _rehydrate_spec(state: WorkerState, spec: dict) -> dict:
    """Expand a header-split run_task body back into a full spec. Header
    definitions ride the same FIFO conn before any reference to them, so a
    miss means connection-state loss — fail the task's refs instead of
    crashing the recv loop."""
    hd = spec.pop("_hdr_def", None)
    if hd is not None:
        state.hdr_cache[hd[0]] = hd[1]
        return {**hd[1], **spec}
    hid = spec.pop("_hdr_ref", None)
    if hid is None:
        return spec
    fields = state.hdr_cache.get(hid)
    if fields is None:
        err = rex.RayTaskError.from_exception(
            spec.get("name", "task"),
            rex.RayError("run_task referenced a spec header this worker never saw"),
        )
        results = [
            (rid, ("inline", ser.serialize(err).to_bytes(), True))
            for rid in spec.get("return_ids", ())
        ]
        try:
            state.ctx.send_raw(
                ("task_done",
                 {"task_id": spec["task_id"], "results": results, "results_error": True})
            )
        except Exception:
            pass
        return None
    return {**fields, **spec}


_profile_gate = threading.Lock()
_prof_exit = None  # set by main() when RAY_TPU_WORKER_CPROFILE is on

# lazily-bound task-path modules: imported on the FIRST task (workers
# deliberately keep startup import-light), then the per-task path pays
# module-global loads instead of sys.modules lookups
_renv = None
_tracing = None
_waterfall = None


def _bind_task_mods() -> None:
    global _renv, _tracing, _waterfall
    from ray_tpu._private import runtime_env as renv
    from ray_tpu.util import tracing, waterfall

    _renv, _tracing, _waterfall = renv, tracing, waterfall


def _start_profile(ctx, req: dict) -> None:
    """On-demand sampling CPU profile (reference: the dashboard's py-spy
    endpoint): sample this worker's threads off the recv loop, then post
    the collapsed stacks back to the head's reply mailbox. Single-flight
    with a bounded duration: samplers burn GIL time, so overlapping
    requests (a dashboard poller in a retry loop) must not stack."""

    def _run():
        from ray_tpu._private.reporter import sample_profile

        if not _profile_gate.acquire(blocking=False):
            text = "<profile already in progress>"
        else:
            try:
                text = sample_profile(
                    min(float(req.get("duration_s", 2.0)), 60.0),
                    float(req.get("interval_s", 0.01)),
                )
            except Exception as e:
                text = f"<profile failed: {e!r}>"
            finally:
                _profile_gate.release()
        try:
            ctx.send_raw(
                ("profile_result",
                 {"req_id": req["req_id"], "pid": os.getpid(), "profile": text})
            )
        except Exception:
            pass  # head gone: nothing to report to

    threading.Thread(target=_run, daemon=True, name="rt-profiler").start()


def _drain_events(ctx, req: dict) -> None:
    """Reply with this worker's flight-recorder ring (head rendezvous:
    ``rpc_collect_events``). Snapshot off the recv loop — the ring can be
    large and serialization must not stall task dispatch."""

    def _run():
        from ray_tpu._private import events as _ev

        try:
            evs = _ev.snapshot()
        except Exception as e:  # noqa: BLE001 — drain is best-effort
            evs = [{"type": "events.drain_failed", "error": repr(e)}]
        try:
            ctx.send_raw(
                ("events_result",
                 {"req_id": req["req_id"], "pid": os.getpid(), "events": evs})
            )
        except Exception:
            pass  # head gone: nothing to report to

    threading.Thread(target=_run, daemon=True, name="rt-events-drain").start()


def _object_report(ctx, req: dict) -> None:
    """Reply with this process's object-plane residency (head rendezvous:
    ``rpc_object_ledger``/``rpc_object_audit``): live arena pins with
    ages (leak-audit input — every pin must map to a live reader), ids
    this context has poisoned locally, and the attached arena's
    occupancy. Off the recv loop like the events drain."""

    def _run():
        from ray_tpu._private import shm_store

        report: dict = {}
        try:
            report = shm_store.pin_stats()
            report["poisoned"] = [
                oid.hex() for oid in list(getattr(ctx, "_poisoned", {}))
            ]
            arena = shm_store._current_write_arena()
            if arena is not None:
                report["arena"] = {
                    "name": arena.name,
                    "used": arena.used,
                    "capacity": arena.capacity,
                    "n_objects": arena.n_objects,
                }
        except Exception as e:  # noqa: BLE001 — report is best-effort
            report = {"error": repr(e)}
        try:
            ctx.send_raw(
                ("object_report_result",
                 {"req_id": req["req_id"], "pid": os.getpid(),
                  "report": report})
            )
        except Exception:
            pass  # head gone: nothing to report to

    threading.Thread(target=_run, daemon=True, name="rt-object-report").start()


def _handle_cancel(state: WorkerState, task_id: bytes):
    state.cancel_requested.add(task_id)
    atask = state.async_tasks.get(task_id)
    if atask is not None and state.async_loop is not None:
        state.async_loop.call_soon_threadsafe(atask.cancel)
        return
    tid = state.task_threads.get(task_id)
    if tid is not None:
        # best-effort async interrupt (reference: SIGINT into the worker),
        # into the thread running this task only
        ctypes.pythonapi.PyThreadState_SetAsyncExc(
            ctypes.c_ulong(tid), ctypes.py_object(rex.TaskCancelledError)
        )


def _exec_loop(state: WorkerState):
    state.exec_thread_id = threading.get_ident()
    while state.running:
        spec = state.task_queue.get()
        if spec is None:
            break
        try:
            _exec_one(state, spec)
        except (BrokenPipeError, ConnectionResetError, EOFError):
            # the head vanished mid-result-send (driver exited): nothing
            # left to report to — exit without a traceback
            os._exit(0)


def _exec_one(state: WorkerState, spec: dict):
    if spec["kind"] == "actor_method" and state.async_loop is not None:
        _dispatch_async(state, spec)
    elif spec["kind"] == "actor_method" and state.group_pools:
        group = spec.get("concurrency_group") or "_default"
        pool = state.group_pools.get(group)
        if pool is None:
            err = rex.RayTaskError.from_exception(
                spec.get("name", "task"),
                ValueError(
                    f"Unknown concurrency group {group!r}; declared: "
                    f"{sorted(g for g in state.group_pools if g != '_default')}"
                ),
            )
            _finish_task(state, spec, err, is_error=True)
        else:
            pool.submit(_run_spec, state, spec)
    elif spec["kind"] == "actor_method" and state.actor_pool is not None:
        state.actor_pool.submit(_run_spec, state, spec)
    else:
        _run_spec(state, spec)


def _run_spec(state: WorkerState, spec: dict):
    kind = spec["kind"]
    if kind == "actor_create":
        _run_actor_create(state, spec)
    else:
        _run_task(state, spec)


def _resolve_function(state: WorkerState, func_id: bytes):
    fn = state.func_cache.get(func_id)
    if fn is None:
        blob = state.ctx.call("get_function", func_id=func_id)
        fn = ser.loads(blob)
        state.func_cache[func_id] = fn
    return fn


def _load_args(state: WorkerState, spec: dict):
    """Deserialize by-value args; fetch by-ref args from the store. Errors in
    dependencies propagate (reference: RayTaskError poisoning dependents)."""
    s_args = spec.get("args", ())
    s_kwargs = spec.get("kwargs")
    if not s_args and not s_kwargs:
        return [], {}  # hot path: no-arg calls skip the fetch machinery
    ref_ids = []
    for a in list(s_args) + list(s_kwargs.values() if s_kwargs else ()):
        if a[0] == "r":
            ref_ids.append(a[1])
    fetched = {}
    if ref_ids:
        locators = state.ctx.call("get", obj_ids=ref_ids, timeout=None)
        for oid, loc in zip(ref_ids, locators):
            value = state.ctx._materialize(oid, loc)
            if loc[2]:  # dependency failed
                if isinstance(value, rex.RayTaskError):
                    raise value.as_instanceof_cause()
                raise value
            fetched[oid] = value

    def one(a):
        if a[0] == "r":
            return fetched[a[1]]
        return ser.deserialize_value(ser.SerializedValue.from_bytes(a[1]))

    args = [one(a) for a in spec.get("args", ())]
    kwargs = {k: one(v) for k, v in spec.get("kwargs", {}).items()}
    return args, kwargs


def _store_results(state: WorkerState, spec: dict, value, is_error=False):
    """Serialize returns; small ones ride the task_done message, large ones go
    straight to shm from this process (zero extra copies)."""
    return_ids = spec["return_ids"]
    n = len(return_ids)
    if value is None and not is_error and n == 1:
        # the most common result: ship the precomputed constant, skip the
        # whole cloudpickle + SerializedValue round per task
        return [(return_ids[0], ("inline", ser.NONE_BYTES, False))]
    if is_error or n == 1:
        values = [value] * n if n else []
    else:
        try:
            values = list(value)
        except TypeError:
            values = [value]
        if len(values) != n:
            err = rex.RayTaskError.from_exception(
                spec.get("name", "task"),
                ValueError(f"Task declared num_returns={n} but returned {type(value)}"),
            )
            return _store_results(state, spec, err, is_error=True)
    results = []
    for rid, v in zip(return_ids, values):
        try:
            sv = ser.serialize(v)
        except Exception as e:  # unserializable return
            sv = ser.serialize(rex.RayTaskError.from_exception(spec.get("name", "task"), e))
            is_error = True
        # large results land in THIS host's shm and only the locator travels
        # (agent hosts serve the bytes peer-to-peer; see data_plane.py) —
        # remote processes without a local store fall back to inline
        locator = state.ctx.store_value(sv, is_error)
        if locator[0] == "shm":
            events.emit(
                "core.object.put",
                obj_id=rid,
                size=locator[1].total_size,
                node=locator[1].node,
                seg=locator[1].name,
            )
        results.append((rid, locator))
    return results


def _stream_results(state: WorkerState, spec: dict, gen) -> None:
    """Drive a streaming-generator task (num_returns="streaming"): each
    yielded item becomes its own object, reported to the head as it is
    produced (reference: ReportGeneratorItemReturns, _raylet.pyx:1230),
    with a consumer-acked backpressure window
    (``streaming_backpressure_items``). The task's single declared return
    becomes the completion object: None on success, the exception on a
    mid-stream failure.

    The generator BODY runs during this drive (not at creation), possibly
    on an async actor's done-pool thread — (re-)install the submitter's
    trace context here so spans/events inside streaming bodies (the serve
    LLM path) keep their request_id for the stream's whole life."""
    if _tracing is None:
        _bind_task_mods()

    prev_trace = _tracing.set_trace_context(
        _tracing.task_context(spec.get("trace_ctx"), spec["task_id"])
    )
    try:
        _stream_results_inner(state, spec, gen)
    finally:
        _tracing.set_trace_context(prev_trace)


def _stream_results_inner(state: WorkerState, spec: dict, gen) -> None:
    from ray_tpu._private.ids import ObjectID, TaskID

    task_id = spec["task_id"]
    cap = max(1, GLOBAL_CONFIG.streaming_backpressure_items)
    idx = 0
    err = None
    try:
        it = iter(gen)
    except TypeError:
        err = rex.RayTaskError.from_exception(
            spec.get("name", "task"),
            TypeError(
                f'num_returns="streaming" requires the task to return an '
                f"iterable/generator, got {type(gen).__name__}"
            ),
        )
        it = iter(())
    while err is None:
        if task_id in state.cancel_requested:
            err = rex.TaskCancelledError()
            break
        try:
            item = next(it)
        except StopIteration:
            break
        except BaseException as e:  # noqa: BLE001 - ships to consumer
            err = e if isinstance(e, rex.RayTaskError) else rex.RayTaskError.from_exception(
                spec.get("name", "task"), e
            )
            break
        try:
            sv = ser.serialize(item)
        except Exception as e:  # unserializable item
            err = rex.RayTaskError.from_exception(spec.get("name", "task"), e)
            break
        locator = state.ctx.store_value(sv)
        if locator[0] == "shm":
            events.emit(
                "core.object.put",
                size=locator[1].total_size,
                node=locator[1].node,
                seg=locator[1].name,
            )
        with state.stream_cv:
            while (
                idx - state.stream_acked.get(task_id, 0) >= cap
                and task_id not in state.cancel_requested
            ):
                state.stream_cv.wait(timeout=0.5)
        if task_id in state.cancel_requested:
            err = rex.TaskCancelledError()
            break
        oid = ObjectID.for_task_return(TaskID(task_id), 1 + idx).binary()
        state.ctx.send_raw(
            ("stream_item", {"task_id": task_id, "index": idx, "obj_id": oid, "locator": locator})
        )
        idx += 1
    with state.stream_cv:
        state.stream_acked.pop(task_id, None)
    is_error = err is not None
    try:
        results = _store_results(state, spec, err if is_error else None, is_error)
    except BaseException:  # noqa: BLE001
        traceback.print_exc()
        results = []
    _emit_done(
        state,
        {
            "task_id": task_id,
            "results": results,
            "results_error": is_error,
            "stream_count": idx,
        },
    )


def _sync_over_asyncgen(agen, loop):
    """Bridge an async generator to a plain iterator: every ``__anext__``
    is marshalled onto the actor's event loop thread (state invariant),
    while the consuming ``_stream_results`` loop runs on a pool thread."""
    import asyncio

    while True:
        try:
            yield asyncio.run_coroutine_threadsafe(agen.__anext__(), loop).result()
        except StopAsyncIteration:
            return


def _run_task(state: WorkerState, spec: dict):
    if _renv is None:
        _bind_task_mods()
    renv = _renv

    task_id = spec["task_id"]
    state.current_task_id = task_id
    state.task_threads[task_id] = threading.get_ident()
    # task-hop waterfall: a sampled spec arrives with the submitter's,
    # head's, and recv loop's stamps (worker_deserialize is taken at
    # receipt — _stamp_deserialized); exec_start/exec_end bracket the
    # body, and the list rides the task_done payload back so the head
    # can fold reply_recv
    wf = spec.get("wf")
    # re-install the submitter's trace context on the executing thread:
    # spans/events inside the task body (and any nested .remote() hops)
    # carry the same request_id end-to-end (util.tracing module doc).
    # A spec with no context gets a LAZY task-rooted one — the id (and
    # its sampling decision) materialize only if something observes it
    prev_trace = _tracing.set_trace_context(
        _tracing.task_context(spec.get("trace_ctx"), task_id)
    )
    if spec["kind"] != "actor_method":
        # a plain task runs in its SUBMITTER's namespace (client sessions):
        # named-actor ops inside the function resolve where the submitter's
        # would. Actor methods keep the ACTOR's namespace (set at create) —
        # reference semantics: an actor belongs to its job's namespace.
        state.ctx.namespace = spec.get("namespace") or "default"
    is_error = False
    try:
        if task_id in state.cancel_requested:
            raise rex.TaskCancelledError()
        if spec["kind"] == "actor_method":
            method = _resolve_actor_method(state, spec["method_name"])
            args, kwargs = _load_args(state, spec)
            if wf is not None:
                _waterfall.stamp(wf)  # exec_start
            value = method(*args, **kwargs)
        else:
            fn = _resolve_function(state, spec["func_id"])
            args, kwargs = _load_args(state, spec)
            if wf is not None:
                _waterfall.stamp(wf)  # exec_start
            env = spec.get("runtime_env")
            if not env:
                # no runtime env: skip the contextmanager protocol — its
                # enter/exit generator dance is pure overhead per task
                value = fn(*args, **kwargs)
            else:
                with renv.applied(env, state.ctx):
                    value = fn(*args, **kwargs)
        if wf is not None:
            _waterfall.stamp(wf)  # exec_end
    except BaseException as e:  # noqa: BLE001
        if isinstance(e, rex.TaskCancelledError):
            value = e
        elif isinstance(e, rex.RayTaskError):
            value = e
        else:
            value = rex.RayTaskError.from_exception(spec.get("name", "task"), e)
        is_error = True
    finally:
        _tracing.set_trace_context(prev_trace)
        state.current_task_id = None
        state.task_threads.pop(task_id, None)
        state.cancel_requested.discard(task_id)
    if spec.get("num_returns") == "streaming" and not is_error:
        # the function returned a generator: drive it item by item
        # (_stream_results re-installs the trace context for the drive)
        _stream_results(state, spec, value)
        return
    try:
        results = _store_results(state, spec, value, is_error)
    except BaseException:  # noqa: BLE001
        traceback.print_exc()
        results = []
    payload = {"task_id": task_id, "results": results, "results_error": is_error}
    if wf is not None:
        payload["wf"] = wf
    _emit_done(state, payload)


def _emit_done(state: WorkerState, payload: dict) -> None:
    """Ship a completion — coalescing a burst into one reply message.

    An idle worker (nothing else queued) ships INLINE: the sync round trip
    pays zero added latency and no thread handoff. With more work queued,
    the payload joins the reply buffer and the off-path flusher thread
    drains whatever accumulated into ONE tasks_done_batch pickle+write —
    unlike the defer-until-queue-empty idea (tried and reverted pre-PR
    13), a finished result is only ever withheld for the flusher's wakeup,
    never for the DURATION of the next pipelined task."""
    if not state.reply_buf and state.task_queue.empty():
        # idle fast path (the sync round trip): nothing buffered, nothing
        # queued — one send under the drain lock, no buffer round trip.
        # Out-of-order risk is nil: completions are per-task keyed and the
        # in-lock re-check keeps us behind any concurrently buffered batch
        with state.reply_send:
            if not state.reply_buf:
                state.ctx.send_raw(("task_done", payload))
                return
    with state.reply_lock:
        state.reply_buf.append(payload)
        n = len(state.reply_buf)
    if (
        n < GLOBAL_CONFIG.core_reply_batch_max
        and state.running
        and not state.task_queue.empty()
    ):
        _reply_flusher_evt(state).set()
        return
    try:
        _flush_done(state)
    except Exception:
        # conn churn: the batch is back on the buffer — hand it to the
        # flusher's retry loop instead of crashing the exec thread (a
        # detached actor survives the reconnect and re-ships)
        _reply_flusher_evt(state).set()


def _flush_done(state: WorkerState) -> None:
    with state.reply_send:  # one drainer at a time = completion-order FIFO
        with state.reply_lock:
            batch = state.reply_buf
            state.reply_buf = []
        if not batch:
            return
        msg = ("task_done", batch[0]) if len(batch) == 1 else (
            "tasks_done_batch", batch
        )
        try:
            state.ctx.send_raw(msg)
        except Exception:
            # conn died mid-flush: put the batch BACK (front, order kept)
            # so the post-reconnect flush re-ships it — a raise here means
            # the kernel never accepted the bytes, so re-sending on the
            # fresh conn cannot double-deliver
            with state.reply_lock:
                state.reply_buf = batch + state.reply_buf
            raise


def _reply_flusher_evt(state: WorkerState) -> threading.Event:
    evt = state.reply_evt
    if evt is not None:
        return evt
    with state.reply_send:  # double-checked: one flusher per worker
        evt = state.reply_evt
        if evt is not None:
            return evt
        evt = threading.Event()

        def loop():
            import time

            while state.running:
                evt.wait()
                evt.clear()
                while state.running:
                    try:
                        _flush_done(state)
                        break
                    except (BrokenPipeError, ConnectionResetError, EOFError,
                            OSError, ValueError, TypeError):
                        # conn churn (head gone, or a detached-actor
                        # reconnect mid-swap): the batch went back on the
                        # buffer — retry until the fresh conn lands or the
                        # worker exits. NEVER return: a dead flusher with
                        # a live event would silently withhold buffered
                        # completions for up to core_reply_batch_max tasks
                        time.sleep(0.1)
                    except Exception:  # noqa: BLE001 - flusher must survive
                        traceback.print_exc()
                        time.sleep(0.1)

        threading.Thread(target=loop, name="reply-flusher", daemon=True).start()
        state.reply_evt = evt
    return evt


def _resolve_actor_method(state: WorkerState, name: str):
    if name == "__dag_exec__":
        import functools

        return functools.partial(_dag_exec_loop, state.actor_instance)
    return getattr(state.actor_instance, name)


def _dag_exec_loop(instance, method_name: str, in_specs, out_channels, call_on_loop=None):
    """Compiled-DAG executor (reference: compiled_dag_node.py executors).

    Owns this actor's dispatch queue until teardown: block on the input
    channels, invoke the bound method, push the result to every consumer
    edge. Exceptions travel through the channels as wrapped errors so the
    driver's CompiledDAGRef.get re-raises them; channel close ends the loop.

    For async actors the channel loop runs on a daemon thread, and
    ``call_on_loop`` (the actor's event loop) is set: each invocation is
    marshalled onto the loop thread so actor state is still only ever
    touched from that one thread.
    """
    from ray_tpu.dag.compiled import _WrappedError
    from ray_tpu.experimental.channel import ChannelClosed

    method = getattr(instance, method_name)
    if call_on_loop is not None:
        import asyncio
        import concurrent.futures
        import inspect

        inner = method
        if inspect.iscoroutinefunction(inner):
            def method(*a, **k):  # noqa: F811
                return asyncio.run_coroutine_threadsafe(inner(*a, **k), call_on_loop).result()
        else:
            def method(*a, **k):  # noqa: F811
                cfut = concurrent.futures.Future()

                def _run():
                    try:
                        cfut.set_result(inner(*a, **k))
                    except BaseException as e:  # noqa: BLE001
                        cfut.set_exception(e)

                call_on_loop.call_soon_threadsafe(_run)
                return cfut.result()
    while True:
        try:
            # drain EVERY input channel each round, even when one carries an
            # upstream error — skipping reads would desynchronize multi-input
            # nodes (later rounds pairing values from different executions)
            args = []
            upstream_err = None
            for kind, v in in_specs:
                if kind == "chan":
                    v = v.read()
                    if isinstance(v, _WrappedError) and upstream_err is None:
                        upstream_err = v
                args.append(v)
            if upstream_err is not None:
                value = upstream_err
            else:
                try:
                    value = method(*args)
                except BaseException as e:  # noqa: BLE001 - ships to driver
                    value = _WrappedError(e)
            for out in out_channels:
                out.write(value)
        except ChannelClosed:
            return "closed"


def _setup_actor_concurrency(state: WorkerState, spec: dict) -> None:
    """Pick the actor's execution engine (reference: async actors on asyncio
    event loops, _raylet.pyx:2082-2084; threaded actors + concurrency groups,
    core_worker/transport/concurrency_group_manager.cc).

    * any ``async def`` method -> one event-loop thread runs ALL methods
      (so actor state is only ever touched from one thread); per-group
      semaphores bound concurrency. Async actors default to a high limit
      (1000, like the reference) unless max_concurrency says otherwise.
    * plain class + concurrency_groups -> one thread pool per group.
    * plain class + max_concurrency>1 -> single thread pool (legacy path).
    """
    import asyncio
    import inspect

    cls = type(state.actor_instance)
    is_async = any(
        inspect.iscoroutinefunction(getattr(cls, n, None))
        or inspect.isasyncgenfunction(getattr(cls, n, None))
        for n in dir(cls)
        if not n.startswith("__")
    )
    groups = dict(spec.get("concurrency_groups") or {})
    mc = spec.get("max_concurrency")  # None = not set by the user
    if is_async:
        from concurrent.futures import ThreadPoolExecutor

        state.async_loop = asyncio.new_event_loop()
        threading.Thread(
            target=state.async_loop.run_forever, name="actor-asyncio", daemon=True
        ).start()
        # async actors default to high concurrency (reference: 1000); an
        # EXPLICIT max_concurrency=1 genuinely serializes the actor.
        default_limit = 1000 if mc is None else max(int(mc), 1)
        state.group_sems = {"_default": asyncio.Semaphore(default_limit)}
        for g, n in groups.items():
            state.group_sems[g] = asyncio.Semaphore(max(int(n), 1))
        # Blocking head I/O runs on these, never on the loop thread. Arg
        # fetches (which can wait indefinitely on unready ObjectRefs) and
        # result completions get SEPARATE pools: if they shared one, enough
        # blocked loads would starve the _finish_task that produces the very
        # object those loads wait for (deadlock).
        state.async_io_pool = ThreadPoolExecutor(
            max_workers=min(32, max(4, len(groups) * 2 + 4)),
            thread_name_prefix="actor-io",
        )
        state.async_done_pool = ThreadPoolExecutor(
            max_workers=2, thread_name_prefix="actor-done"
        )
    elif groups:
        from concurrent.futures import ThreadPoolExecutor

        state.group_pools = {
            "_default": ThreadPoolExecutor(max_workers=max(int(mc or 1), 1))
        }
        for g, n in groups.items():
            state.group_pools[g] = ThreadPoolExecutor(max_workers=max(int(n), 1))
    elif mc is not None and int(mc) > 1:
        from concurrent.futures import ThreadPoolExecutor

        state.actor_pool = ThreadPoolExecutor(max_workers=int(mc))


def _dispatch_async(state: WorkerState, spec: dict) -> None:
    """Schedule an actor method onto the actor's event loop immediately.

    All blocking head I/O — arg fetch at the start, result store/send at the
    end — runs on ``state.async_io_pool`` threads, never on the dispatch
    thread (one unready ObjectRef arg must not block dispatch of the later
    method that produces it) and never on the loop thread."""
    import asyncio

    asyncio.run_coroutine_threadsafe(_arun(state, spec), state.async_loop)


async def _arun(state: WorkerState, spec: dict):
    import asyncio
    import functools
    import inspect

    if _tracing is None:
        _bind_task_mods()

    loop = asyncio.get_running_loop()
    task_id = spec["task_id"]
    state.async_tasks[task_id] = asyncio.current_task()
    is_error = False
    # task-hop waterfall (sampled specs only; see _run_task — the
    # worker_deserialize stamp was taken at receipt in the recv loop).
    # exec_start is stamped after the arg fetch below; exec_end after
    # the method.
    wf = spec.get("wf")
    # best-effort trace context for async actors: the loop thread is shared,
    # so interleaved coroutines can momentarily see each other's context —
    # spans inside async methods still tag correctly in the common
    # one-request-at-a-time case (sync actors get exact scoping in _run_task).
    # On exit the context is CLEARED (if still ours) rather than restored:
    # under interleaving, a saved "previous" context can belong to a request
    # that already finished, and restoring it would tag the loop thread's
    # later events with a dead request's id indefinitely.
    my_trace = _tracing.task_context(spec.get("trace_ctx"), task_id)
    _tracing.set_trace_context(my_trace)
    try:
        group = spec.get("concurrency_group")
        if group and group not in state.group_sems:
            raise ValueError(
                f"Unknown concurrency group {group!r}; declared groups: "
                f"{sorted(g for g in state.group_sems if g != '_default')}"
            )
        sem = state.group_sems[group or "_default"]
        if task_id in state.cancel_requested:
            raise rex.TaskCancelledError()
        args, kwargs = await loop.run_in_executor(
            state.async_io_pool, functools.partial(_load_args, state, spec)
        )
        async with sem:
            if task_id in state.cancel_requested:
                raise rex.TaskCancelledError()
            method = _resolve_actor_method(state, spec["method_name"])
            if wf is not None:
                _waterfall.stamp(wf)  # exec_start
            if inspect.iscoroutinefunction(method):
                value = await method(*args, **kwargs)
            elif spec["method_name"] == "__dag_exec__":
                # The compiled-DAG executor loop blocks on channels until
                # teardown; parking it on the event loop (or a shared
                # executor) would wedge every other method of this actor.
                # Run the channel loop on a dedicated daemon thread, but
                # marshal each bound-method invocation back onto the event
                # loop (via call_on_loop) so actor state keeps its
                # single-thread invariant (_setup_actor_concurrency).
                method = functools.partial(method, call_on_loop=loop)
                fut = loop.create_future()

                def _dag_runner():
                    try:
                        r = method(*args, **kwargs)
                    except BaseException as e:  # noqa: BLE001
                        res, err = None, e
                    else:
                        res, err = r, None

                    def _complete():
                        if fut.cancelled():
                            return
                        if err is not None:
                            fut.set_exception(err)
                        else:
                            fut.set_result(res)

                    try:
                        loop.call_soon_threadsafe(_complete)
                    except RuntimeError:
                        pass  # loop already closed (worker shutdown)

                threading.Thread(target=_dag_runner, daemon=True, name="dag-exec").start()
                value = await fut
            else:
                value = method(*args, **kwargs)
        if wf is not None:
            _waterfall.stamp(wf)  # exec_end
    except BaseException as e:  # noqa: BLE001
        if isinstance(e, asyncio.CancelledError):
            value = rex.TaskCancelledError()
        elif isinstance(e, (rex.TaskCancelledError, rex.RayTaskError)):
            value = e
        else:
            value = rex.RayTaskError.from_exception(spec.get("name", "task"), e)
        is_error = True
    finally:
        if _tracing.get_trace_context() is my_trace:
            _tracing.set_trace_context(None)
        state.async_tasks.pop(task_id, None)
        state.cancel_requested.discard(task_id)
    if spec.get("num_returns") == "streaming" and not is_error:
        # drive the generator off the loop thread; async generators are
        # bridged so each __anext__ still runs ON the loop (single-thread
        # actor-state invariant)
        if inspect.isasyncgen(value):
            value = _sync_over_asyncgen(value, loop)
        state.async_done_pool.submit(_stream_results, state, spec, value)
        return
    # fire-and-forget onto the dedicated completion pool: must not be
    # cancellable, must not serialize on the loop thread, and must not queue
    # behind blocked arg fetches (see _setup_actor_concurrency)
    state.async_done_pool.submit(_finish_task, state, spec, value, is_error)


def _finish_task(state: WorkerState, spec: dict, value, is_error: bool) -> None:
    try:
        results = _store_results(state, spec, value, is_error)
    except BaseException:  # noqa: BLE001
        traceback.print_exc()
        results = []
    payload = {"task_id": spec["task_id"], "results": results, "results_error": is_error}
    wf = spec.get("wf")
    if wf is not None:
        payload["wf"] = wf  # waterfall stamps ride the reply (head folds)
    state.ctx.send_raw(("task_done", payload))


def _cli_main():
    """Entry point for ``python -m ray_tpu._private.worker_main`` — workers
    are exec'd fresh (reference: worker_pool spawning default_worker.py), so
    they never re-import the driver's __main__ module."""
    import sys

    socket_path, authkey_hex, node_id_hex = sys.argv[1], sys.argv[2], sys.argv[3]
    token = sys.argv[4] if len(sys.argv) > 4 else ""
    remote = len(sys.argv) > 5 and sys.argv[5] == "--remote"
    main(
        socket_path,
        bytes.fromhex(authkey_hex),
        bytes.fromhex(node_id_hex),
        token=token,
        remote=remote,
    )


def _run_actor_create(state: WorkerState, spec: dict):
    from ray_tpu._private import runtime_env as renv

    try:
        cls = _resolve_function(state, spec["func_id"])
        args, kwargs = _load_args(state, spec)
        # permanent: the actor owns this worker process for life, so its
        # runtime env applies to every subsequent method call too
        with renv.applied(spec.get("runtime_env"), state.ctx, permanent=True):
            state.actor_instance = cls(*args, **kwargs)
        state.actor_id = spec["actor_id"]
        # detached actors outlive the head: on conn loss they retry the
        # head address and rebind instead of dying (reference: raylet
        # reconnect window; gcs_actor_manager re-registration on failover)
        state.detached = spec.get("lifetime") == "detached"
        state.ctx.current_actor = spec["actor_id"].hex()  # for get_runtime_context()
        # the actor lives in its namespace for good (worker is dedicated)
        state.ctx.namespace = spec.get("namespace") or "default"
        _setup_actor_concurrency(state, spec)
        state.ctx.send_raw(("actor_ready", {"actor_id": spec["actor_id"], "error": None}))
    except BaseException as e:  # noqa: BLE001
        err = rex.RayTaskError.from_exception(spec.get("name", "actor"), e)
        state.ctx.send_raw(("actor_ready", {"actor_id": spec["actor_id"], "error": err}))


if __name__ == "__main__":
    _cli_main()
