"""Zero-copy-aware serialization.

TPU-native counterpart of the reference's ``python/ray/_private/serialization.py``
(+ vendored cloudpickle): values are serialized with cloudpickle at pickle
protocol 5 so large contiguous buffers (numpy arrays, jax host arrays via
dlpack→numpy, arrow buffers) travel out-of-band. The out-of-band buffers are
what the shared-memory store lays out contiguously, giving zero-copy reads on
the consumer side (the plasma mmap equivalent).
"""

from __future__ import annotations

import io
import os
import pickle
from typing import Any

import cloudpickle


class SerializedValue:
    """A pickled header plus out-of-band buffers.

    total_size == len(header) + sum(buffer sizes); the store uses this to
    decide inline vs shared-memory placement.
    """

    __slots__ = ("header", "buffers", "total_size")

    def __init__(self, header: bytes, buffers: list[pickle.PickleBuffer]):
        self.header = header
        self.buffers = buffers
        self.total_size = len(header) + sum(len(b.raw()) for b in buffers)

    def to_bytes(self) -> bytes:
        """Flatten to a single self-describing byte string (for socket
        transport of small objects)."""
        if not self.buffers:
            # no out-of-band buffers (every small value): one concat, no
            # BytesIO round trip — this runs once per task result
            return (
                len(self.header).to_bytes(8, "little")
                + b"\x00\x00\x00\x00"
                + self.header
            )
        out = io.BytesIO()
        out.write(len(self.header).to_bytes(8, "little"))
        out.write(len(self.buffers).to_bytes(4, "little"))
        for b in self.buffers:
            out.write(len(b.raw()).to_bytes(8, "little"))
        out.write(self.header)
        for b in self.buffers:
            out.write(b.raw())
        return out.getvalue()

    @classmethod
    def from_bytes(cls, data: bytes | memoryview) -> "SerializedValue":
        mv = memoryview(data)
        if len(mv) < 12:
            raise ValueError("truncated serialized value")
        hlen = int.from_bytes(mv[:8], "little")
        nbuf = int.from_bytes(mv[8:12], "little")
        off = 12
        if nbuf > (len(mv) - off) // 8:
            raise ValueError("corrupt serialized value (buffer count)")
        sizes = []
        for _ in range(nbuf):
            sizes.append(int.from_bytes(mv[off : off + 8], "little"))
            off += 8
        if off + hlen + sum(sizes) != len(mv):
            # length mismatch = truncated/corrupt payload (spill-file rot is
            # the practical case; callers fall back to lineage/LOST)
            raise ValueError("corrupt serialized value (length mismatch)")
        header = bytes(mv[off : off + hlen])
        off += hlen
        bufs = []
        for s in sizes:
            bufs.append(pickle.PickleBuffer(mv[off : off + s]))
            off += s
        return cls(header, bufs)


def serialize(value: Any) -> SerializedValue:
    buffers: list[pickle.PickleBuffer] = []

    def cb(buf: pickle.PickleBuffer):
        # Only keep genuinely large buffers out-of-band; tiny ones are cheaper
        # inline in the header.
        if buf.raw().nbytes >= 4096:
            buffers.append(buf)
            return False  # out-of-band
        return True  # serialize in-band

    try:
        # C pickler first: ~10x cheaper per call — this runs once per task
        # result and once per by-value argument. Out-of-band buffer
        # extraction works identically. Lambdas/closures raise here and
        # fall back; the DANGEROUS case is silent success: pickle encodes
        # driver-__main__ classes/functions BY REFERENCE, which a worker
        # (different __main__) cannot resolve — cloudpickle pickles them
        # by value. Any __main__ marker in the payload routes to the
        # fallback (a false hit from a user string merely costs the old
        # cloudpickle price).
        header = pickle.dumps(value, protocol=5, buffer_callback=cb)
        if b"__main__" in header or b"__mp_main__" in header:
            raise ValueError("__main__ reference: reserialize by value")
    except Exception:
        del buffers[:]  # a partial out-of-band list must not leak through
        header = cloudpickle.dumps(value, protocol=5, buffer_callback=cb)
    return SerializedValue(header, buffers)


def deserialize(header: bytes | memoryview, buffers: list) -> Any:
    return pickle.loads(header, buffers=buffers)


def deserialize_value(sv: SerializedValue) -> Any:
    return pickle.loads(sv.header, buffers=sv.buffers)


def dumps(value: Any) -> bytes:
    """Convenience: fully in-band cloudpickle (control-plane metadata)."""
    return cloudpickle.dumps(value)


def loads(data: bytes) -> Any:
    return pickle.loads(data)


def conn_send(conn, msg) -> None:
    """Control-plane message send: one-shot C ``pickle.dumps`` straight
    into the connection. ``Connection.send`` builds a ForkingPickler plus
    a BytesIO per message — ~10us of pure overhead that is real money on
    the task plane's per-message hot paths. Control messages carry plain
    data (dicts/bytes/exceptions), never the fd-passing types the mp
    reducers exist for, and the peer's ``recv`` unpickles identically."""
    try:
        conn._send_bytes(pickle.dumps(msg, protocol=5))
    except AttributeError:  # exotic conn without the CPython internals
        conn.send(msg)
    except TypeError as e:
        # a concurrent close nulls the Connection's _handle mid-send and
        # os.write(None, ...) raises TypeError; surface the same family
        # Connection.send's _check_closed raised (OSError) so every
        # existing send guard — worker-death reap, reply guards, the IO
        # loop — keeps classifying it as a dead conn instead of dying
        raise OSError(f"connection closed during send: {e}") from e


#: the flattened serialization of ``None`` — deterministic, so producers
#: ship the constant without re-pickling and consumers recognize it with
#: one bytes compare (the single most common task result: every
#: mutator/noop returns None)
NONE_BYTES = serialize(None).to_bytes()


_NO_MSG = object()

#: sentinel for split_spec_body's identity elision (header values may be None)
_MISSING = object()


def spec_header_id(*parts) -> bytes:
    """Stable 8-byte spec-header id from content parts (bytes pass
    through, everything else hashes by ``repr`` — so ``"streaming"`` and
    ``1`` are both valid ``num_returns`` inputs). Content-derived on
    purpose: every process that rebuilds the same header (deserialized
    actor handles, re-pickled remote functions) mints the SAME id, so
    receiver-side header caches dedupe instead of growing per copy.
    The ONE id rule for both minting sites (ActorHandle._submit_method,
    RemoteFunction._remote) — keep them in lockstep."""
    import hashlib

    h = hashlib.sha1()
    for p in parts:
        h.update(p if isinstance(p, bytes) else repr(p).encode())
        h.update(b"\x00")
    return h.digest()[:8]


def split_spec_body(spec: dict, fields: dict) -> dict:
    """Header-split elision (ISSUE 14), the ONE implementation both the
    submitter (`runtime._split_for_wire`) and the head (`Head._wire_spec`)
    use — the wire protocol desynchronizes if the rule ever forks. Keep
    only the keys whose values are NOT the very objects the header already
    carries: templates share static fields by reference end to end, so
    identity comparison elides them, and anything rebound per call (a
    resolved ``max_retries``, a ``_pg_bundle``) rides the body."""
    return {
        k: v
        for k, v in spec.items()
        if k != "_hdr" and fields.get(k, _MISSING) is not v
    }


class ConnReader:
    """Buffered framed reader over a ``multiprocessing.Connection`` fd.

    ``Connection.recv`` costs two ``os.read`` syscalls per message (4-byte
    length header, then the body) plus a BytesIO round trip — real money
    at one completion per task. This reader pulls whatever the kernel has
    in ONE read and parses out every complete frame, so a burst of
    coalesced replies costs one syscall, not two per message. Framing
    matches ``Connection._send_bytes``: ``!i`` length prefix, with the
    ``-1 + !Q`` escape for >2GB bodies. The wrapped conn must have no
    other reader once this is attached (send side is unaffected)."""

    __slots__ = ("conn", "fd", "buf")

    def __init__(self, conn):
        self.conn = conn
        self.fd = conn.fileno()
        self.buf = bytearray()

    def _pop(self):
        buf = self.buf
        n = len(buf)
        if n < 4:
            return _NO_MSG
        size = int.from_bytes(buf[:4], "big", signed=True)
        off = 4
        if size == -1:
            if n < 12:
                return _NO_MSG
            size = int.from_bytes(buf[4:12], "big")
            off = 12
        end = off + size
        if n < end:
            return _NO_MSG
        msg = pickle.loads(memoryview(buf)[off:end])
        del buf[:end]
        return msg

    def recv(self):
        """Blocking single-message recv (worker recv loop)."""
        while True:
            msg = self._pop()
            if msg is not _NO_MSG:
                return msg
            data = os.read(self.fd, 65536)
            if not data:
                raise EOFError
            self.buf += data

    def read_available(self) -> list:
        """One kernel read, every complete frame parsed (head IO drain —
        call only when select reported the fd readable). Raises EOFError
        on a closed peer."""
        try:
            data = os.read(self.fd, 262144)
        except BlockingIOError:
            data = None
        if data is not None:
            if not data:
                raise EOFError
            self.buf += data
        out = []
        while True:
            msg = self._pop()
            if msg is _NO_MSG:
                return out
            out.append(msg)
