"""Zero-copy-aware serialization.

TPU-native counterpart of the reference's ``python/ray/_private/serialization.py``
(+ vendored cloudpickle): values are serialized with cloudpickle at pickle
protocol 5 so large contiguous buffers (numpy arrays, jax host arrays via
dlpack→numpy, arrow buffers) travel out-of-band. The out-of-band buffers are
what the shared-memory store lays out contiguously, giving zero-copy reads on
the consumer side (the plasma mmap equivalent).
"""

from __future__ import annotations

import io
import pickle
from typing import Any

import cloudpickle


class SerializedValue:
    """A pickled header plus out-of-band buffers.

    total_size == len(header) + sum(buffer sizes); the store uses this to
    decide inline vs shared-memory placement.
    """

    __slots__ = ("header", "buffers", "total_size")

    def __init__(self, header: bytes, buffers: list[pickle.PickleBuffer]):
        self.header = header
        self.buffers = buffers
        self.total_size = len(header) + sum(len(b.raw()) for b in buffers)

    def to_bytes(self) -> bytes:
        """Flatten to a single self-describing byte string (for socket
        transport of small objects)."""
        out = io.BytesIO()
        out.write(len(self.header).to_bytes(8, "little"))
        out.write(len(self.buffers).to_bytes(4, "little"))
        for b in self.buffers:
            out.write(len(b.raw()).to_bytes(8, "little"))
        out.write(self.header)
        for b in self.buffers:
            out.write(b.raw())
        return out.getvalue()

    @classmethod
    def from_bytes(cls, data: bytes | memoryview) -> "SerializedValue":
        mv = memoryview(data)
        if len(mv) < 12:
            raise ValueError("truncated serialized value")
        hlen = int.from_bytes(mv[:8], "little")
        nbuf = int.from_bytes(mv[8:12], "little")
        off = 12
        if nbuf > (len(mv) - off) // 8:
            raise ValueError("corrupt serialized value (buffer count)")
        sizes = []
        for _ in range(nbuf):
            sizes.append(int.from_bytes(mv[off : off + 8], "little"))
            off += 8
        if off + hlen + sum(sizes) != len(mv):
            # length mismatch = truncated/corrupt payload (spill-file rot is
            # the practical case; callers fall back to lineage/LOST)
            raise ValueError("corrupt serialized value (length mismatch)")
        header = bytes(mv[off : off + hlen])
        off += hlen
        bufs = []
        for s in sizes:
            bufs.append(pickle.PickleBuffer(mv[off : off + s]))
            off += s
        return cls(header, bufs)


def serialize(value: Any) -> SerializedValue:
    buffers: list[pickle.PickleBuffer] = []

    def cb(buf: pickle.PickleBuffer):
        # Only keep genuinely large buffers out-of-band; tiny ones are cheaper
        # inline in the header.
        if buf.raw().nbytes >= 4096:
            buffers.append(buf)
            return False  # out-of-band
        return True  # serialize in-band

    header = cloudpickle.dumps(value, protocol=5, buffer_callback=cb)
    return SerializedValue(header, buffers)


def deserialize(header: bytes | memoryview, buffers: list) -> Any:
    return pickle.loads(header, buffers=buffers)


def deserialize_value(sv: SerializedValue) -> Any:
    return pickle.loads(sv.header, buffers=sv.buffers)


def dumps(value: Any) -> bytes:
    """Convenience: fully in-band cloudpickle (control-plane metadata)."""
    return cloudpickle.dumps(value)


def loads(data: bytes) -> Any:
    return pickle.loads(data)
