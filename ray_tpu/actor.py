"""Actors: stateful workers with ordered method calls.

Counterpart of the reference's ``python/ray/actor.py`` (``ActorClass._remote``
:830, ``ActorHandle``, ``ActorMethod``). An actor is a dedicated worker
process holding a class instance; method calls are pushed in submission order
over the head→worker FIFO socket (= the reference's sequential actor submit
queue), with ``max_concurrency`` switching to a thread pool. Restart-on-death
follows ``max_restarts`` / ``max_task_retries``
(reference: gcs_actor_manager.cc state machine).
"""

from __future__ import annotations

import functools
from typing import Any, Optional

from ray_tpu._private import options as opt
from ray_tpu._private import serialization as ser
from ray_tpu._private.ids import ActorID
from ray_tpu._private.runtime import get_ctx


class ActorMethod:
    def __init__(
        self,
        handle: "ActorHandle",
        name: str,
        num_returns: int = 1,
        concurrency_group: Optional[str] = None,
    ):
        self._handle = handle
        self._name = name
        self._num_returns = num_returns
        self._concurrency_group = concurrency_group

    _SUPPORTED_OPTIONS = frozenset({"num_returns", "concurrency_group"})

    def options(self, **options) -> "ActorMethod":
        unknown = set(options) - self._SUPPORTED_OPTIONS
        if unknown:
            raise ValueError(
                f"Unsupported actor-method options: {sorted(unknown)} "
                f"(supported: {sorted(self._SUPPORTED_OPTIONS)})"
            )
        return ActorMethod(
            self._handle,
            self._name,
            options.get("num_returns", self._num_returns),
            options.get("concurrency_group", self._concurrency_group),
        )

    def remote(self, *args, **kwargs):
        return self._handle._submit_method(
            self._name, args, kwargs, self._num_returns, self._concurrency_group
        )

    def bind(self, *args, **kwargs):
        """DAG node for this actor method (reference: dag ClassMethodNode);
        compiled DAGs bind methods on live actor handles."""
        from ray_tpu.dag.compiled import ClassMethodNode

        return ClassMethodNode(self._handle, self._name, args, kwargs)

    def __call__(self, *a, **k):
        raise TypeError(
            f"Actor method {self._name}() cannot be called directly; use .remote()."
        )


class ActorHandle:
    def __init__(self, actor_id: bytes, methods: dict[str, dict], class_name: str, owned: bool):
        self._actor_id = actor_id
        self._methods = methods
        self._class_name = class_name
        self._owned = owned
        # spec headers per (method, num_returns): the static call fields
        # ship once per connection/worker, bodies reference them by id
        # (cheaper per-task bytes, ISSUE 14); rebuilt fresh after
        # deserialization — header ids are connection-lifetime cheap
        self._hdr_cache: dict = {}

    @property
    def _actor_id_hex(self) -> str:
        return self._actor_id.hex()

    @property
    def __dag_exec__(self) -> ActorMethod:
        """Internal: the compiled-DAG executor loop entry (worker builtin)."""
        return ActorMethod(self, "__dag_exec__")

    def __getattr__(self, name: str) -> ActorMethod:
        if name.startswith("_"):
            raise AttributeError(name)
        meta = self._methods.get(name)
        if meta is None:
            raise AttributeError(f"Actor {self._class_name} has no method {name!r}")
        return ActorMethod(
            self, name, meta.get("num_returns", 1), meta.get("concurrency_group")
        )

    def _submit_method(self, name, args, kwargs, num_returns, concurrency_group=None):
        # trace-context propagation: the submitter's context rides the
        # spec by reference (sampled dict, or the shared unsampled token
        # that keeps forensics correlated while spans stay free); with no
        # active context the worker roots a lazy trace at the task id
        from ray_tpu.util import tracing as _tracing
        from ray_tpu.util import waterfall as _waterfall

        ctx = get_ctx()
        streaming = num_returns == "streaming"
        tctx = _tracing.get_trace_context()
        sp_ctx = _tracing.context_for_spec(tctx) if tctx is not None else None
        # task-hop waterfall: sampled request/reply calls stamp phases
        # (streaming replies arrive long after exec — no waterfall)
        wf = None if streaming else _waterfall.maybe_start(sp_ctx)
        s_args, s_kwargs = ctx.serialize_args(args, kwargs)
        if wf is not None:
            _waterfall.stamp(wf)  # serialize: args done, spec build next
        task_id, return_ids = ctx.new_task_returns(
            1 if streaming else max(num_returns, 1)
        )
        hdr = self._hdr_cache.get((name, num_returns))
        if hdr is None:
            from ray_tpu._private.runtime import EMPTY_ARGS, EMPTY_KWARGS

            fields = {
                "kind": "actor_method",
                "actor_id": self._actor_id,
                "method_name": name,
                "num_returns": num_returns,
                "name": f"{self._class_name}.{name}",
                # no-arg calls elide these by identity (serialize_args
                # returns the same constants)
                "args": EMPTY_ARGS,
                "kwargs": EMPTY_KWARGS,
            }
            # CONTENT-derived id (ser.spec_header_id), not per-instance
            # urandom: every deserialized copy of this handle
            # (handle-per-task serve patterns mint thousands) produces the
            # SAME id for the same fields, so receiver-side header caches
            # dedupe instead of growing one entry per handle copy forever
            hid = ser.spec_header_id(
                b"actor_method", self._actor_id, name, num_returns
            )
            hdr = self._hdr_cache[(name, num_returns)] = (hid, fields)
        spec = {
            **hdr[1],
            "task_id": task_id,
            "args": s_args,
            "kwargs": s_kwargs,
            "return_ids": return_ids,
            "_hdr": hdr,
        }
        if sp_ctx is not None:
            spec["trace_ctx"] = sp_ctx
        if wf is not None:
            spec["wf"] = wf
        if concurrency_group:
            spec["concurrency_group"] = concurrency_group
        refs = ctx.submit_actor_task(spec)
        if streaming:
            from ray_tpu._private.runtime import ObjectRefGenerator

            return ObjectRefGenerator(task_id, refs[0], ctx)
        return refs[0] if num_returns == 1 else refs

    def __repr__(self):
        return f"ActorHandle({self._class_name}, {self._actor_id.hex()[:8]})"

    def __reduce__(self):
        # A handle crossing a serialization boundary pins the actor for the
        # session (conservative GC; see ObjectRef.__reduce__).
        try:
            get_ctx().call("actor_inc_handle", actor_id=self._actor_id)
        except Exception:
            pass
        return (_deserialize_handle, (self._actor_id, self._methods, self._class_name))

    def __del__(self):
        # GC-safe: a blocking RPC from a GC tick can deadlock against a
        # thread that holds the head lock (see ObjectRef.__del__); only a
        # reentrant queue put is allowed here.
        if self._owned:
            try:
                ctx = get_ctx()
                if not ctx.closed:
                    ctx.enqueue_gc(
                        "call", ("actor_dec_handle", {"actor_id": self._actor_id})
                    )
            except Exception:
                pass


def _deserialize_handle(actor_id, methods, class_name):
    return ActorHandle(actor_id, methods, class_name, owned=False)


class ActorClass:
    def __init__(self, cls: type, default_options: dict[str, Any]):
        self._cls = cls
        self._options = default_options
        opt.validate(self._options, is_actor=True)
        self._blob: Optional[bytes] = None
        functools.update_wrapper(self, cls, updated=[])

    def __call__(self, *a, **k):
        raise TypeError(
            f"Actor class {self._cls.__name__} cannot be instantiated directly; "
            f"use {self._cls.__name__}.remote()."
        )

    def options(self, **new_options) -> "ActorClass":
        merged = {**self._options, **new_options}
        ac = ActorClass(self._cls, merged)
        ac._blob = self._blob
        return ac

    def method_table(self) -> dict[str, dict]:
        methods = {}
        for name in dir(self._cls):
            if name.startswith("__"):
                continue
            m = getattr(self._cls, name, None)
            if callable(m):
                methods[name] = {"num_returns": getattr(m, "_num_returns", 1)}
                group = getattr(m, "_concurrency_group", None)
                if group:
                    methods[name]["concurrency_group"] = group
        return methods

    def remote(self, *args, **kwargs):
        return self._remote(args, kwargs, self._options)

    def _remote(self, args, kwargs, options):
        ctx = get_ctx()
        name = options.get("name")
        if name and options.get("get_if_exists"):
            try:
                # look where the CREATE would register (explicit namespace,
                # or "default" for detached) — the bare ctx namespace would
                # miss and then collide in create_actor
                ns = options.get("namespace") or (
                    "default" if options.get("lifetime") == "detached" else None
                )
                return get_actor(name, namespace=ns)
            except ValueError:
                pass
        if self._blob is None:
            self._blob = ser.dumps(self._cls)
        func_id = ctx.upload_function(self._blob)
        s_args, s_kwargs = ctx.serialize_args(args, kwargs)
        actor_id = ActorID.from_random().binary()
        task_id, return_ids = ctx.new_task_returns(1)
        methods = self.method_table()
        spec = {
            "task_id": task_id,
            "kind": "actor_create",
            "actor_id": actor_id,
            "func_id": func_id,
            "args": s_args,
            "kwargs": s_kwargs,
            "num_returns": 1,
            "return_ids": return_ids,
            "resources": opt.to_resources(options, is_actor=True),
            "strategy": opt.to_strategy(options),
            "max_restarts": options.get("max_restarts", 0),
            "max_task_retries": options.get("max_task_retries", 0),
            # None = "not set": async actors then default to high concurrency
            # (1000, reference semantics) while an explicit 1 serializes them
            "max_concurrency": options.get("max_concurrency"),
            "concurrency_groups": options.get("concurrency_groups"),
            "name": options.get("name") or self._cls.__name__,
            "lifetime": options.get("lifetime"),
            # detached actors are cluster-scoped services: they register in
            # the shared "default" namespace so every client session can
            # find them; regular named actors scope to the creator's
            # session namespace (reference: namespaces + detached lifetime)
            "namespace": options.get("namespace")
            or (
                "default"
                if options.get("lifetime") == "detached"
                else getattr(ctx, "namespace", "default")
            ),
            "methods": methods,
        }
        if not options.get("name"):
            spec["name"] = None  # anonymous actors are not registered by name
        spec["class_name"] = self._cls.__name__
        if options.get("runtime_env"):
            from ray_tpu._private import runtime_env as renv

            spec["runtime_env"] = renv.package(options["runtime_env"], ctx, kind="actor")
        # head.submit_task takes the submitter's refs on return_ids; the
        # except-free below is a no-op when the failure preceded the submit
        # (remove_ref on a missing entry does nothing)
        try:
            ctx.call("create_actor", spec=spec)
        except Exception:
            for rid in return_ids:
                ctx.call("free_ref_async", obj_id=rid)
            raise
        return ActorHandle(actor_id, methods, self._cls.__name__, owned=True)

    def bind(self, *args, **kwargs):
        from ray_tpu.dag import ClassNode

        return ClassNode(self, args, kwargs)


def get_actor(name: str, namespace: Optional[str] = None) -> ActorHandle:
    """Look up a named actor (reference: ``ray.get_actor``). Scoped to the
    caller's namespace; detached actors in "default" are cluster-visible."""
    ctx = get_ctx()
    actor_id, methods = ctx.call(
        "get_actor_named",
        name=name,
        namespace=namespace or getattr(ctx, "namespace", None),
        timeout=0.0,
    )
    spec_methods = methods or {}
    return ActorHandle(actor_id, spec_methods, name, owned=False)


def method(**kwargs):
    """Decorator to override per-method defaults, e.g.
    ``@ray_tpu.method(num_returns=2)`` or
    ``@ray_tpu.method(concurrency_group="io")`` (reference: ``ray.method``,
    concurrency groups in ``core_worker/transport/concurrency_group_manager.cc``)."""

    def wrap(fn):
        if "num_returns" in kwargs:
            fn._num_returns = kwargs["num_returns"]
        if "concurrency_group" in kwargs:
            fn._concurrency_group = kwargs["concurrency_group"]
        return fn

    return wrap
