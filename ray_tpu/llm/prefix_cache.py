"""Cross-request prefix cache: a radix tree of shared KV blocks.

Production chat traffic is dominated by shared system prompts and
few-shot prefixes, yet a plain paged engine re-prefills every request
from token 0.  This module makes prefill work proportional to the
*uncached suffix*: a block-content-keyed radix/trie tree maps token
blocks to resident physical KV blocks, and requests whose prompt walks
an existing path borrow those blocks instead of recomputing them.

Correctness rests on one fact about causal attention: for two sequences
whose first ``P`` tokens are identical, the KV entries at positions
``0..P-1`` are identical too (each position's k/v depends only on the
tokens at and before it).  Prefix reuse is therefore exact — outputs are
token-identical with the cache on or off, under greedy and seeded
sampling alike — never approximate.

Structure (vLLM-style block granularity rather than SGLang's token-level
radix nodes — it composes with the pool's static block ledger):

* **one node per full block** — a child edge is keyed by the EXACT
  ``block_size``-token tuple it covers (collision-free; hashes are an
  index, tokens are the key), and carries the physical block id whose
  device k/v holds those positions.  A root-to-node path spells a prompt
  prefix; the path's block ids are a ready-made block-table prefix.
* **copy-on-write fork on divergence inside a block** — when the prompt
  diverges from a cached path mid-block, the partially-matching child's
  block is FORKED: the engine device-copies it into a fresh block
  (``model_runner.fork_blocks``) and prefill resumes at the divergence
  point, not the block boundary.  Fully-matched blocks are shared
  read-only (prefill/decode never scatter into positions below the
  request's prefill start, so a shared block is never written).
* **refcounts in the pool** — ``KVBlockPool`` counts every reference
  (sequence owners + one for cache residency).  A block drops to the
  free list only at zero; a cached block whose sequences all finished
  (ref == 1, cache-only) is *evictable*.
* **LRU eviction under pressure** — the scheduler reclaims evictable
  leaf blocks (least-recently-matched first) BEFORE preempting live
  requests; eviction removes the tree node and releases the cache's
  reference in one motion, so there is never a dangling tree entry.
  Leaf-only eviction keeps every remaining path contiguous.

Consistency: all tree mutations happen under the engine lock (admission
match, prefill insert, pressure eviction, weight-swap flush); the
internal lock additionally makes reads (stats, audit, drafter corpus)
safe from the watchdog and drafter threads.  Lock order is always
engine → cache → pool; the pool never calls back up.

A weight hot-swap (``LLMEngine.update_weights``) FLUSHES the tree:
cached k/v was computed under the old parameters and must not seed new
requests (in-flight requests keep their blocks — their refs outlive the
flush — matching the existing mid-swap semantics).

Observability: ``llm.prefix.*`` recorder events (``hit``/``insert``/
``evict``/``flush``) and the ``llm_prefix_cache_*`` metric family
(OBSERVABILITY.md); ``audit()`` is wired into the watchdog's leak audit.
"""

from __future__ import annotations

import dataclasses
import itertools
import threading
from typing import Optional, Sequence

from ray_tpu._private import events as _events
from ray_tpu.llm.cache import KVBlockPool

#: metric names, exported so the grafana row and the docs stay aligned
#: with the code (tests cross-check ``util.grafana`` against this tuple)
METRIC_NAMES = (
    "llm_prefix_cache_hit_tokens",
    "llm_prefix_cache_miss_tokens",
    "llm_prefix_cache_evicted_blocks",
    "llm_prefix_cache_hit_rate",
    "llm_prefix_cache_blocks",
)

_METRICS = None
_METRICS_LOCK = threading.Lock()


def _metrics() -> dict:
    global _METRICS
    if _METRICS is not None:
        return _METRICS
    with _METRICS_LOCK:
        if _METRICS is not None:
            return _METRICS
        from ray_tpu.util.metrics import Counter, Gauge

        _METRICS = {
            "hit_tokens": Counter(
                "llm_prefix_cache_hit_tokens",
                "prompt tokens served from cached KV blocks (prefill skipped)",
            ),
            "miss_tokens": Counter(
                "llm_prefix_cache_miss_tokens",
                "prompt tokens that had to be prefilled (cache miss)",
            ),
            "evicted": Counter(
                "llm_prefix_cache_evicted_blocks",
                "cached KV blocks evicted under pool pressure",
            ),
            "hit_rate": Gauge(
                "llm_prefix_cache_hit_rate",
                "lifetime hit_tokens / (hit_tokens + miss_tokens)",
            ),
            "blocks": Gauge(
                "llm_prefix_cache_blocks", "KV blocks resident in the prefix tree"
            ),
        }
    return _METRICS


@dataclasses.dataclass(frozen=True)
class PrefixMatch:
    """Result of matching a prompt against the tree.

    ``blocks`` are the physical ids of fully-matched cached blocks, in
    prompt order — they become the head of the request's block table.
    ``cow_src``/``cow_tokens`` describe a partial match inside the NEXT
    block: fork ``cow_src`` (device copy) and its first ``cow_tokens``
    positions are already valid.  ``matched`` is the total token count
    (``len(blocks) * block_size + cow_tokens``); it is always capped at
    ``len(prompt) - 1`` so at least one token remains to prefill (the
    final prefill position's logits seed generation)."""

    blocks: tuple = ()
    matched: int = 0
    cow_src: Optional[int] = None
    cow_tokens: int = 0


class _Node:
    """One cached block: the exact tokens it covers and where they live."""

    __slots__ = ("tokens", "block", "children", "parent", "last_used")

    def __init__(self, tokens: tuple, block: int, parent: "_Node"):
        self.tokens = tokens
        self.block = block
        self.children: dict = {}
        self.parent = parent
        self.last_used = 0


class PrefixCache:
    """Radix tree over the pool's blocks (module doc).  One per engine."""

    def __init__(self, pool: KVBlockPool, cow_min_tokens: int = 1):
        if cow_min_tokens < 1:
            raise ValueError("cow_min_tokens must be >= 1")
        self.pool = pool
        #: minimum intra-block match worth a device block copy — below it
        #: the divergent block is simply prefilled from its first token
        self.cow_min_tokens = cow_min_tokens
        self._root = _Node((), -1, None)  # sentinel: no block, no tokens
        self._by_block: dict[int, _Node] = {}
        self._clock = itertools.count(1)
        self._lock = threading.Lock()
        #: bumped by every flush().  Admission stamps the current epoch
        #: onto the request; ``insert`` refuses blocks from an older
        #: epoch — a request mid-prefill across a weight swap computed
        #: (some of) its KV under the OLD parameters, and re-registering
        #: it would hand stale KV to the very requests the flush protects.
        self.epoch = 0
        # lifetime accounting (metrics mirror these; stats() reads them)
        self.hit_tokens = 0
        self.miss_tokens = 0
        self.evicted_blocks = 0
        self.cow_forks = 0

    # -- matching ----------------------------------------------------------

    def match(self, tokens: Sequence[int]) -> PrefixMatch:
        """Longest cached prefix of ``tokens`` (full blocks + an optional
        intra-block CoW tail), capped at ``len(tokens) - 1``.  Touches the
        matched path's LRU clock; records no hit/miss metrics — callers
        call ``record`` once the match is actually USED (admission can
        retry, and a retried match must not double-count)."""
        bs = self.pool.cfg.block_size
        limit = len(tokens) - 1  # >= 1 token must remain to prefill
        with self._lock:
            node = self._root
            blocks: list[int] = []
            i = 0
            while i + bs <= limit:
                child = node.children.get(tuple(tokens[i : i + bs]))
                if child is None:
                    break
                blocks.append(child.block)
                child.last_used = next(self._clock)
                node = child
                i += bs
            cow_src: Optional[int] = None
            cow_tokens = 0
            rem = limit - i
            if rem >= self.cow_min_tokens and node.children:
                tail = tuple(tokens[i : i + min(rem, bs)])
                best_len = 0
                best: Optional[_Node] = None
                for key, child in node.children.items():
                    n = 0
                    for a, b in zip(key, tail):
                        if a != b:
                            break
                        n += 1
                    if n > best_len:
                        best_len, best = n, child
                if best is not None and best_len >= self.cow_min_tokens:
                    cow_src, cow_tokens = best.block, best_len
                    best.last_used = next(self._clock)
            return PrefixMatch(
                blocks=tuple(blocks),
                matched=i + cow_tokens,
                cow_src=cow_src,
                cow_tokens=cow_tokens,
            )

    def record(self, req, match: Optional[PrefixMatch], total_tokens: int) -> None:
        """Account a COMMITTED match (the request was admitted with it):
        hit/miss counters, hit-rate gauge, and the ``llm.prefix.hit``
        event when anything was actually reused."""
        m = _metrics()
        matched = match.matched if match is not None else 0
        missed = max(total_tokens - matched, 0)
        with self._lock:
            self.hit_tokens += matched
            self.miss_tokens += missed
            if match is not None and match.cow_src is not None:
                self.cow_forks += 1
            hits, misses = self.hit_tokens, self.miss_tokens
        if matched:
            m["hit_tokens"].inc(matched)
        if missed:
            m["miss_tokens"].inc(missed)
        m["hit_rate"].set(hits / max(hits + misses, 1))
        if match is not None and matched:
            _events.record(
                "llm.prefix.hit", request_id=req.trace_id, engine_req=req.id,
                matched_tokens=matched, blocks=len(match.blocks),
                cow_tokens=match.cow_tokens, miss_tokens=missed,
            )

    # -- insertion ---------------------------------------------------------

    def insert(self, tokens: Sequence[int], blocks: Sequence[int],
               limit: int, epoch: Optional[int] = None) -> int:
        """Register the sequence's fully-prefilled prompt blocks: block
        ``b`` is inserted once positions ``[b*bs, (b+1)*bs)`` all sit
        below ``limit`` (callers pass ``min(prefill_pos, len(prompt))`` —
        only PROMPT-content blocks are cacheable; generated tokens never
        enter the tree).  Existing nodes (including this sequence's own
        shared prefix) are touched, not duplicated; a new node takes a
        cache reference on the block (``pool.cache_retain``).  Returns
        the number of nodes created.

        ``epoch`` — the flush epoch the sequence was ADMITTED under
        (``self.epoch`` at admission).  A stale epoch means a weight
        swap flushed the tree mid-prefill: this sequence's KV is (partly)
        old-parameter output and must not re-enter the tree."""
        if epoch is not None and epoch != self.epoch:
            return 0
        bs = self.pool.cfg.block_size
        n_full = min(limit // bs, len(blocks))
        created = 0
        with self._lock:
            node = self._root
            for b in range(n_full):
                key = tuple(tokens[b * bs : (b + 1) * bs])
                child = node.children.get(key)
                if child is None:
                    blk = blocks[b]
                    if blk in self._by_block:
                        break  # one node per physical block
                    # The node is built BEFORE the pool reference is
                    # taken, so the retain → tree-registration window
                    # holds only plain stores: an error escaping between
                    # the two would strand a cache reference no tree node
                    # tracks — a leak only the runtime audit would see
                    # (RL015's bug class; its conservative model treats
                    # any call, even this trivial constructor, as able to
                    # raise).
                    fresh = _Node(key, blk, node)
                    # only blocks the pool can take a reference on
                    # (defensive: a block freed between prefill and
                    # insert must not resurrect)
                    if not self.pool.cache_retain(blk):
                        break
                    child = fresh
                    node.children[key] = child
                    self._by_block[blk] = child
                    created += 1
                child.last_used = next(self._clock)
                node = child
            n_nodes = len(self._by_block)
        if created:
            _metrics()["blocks"].set(n_nodes)
            _events.record("llm.prefix.insert", blocks=created, total=n_nodes)
        return created

    # -- eviction ----------------------------------------------------------

    def evict(self, n_blocks: int, protect: frozenset = frozenset()) -> int:
        """Free up to ``n_blocks`` evictable blocks (cache-only refcount,
        leaf nodes, least-recently-used first), skipping ``protect`` (the
        blocks an in-flight admission is about to share — they may be
        cache-only until ``allocate`` takes its reference).  Node removal
        and ``pool.cache_release`` happen together, so the tree never
        holds a dangling block id.  Returns the number freed."""
        freed = 0
        with self._lock:
            while freed < n_blocks:
                best: Optional[_Node] = None
                for blk, node in self._by_block.items():
                    if node.children or blk in protect:
                        continue
                    if not self.pool.is_evictable(blk):
                        continue
                    if best is None or node.last_used < best.last_used:
                        best = node
                if best is None:
                    break
                del best.parent.children[best.tokens]
                del self._by_block[best.block]
                self.pool.cache_release(best.block)
                freed += 1
            self.evicted_blocks += freed
            n_nodes = len(self._by_block)
        if freed:
            m = _metrics()
            m["evicted"].inc(freed)
            m["blocks"].set(n_nodes)
            _events.record(
                "llm.prefix.evict", blocks=freed, remaining=n_nodes,
                reason="pressure",
            )
        return freed

    def flush(self, reason: str = "flush") -> int:
        """Drop the whole tree (weight hot-swap: cached k/v was computed
        under the old parameters).  Blocks still referenced by in-flight
        sequences keep THEIR references — only the cache's are released;
        such blocks return to the free list when their sequences finish."""
        with self._lock:
            n = len(self._by_block)
            for blk in list(self._by_block):
                self.pool.cache_release(blk)
            self._by_block.clear()
            self._root = _Node((), -1, None)
            self.epoch += 1  # in-flight prefills may no longer insert
        if n:
            _metrics()["blocks"].set(0)
            _events.record("llm.prefix.flush", blocks=n, reason=reason)
        return n

    # -- drafting corpus ---------------------------------------------------

    def paths(self, max_paths: int = 8) -> list:
        """Root-to-leaf token sequences, most recently used first — the
        cross-request drafting corpus (``NGramDrafter.corpus``): a warm
        request's continuation often literally already sits on a cached
        path another request prefilled.  Bounded by ``max_paths`` so the
        per-step drafting cost stays constant."""
        with self._lock:
            leaves = [n for n in self._by_block.values() if not n.children]
            leaves.sort(key=lambda n: n.last_used, reverse=True)
            out = []
            for leaf in leaves[:max_paths]:
                rev = []
                node = leaf
                while node is not None and node.block != -1:
                    rev.append(node.tokens)
                    node = node.parent
                seq: list[int] = []
                for toks in reversed(rev):
                    seq.extend(toks)
                out.append(seq)
            return out

    # -- introspection -----------------------------------------------------

    def stats(self) -> dict:
        with self._lock:
            hits, misses = self.hit_tokens, self.miss_tokens
            return {
                "nodes": len(self._by_block),
                "cached_blocks": len(self._by_block),
                "hit_tokens": hits,
                "miss_tokens": misses,
                "hit_rate": hits / max(hits + misses, 1),
                "evicted_blocks": self.evicted_blocks,
                "cow_forks": self.cow_forks,
            }

    def audit(self) -> dict:
        """Tree↔pool cross-check (the watchdog's leak audit calls this
        beside ``KVBlockPool.audit``): every tree node's block must be
        cache-held in the pool, every cache-held pool block must have a
        tree node, and parent links must be intact.  Needs no engine
        lock — safe in the wedged-step path."""
        with self._lock:
            nodes = dict(self._by_block)
            held = self.pool.cache_held_blocks()
            dangling = [
                b for b, n in nodes.items()
                if b not in held or n.parent is None
                or n.parent.children.get(n.tokens) is not n
            ]
            unindexed = [b for b in held if b not in nodes]
        return {
            "ok": not dangling and not unindexed,
            "nodes": len(nodes),
            "dangling": dangling,
            "unindexed": unindexed,
        }
