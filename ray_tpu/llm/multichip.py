"""Tensor-parallel paged inference — the multi-chip LLM engine substrate.

The single-chip serving stack (``llm.cache`` pool, ``llm.model_runner``
jitted steps, ``ops.paged_attention``) caps the servable model at one
chip's HBM.  This module lifts exactly that stack onto a 1-axis
``("tp",)`` mesh (``parallel.mesh.make_tp_mesh``) with the classic
Megatron column/row split, chosen so the PAGED layout shards for free:

* **KV block pool** — head axis sharded, ``P(None, None, "tp", None,
  None)`` over ``(layers, num_blocks, heads, block_size, head_dim)``.
  Block ids are GLOBAL (every device holds the same blocks' local
  heads), so the host-side ledger, block tables, prefix-cache radix
  tree, watchdog ``audit()`` and CoW fork bookkeeping are untouched —
  the only sharded thing is the payload.
* **Attention** — per-head math never crosses heads: q/k/v projections
  are column-parallel (each device computes its own heads), the paged
  gather/scatter and softmax run on the local head group, and only the
  output projection is row-parallel (one ``psum`` per layer).
* **MLP** — ``mlp_in`` column-parallel, ``mlp_out`` row-parallel,
  second ``psum``.  GPT-J's parallel residual lets attention and MLP
  share a single fused reduction per layer.
* **Everything else** (embedding, layernorms, lm_head, sampling) is
  replicated: post-``psum`` activations are identical on all devices,
  so every device samples the same token and the engine reads one
  replicated result.

The three jitted entry points (decode / prefill / verify) and the CoW
``fork_blocks`` keep their single-chip signatures — ``LLMEngine``,
speculative decoding, preemption-recompute, failover ``resume_tokens``
and the prefix cache run UNCHANGED on top; ``EngineConfig(tp=N)`` is
the only switch.  Off-TPU this runs on jax host-platform device-count
meshes (``XLA_FLAGS=--xla_force_host_platform_device_count``), which is
how tier-1 exercises tp=2/4 on CPU; Pallas kernels stay interpret-gated
per ``ops.paged_attention.INTERPRET_ONLY``.

Numerics: splitting the two row-parallel contractions across devices
changes the floating-point reduction order, so activations drift from
the single-chip engine by ~1 ulp per layer.  Greedy argmax and
fixed-seed sampling are robust to that (pinned by
``tests/test_llm_multichip.py``'s tp=1 vs tp=2/4 identity matrix); the
per-head attention path itself is bitwise identical per head.
"""

from __future__ import annotations

import time
from typing import Any

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from ray_tpu._private.jax_compat import shard_map
from ray_tpu.llm.cache import CacheConfig, KVBlockPool
from ray_tpu.llm.model_runner import (
    PagedModelRunner,
    _fork_impl,
    _layernorm,
    _sample_rows,
    _scatter_kv,
    _verify_rows,
)
from ray_tpu.ops.paged_attention import (
    paged_attention,
    paged_prefill_attention_xla,
    paged_verify_attention,
)
from ray_tpu.parallel.mesh import make_tp_mesh


def _per_device_bytes(mesh, leaves) -> dict:
    """device-id label -> local bytes actually resident on that device
    (replicated leaves count once PER device — that copy is real HBM).
    The HBM ledger's per-device attribution reads this."""
    out = {str(d.id): 0 for d in mesh.devices.flat}
    for leaf in leaves:
        for sh in getattr(leaf, "addressable_shards", ()):
            key = str(sh.device.id)
            if key in out:
                out[key] += int(sh.data.nbytes)
    return out


class ShardedKVBlockPool(KVBlockPool):
    """KV block pool whose device arrays are head-sharded over the tp
    mesh.  The host ledger (free list, refcounts, audit) is inherited
    verbatim — block ids are global, so every ledger invariant and the
    watchdog's leak audit hold independent of the mesh size."""

    def __init__(
        self,
        cfg: CacheConfig,
        n_layers: int,
        n_heads: int,
        head_dim: int,
        dtype="float32",
        *,
        tp: int = 1,
    ):
        if tp < 1:
            raise ValueError(f"tp must be >= 1, got {tp}")
        if n_heads % tp:
            raise ValueError(
                f"n_heads={n_heads} not divisible by tp={tp} — the pool "
                "shards the head axis"
            )
        self.tp = tp
        self._mesh = make_tp_mesh(tp)
        super().__init__(
            cfg, n_layers, n_heads, head_dim, dtype,
            sharding=NamedSharding(self._mesh, P(None, None, "tp", None, None)),
        )

    def per_device_bytes(self) -> dict:
        """Local pool bytes per device — ``device_bytes / tp`` each, the
        whole point of sharding the pool."""
        return _per_device_bytes(self._mesh, (self.k, self.v))


class TensorParallelPagedModelRunner(PagedModelRunner):
    """``PagedModelRunner`` with the jitted steps shard_map'd over the
    tp mesh.  Wrapper methods (``decode_step``/``verify_step``/
    ``fork_blocks``) and the engine-facing contract are inherited; only
    the traced bodies and parameter placement change."""

    def __init__(
        self,
        cfg: Any,
        params: dict,
        block_size: int,
        attn_impl: str = "auto",
        *,
        tp: int,
    ):
        super().__init__(cfg, params, block_size, attn_impl)
        if tp < 1:
            raise ValueError(f"tp must be >= 1, got {tp}")
        if cfg.n_heads % tp:
            raise ValueError(f"n_heads={cfg.n_heads} not divisible by tp={tp}")
        if cfg.d_ff % tp:
            raise ValueError(f"d_ff={cfg.d_ff} not divisible by tp={tp}")
        self.tp = tp
        self._mesh = make_tp_mesh(tp)
        # inherited _qkv_rows reshapes to this many heads — the ones
        # whose kernels' column shards live on this device
        self.n_local_heads = cfg.n_heads // tp
        self.params = self.prepare_params(params)
        pspecs = self._param_spec_tree()
        # re-jit the step functions over the mesh (the base jits were
        # never traced); donation contract is the base class's — the
        # pool shards update in place
        self._decode = jax.jit(
            shard_map(
                self._decode_shard,
                mesh=self._mesh,
                in_specs=(
                    pspecs,
                    P(None, None, "tp", None, None),
                    P(None, None, "tp", None, None),
                    P(), P(), P(), P(), P(), P(), P(), P(),
                ),
                out_specs=(
                    P(None, None, "tp", None, None),
                    P(None, None, "tp", None, None),
                    P(), P(),
                ),
                check_vma=False,
            ),
            donate_argnums=(1, 2),
        )
        self._verify = jax.jit(
            shard_map(
                self._verify_shard,
                mesh=self._mesh,
                in_specs=(
                    pspecs,
                    P(None, None, "tp", None, None),
                    P(None, None, "tp", None, None),
                    P(), P(), P(), P(), P(), P(), P(), P(),
                ),
                out_specs=(
                    P(None, None, "tp", None, None),
                    P(None, None, "tp", None, None),
                    P(), P(), P(),
                ),
                check_vma=False,
            ),
            donate_argnums=(1, 2),
        )
        self._prefill = jax.jit(
            shard_map(
                self._prefill_shard,
                mesh=self._mesh,
                in_specs=(
                    pspecs,
                    P(None, None, "tp", None, None),
                    P(None, None, "tp", None, None),
                    P(), P(), P(), P(),
                ),
                out_specs=(
                    P(None, None, "tp", None, None),
                    P(None, None, "tp", None, None),
                    P(),
                ),
                check_vma=False,
            ),
            donate_argnums=(1, 2),
        )
        # CoW fork copies whole blocks along axis 1 — head-agnostic, so
        # the single-chip impl runs per-shard unchanged
        self._fork = jax.jit(
            shard_map(
                _fork_impl,
                mesh=self._mesh,
                in_specs=(
                    P(None, None, "tp", None, None),
                    P(None, None, "tp", None, None),
                    P(), P(),
                ),
                out_specs=(
                    P(None, None, "tp", None, None),
                    P(None, None, "tp", None, None),
                ),
                check_vma=False,
            ),
            donate_argnums=(0, 1),
        )

    # -- parameter placement ----------------------------------------------

    def _spec_for(self, path) -> P:
        """Megatron split by param path: q/k/v + mlp_in column-parallel
        (output dim sharded, biases ride along), attn_out + mlp_out
        row-parallel (input dim sharded, replicated biases added after
        the psum), everything else replicated."""
        names = [getattr(p, "key", None) for p in path]
        if names and names[0] == "blocks" and len(names) >= 3:
            mod, slot = names[1], names[-1]
            if mod in ("q", "k", "v", "attn_qkv", "mlp_in"):
                return P(None, None, "tp") if slot == "kernel" else P(None, "tp")
            if mod in ("attn_out", "mlp_out") and slot == "kernel":
                return P(None, "tp", None)
        return P()

    def _param_spec_tree(self):
        return jax.tree_util.tree_map_with_path(
            lambda path, _leaf: self._spec_for(path), self.params
        )

    def _shuffle_qkv(self, x: jax.Array) -> jax.Array:
        """GPT's fused qkv projection lays its last axis out ``[Q|K|V]``;
        plain column sharding would hand device i a slice of Q spilling
        into K.  Permute host-side to the concat over devices of
        ``[Q_i|K_i|V_i]`` so each device's contiguous shard splits
        locally into its own head group's q/k/v (shape preserved, so
        ``update_weights`` leaf validation is unaffected)."""
        d = x.shape[-1] // 3
        dl = d // self.tp
        q, k, v = jnp.split(x, 3, axis=-1)
        parts = []
        for i in range(self.tp):
            sl = slice(i * dl, (i + 1) * dl)
            parts.extend([q[..., sl], k[..., sl], v[..., sl]])
        return jnp.concatenate(parts, axis=-1)

    def prepare_params(self, params: dict) -> dict:
        """Sharded ``device_put`` of a (new) weight tree — the
        ``update_weights`` hot-swap path and __init__ share it, so a
        swap lands with the exact placement the compiled steps expect
        (no silent retrace; RL024's runtime twin watches this)."""
        new = jax.tree_util.tree_map(jnp.asarray, params)
        if self.arch == "gpt":
            new = dict(new)
            blocks = dict(new["blocks"])
            qkv = dict(blocks["attn_qkv"])
            qkv["kernel"] = self._shuffle_qkv(qkv["kernel"])
            qkv["bias"] = self._shuffle_qkv(qkv["bias"])
            blocks["attn_qkv"] = qkv
            new["blocks"] = blocks
        return jax.tree_util.tree_map_with_path(
            lambda path, leaf: jax.device_put(
                leaf, NamedSharding(self._mesh, self._spec_for(path))
            ),
            new,
        )

    def per_device_param_bytes(self) -> dict:
        """device-id label -> param bytes resident there (column/row
        shards + this device's copy of every replicated leaf)."""
        return _per_device_bytes(
            self._mesh, jax.tree_util.tree_leaves(self.params)
        )

    # -- per-device layer math --------------------------------------------

    def _tp_layer(self, x, layer, k_l, v_l, positions, phys, off, attend):
        """One transformer layer on THIS device's head/ff shard.
        ``attend(q, k_l, v_l) -> (rows, local_d)`` supplies the step
        shape's paged attention over the local head group; the two
        row-parallel projections produce partial sums reduced with
        ``psum`` over "tp" (replicated biases added once, after)."""
        dt = x.dtype
        if self.arch == "gptj":
            h = _layernorm(x, layer["ln1"]["scale"], layer["ln1"]["bias"])
            q, k, v = self._qkv_rows(layer, h, positions)
            k_l = _scatter_kv(k_l, k.astype(k_l.dtype), phys, off)
            v_l = _scatter_kv(v_l, v.astype(v_l.dtype), phys, off)
            att_p = attend(q, k_l, v_l) @ layer["attn_out"]["kernel"].astype(dt)
            mid = jax.nn.gelu(
                h @ layer["mlp_in"]["kernel"].astype(dt)
                + layer["mlp_in"]["bias"].astype(dt)
            )
            mlp_p = mid @ layer["mlp_out"]["kernel"].astype(dt)
            # parallel residual: attention + MLP partials share ONE
            # fused reduction per layer (half the collectives of the
            # sequential-residual arch below)
            out = (
                x
                + jax.lax.psum(att_p + mlp_p, "tp")
                + layer["mlp_out"]["bias"].astype(dt)
            )
        else:
            ln1 = _layernorm(x, layer["ln1"]["scale"], layer["ln1"]["bias"])
            q, k, v = self._qkv_rows(layer, ln1, positions)
            k_l = _scatter_kv(k_l, k.astype(k_l.dtype), phys, off)
            v_l = _scatter_kv(v_l, v.astype(v_l.dtype), phys, off)
            att_p = attend(q, k_l, v_l) @ layer["attn_out"]["kernel"].astype(dt)
            h = (
                x
                + jax.lax.psum(att_p, "tp")
                + layer["attn_out"]["bias"].astype(dt)
            )
            ln2 = _layernorm(h, layer["ln2"]["scale"], layer["ln2"]["bias"])
            mid = jax.nn.gelu(
                ln2 @ layer["mlp_in"]["kernel"].astype(dt)
                + layer["mlp_in"]["bias"].astype(dt)
            )
            out = (
                h
                + jax.lax.psum(mid @ layer["mlp_out"]["kernel"].astype(dt), "tp")
                + layer["mlp_out"]["bias"].astype(dt)
            )
        return out, k_l, v_l

    # -- shard bodies ------------------------------------------------------
    # Same control flow as the PagedModelRunner._*_impl bodies, with the
    # pool/head math local and the reductions explicit.  Post-psum
    # activations are replicated, so lm_head + sampling run identically
    # on every device and the P() out_specs read one copy.

    def _decode_shard(
        self, params, k_pool, v_pool, tokens, positions, tables,
        temp, top_k, top_p, seeds, counters,
    ):
        bs = self.block_size
        S = tokens.shape[0]
        x = self._embed(params, tokens, positions)
        phys = jnp.take_along_axis(tables, (positions // bs)[:, None], axis=1)[:, 0]
        off = positions % bs
        lengths = positions + 1
        runner = self

        def one_layer(carry, inputs):
            x = carry
            layer, k_l, v_l = inputs

            def attend(q, k_loc, v_loc):
                return paged_attention(
                    q, k_loc, v_loc, tables, lengths, impl=runner.attn_impl
                ).astype(x.dtype).reshape(S, -1)

            out, k_l, v_l = runner._tp_layer(
                x, layer, k_l, v_l, positions, phys, off, attend
            )
            return out, (k_l, v_l)

        x, (k_pool, v_pool) = jax.lax.scan(
            one_layer, x, (params["blocks"], k_pool, v_pool)
        )
        logits = self._lm_head(params, x)
        nxt, logp = _sample_rows(logits, seeds, counters, temp, top_k, top_p)
        return k_pool, v_pool, nxt, logp

    def _verify_shard(
        self, params, k_pool, v_pool, tokens, base_pos, tables,
        temp, top_k, top_p, seeds, counters,
    ):
        cfg = self.cfg
        bs = self.block_size
        S, W = tokens.shape
        tmax = tables.shape[1]
        positions = base_pos[:, None] + jnp.arange(W, dtype=jnp.int32)[None, :]
        pos_flat = positions.reshape(-1)
        x = self._embed(params, tokens.reshape(-1), pos_flat)
        # overflow positions clamp to trash exactly like the base impl
        valid = pos_flat < tmax * bs
        logical = jnp.minimum(pos_flat // bs, tmax - 1)
        tables_rep = jnp.repeat(tables, W, axis=0)
        phys = jnp.where(
            valid,
            jnp.take_along_axis(tables_rep, logical[:, None], axis=1)[:, 0],
            0,
        )
        off = pos_flat % bs
        runner = self
        nh, hd = self.n_local_heads, cfg.head_dim

        def one_layer(carry, inputs):
            x = carry
            layer, k_l, v_l = inputs

            def attend(q, k_loc, v_loc):
                return paged_verify_attention(
                    q.reshape(S, W, nh, hd), k_loc, v_loc, tables, positions,
                    impl=runner.attn_impl,
                ).astype(x.dtype).reshape(S * W, -1)

            out, k_l, v_l = runner._tp_layer(
                x, layer, k_l, v_l, pos_flat, phys, off, attend
            )
            return out, (k_l, v_l)

        x, (k_pool, v_pool) = jax.lax.scan(
            one_layer, x, (params["blocks"], k_pool, v_pool)
        )
        logits = self._lm_head(params, x).reshape(S, W, -1)
        n_acc, out, logp = _verify_rows(
            logits, tokens[:, 1:], seeds, counters, temp, top_k, top_p
        )
        return k_pool, v_pool, n_acc, out, logp

    def _prefill_shard(
        self, params, k_pool, v_pool, tokens, start, n_valid, table,
    ):
        # chunk is tokens.shape[0] — static under jit, but NOT a static
        # kwarg: shard_map takes positional specs only, and the engine
        # always pads to cfg.prefill_chunk so this still traces once
        bs = self.block_size
        chunk = tokens.shape[0]
        positions = start + jnp.arange(chunk, dtype=jnp.int32)
        valid = jnp.arange(chunk) < n_valid
        x = self._embed(params, tokens, positions)
        phys = jnp.where(valid, table[positions // bs], 0)
        off = positions % bs
        runner = self

        def one_layer(carry, inputs):
            x = carry
            layer, k_l, v_l = inputs

            def attend(q, k_loc, v_loc):
                return paged_prefill_attention_xla(
                    q, k_loc, v_loc, table, positions
                ).astype(x.dtype).reshape(chunk, -1)

            out, k_l, v_l = runner._tp_layer(
                x, layer, k_l, v_l, positions, phys, off, attend
            )
            return out, (k_l, v_l)

        x, (k_pool, v_pool) = jax.lax.scan(
            one_layer, x, (params["blocks"], k_pool, v_pool)
        )
        last = x[jnp.maximum(n_valid - 1, 0)]
        logits = self._lm_head(params, last[None, :])[0]
        return k_pool, v_pool, logits

    def prefill_chunk(self, k_pool, v_pool, tokens, start, n_valid, table):
        # base passes chunk= as a static kwarg; the shard body derives it
        t0 = time.perf_counter()
        out = self._prefill(
            self.params, k_pool, v_pool, tokens,
            jnp.int32(start), jnp.int32(n_valid), table,
        )
        self._note_compile("prefill", len(tokens), t0)
        self.prof.note("prefill", self._prefill, time.perf_counter() - t0)
        return out
