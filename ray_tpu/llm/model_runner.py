"""Paged-cache model execution: jitted prefill-chunk and decode-step fns.

Bridges the model zoo (``models.gpt``, ``models.gptj``) to the paged KV
cache: where ``gptj_decode`` owns a dense per-call cache, these functions
thread the SHARED block pool through every call — scatter the new
positions' k/v into physical blocks, attend via ``ops.paged_attention``,
and hand back the updated pool arrays (functional updates; the engine
holds the current version).

Three entry shapes, each jitted once per engine:

* ``decode_step`` — (slots,) one token per running slot, batched across
  heterogeneous sequences (different lengths, block tables, sampling
  params).  Inactive slots carry position 0 and an all-trash block table;
  their writes land in reserved block 0 and their sampled tokens are
  discarded host-side.  Every sampled token returns with its behavior
  logprob (``models.sampling`` logprob convention — the RLHF capture
  path), as does every verified window position below.
* ``prefill_chunk`` — (chunk,) tokens of ONE sequence at positions
  ``start..start+chunk`` (tail-padded; padded positions scatter to the
  trash block).  Returns the last valid position's logits so the final
  chunk seeds the first generated token.
* ``verify_step`` — (slots, k+1) speculative-decode verification: each
  slot feeds its last emitted token plus ``k`` drafted tokens, their k/v
  scatter PROVISIONALLY into the pool, one multi-query paged attention
  (``ops.paged_verify_attention``) yields all ``k+1`` positions' logits,
  and ``models.sampling.speculative_verify`` accepts a prefix + one
  correction/bonus token per slot.  Rejected positions need no device
  rollback — they sit beyond the sequence length, everything masks by
  length, and the next window overwrites them first (the block LEDGER
  rolls back host-side via ``cache.shrink_to``).  Window positions past
  the table's reach scatter to the trash block, so slots at the model-
  length cap stay safe (their surplus logits are discarded host-side).

Static shapes everywhere: slot count, chunk size, window width ``k+1``,
table width, and pool geometry are compile-time constants — admission,
preemption, completion, and per-step acceptance-length changes never
retrace.
"""

from __future__ import annotations

import time
from typing import Any

import jax
import jax.numpy as jnp

from ray_tpu._private import events as _events
from ray_tpu.models.gpt import GPTConfig, _layernorm
from ray_tpu.util.device_prof import JitProfiler
from ray_tpu.models.gptj import GPTJConfig
from ray_tpu.models.sampling import (
    sample_tokens_logprobs,
    speculative_verify_logprobs,
)
from ray_tpu.ops.paged_attention import (
    paged_attention,
    paged_prefill_attention_xla,
    paged_verify_attention,
)


def _rotary_rows(x: jax.Array, positions: jax.Array, rotary_dim: int) -> jax.Array:
    """GPT-J interleaved rotary with PER-ROW positions. x: (n, heads, hd);
    positions: (n,) int32.  (models.gptj applies one shared position vector
    across the batch; decode slots each sit at a different position.)"""
    inv_freq = 1.0 / (
        10000.0 ** (jnp.arange(0, rotary_dim, 2, dtype=jnp.float32) / rotary_dim)
    )
    ang = positions.astype(jnp.float32)[:, None] * inv_freq[None, :]  # (n, r/2)
    sin, cos = jnp.sin(ang), jnp.cos(ang)
    rot, pas = x[..., :rotary_dim], x[..., rotary_dim:]
    r = rot.astype(jnp.float32).reshape(*rot.shape[:-1], rotary_dim // 2, 2)
    x1, x2 = r[..., 0], r[..., 1]
    c = cos[:, None, :]
    s = sin[:, None, :]
    o1 = x1 * c - x2 * s
    o2 = x2 * c + x1 * s
    out = jnp.stack([o1, o2], axis=-1).reshape(rot.shape).astype(x.dtype)
    return jnp.concatenate([out, pas], axis=-1) if pas.shape[-1] else out


def _scatter_kv(pool_l: jax.Array, vals: jax.Array, phys: jax.Array, off: jax.Array):
    """Write per-row k or v into physical blocks.  pool_l: (num_blocks,
    heads, block, d); vals: (n, heads, d); phys/off: (n,) int32."""
    n, heads, _ = vals.shape
    return pool_l.at[
        phys[:, None], jnp.arange(heads)[None, :], off[:, None], :
    ].set(vals)


def _sample_rows(logits, seeds, counters, temp, top_k, top_p):
    """Per-row sampling with per-request determinism: row i's key derives
    from (seeds[i], counters[i]) only, so a request draws the same tokens
    no matter which slot or step it lands in.  Returns (tokens (n,),
    logprobs (n,)) — the chosen-token behavior logprob rides along free
    (``models.sampling`` module doc)."""
    keys = jax.vmap(lambda s, c: jax.random.fold_in(jax.random.PRNGKey(s), c))(
        seeds, counters
    )

    def one(lg, k, t, kk, pp):
        tok, lp = sample_tokens_logprobs(
            lg[None, :], k, t[None], kk[None], pp[None]
        )
        return tok[0], lp[0]

    return jax.vmap(one)(logits, keys, temp, top_k, top_p)


def _fork_impl(k_pool, v_pool, src, dst):
    """Copy-on-write block fork for the prefix cache: duplicate whole
    physical blocks across every layer — ``pool[:, dst[i]] = pool[:,
    src[i]]``.  A block copy is a memmove; recomputing the same positions
    through the model is L layer matmuls — the fork wins by orders of
    magnitude.  Unused lanes pad with (0, 0): trash copied onto trash,
    harmless and value-deterministic even with duplicate dst indices."""
    k_pool = k_pool.at[:, dst].set(k_pool[:, src])
    v_pool = v_pool.at[:, dst].set(v_pool[:, src])
    return k_pool, v_pool


def _verify_rows(logits, draft, seeds, counters, temp, top_k, top_p):
    """Per-slot speculative verification (same per-request determinism as
    ``_sample_rows``: window token i keys off (seed, counter + i)).
    logits: (S, W, V); draft: (S, W-1).  Returns (n_accepted (S,),
    out_tokens (S, W), out_logprobs (S, W))."""
    return jax.vmap(speculative_verify_logprobs)(
        logits, draft, seeds, counters, temp, top_k, top_p
    )


class PagedModelRunner:
    """Owns the jitted step functions for one (config, params) pair."""

    def __init__(self, cfg: Any, params: dict, block_size: int, attn_impl: str = "auto"):
        if isinstance(cfg, GPTJConfig):
            self.arch = "gptj"
        elif isinstance(cfg, GPTConfig):
            if cfg.n_experts > 0:
                raise NotImplementedError("paged decode supports dense GPT only")
            self.arch = "gpt"
        else:
            raise TypeError(f"unsupported model config {type(cfg).__name__}")
        self.cfg = cfg
        self.params = params
        self.block_size = block_size
        self.attn_impl = attn_impl
        # heads THIS runner's traced bodies see: all of them single-chip;
        # the tensor-parallel subclass (llm.multichip) narrows this to its
        # per-device head group and reuses _qkv_rows unchanged
        self.n_local_heads = cfg.n_heads
        # donate the pool buffers: the scatter of each step's k/v updates
        # in place instead of copying the whole pool every call (the pool
        # is the biggest array in inference — a per-step copy would cost
        # more than the step's math)
        self._decode = jax.jit(self._decode_impl, donate_argnums=(1, 2))
        self._prefill = jax.jit(
            self._prefill_impl, donate_argnums=(1, 2), static_argnames=("chunk",)
        )
        self._verify = jax.jit(self._verify_impl, donate_argnums=(1, 2))
        self._fork = jax.jit(_fork_impl, donate_argnums=(0, 1))
        self._compiled: set = set()  # (fn, shape-key)s already traced
        # device-step profiler: per-call wall time into device_step_seconds
        # {site=decode|prefill|verify|fork} + retrace detection against the
        # jit cache size — a site recompiling after its warmup baseline
        # emits llm.retrace and trips the retrace-storm SLO (per-runner so
        # two engines in one process never compare cache sizes)
        self.prof = JitProfiler(event="llm.retrace")

    def _note_compile(self, fn: str, key: Any, t0: float) -> None:
        """Flight-recorder marker for each jit trace+compile: the first
        call per (fn, static-shape) pays the compile, and that wall time
        dominating a serve replica's init (or a mid-traffic retrace, which
        should NEVER happen — static shapes) is exactly what a postmortem
        needs to see.  Subsequent steady-state calls record nothing."""
        if (fn, key) in self._compiled:
            return
        self._compiled.add((fn, key))
        _events.record(
            "llm.compile", fn=fn, shape=str(key), arch=self.arch,
            first_call_s=round(time.perf_counter() - t0, 3),
        )

    def prepare_params(self, params: dict) -> dict:
        """Normalize a (new) weight tree to the placement the compiled
        steps expect.  Single-chip that is just host->device conversion;
        the tensor-parallel runner overrides this with its sharded
        ``device_put`` (plus the fused-qkv column permutation), and
        ``LLMEngine.update_weights`` routes every hot-swap through here
        so swapped weights land exactly like the originals."""
        return jax.tree_util.tree_map(jnp.asarray, params)

    # -- shared layer math -------------------------------------------------

    def _qkv_rows(self, layer, h, positions):
        """h: (n, d) post-ln hidden → q/k/v (n, heads, hd), rotary applied
        for gptj."""
        cfg = self.cfg
        dt = h.dtype
        n = h.shape[0]
        nh, hd = self.n_local_heads, cfg.head_dim
        if self.arch == "gptj":
            q = (h @ layer["q"]["kernel"].astype(dt)).reshape(n, nh, hd)
            k = (h @ layer["k"]["kernel"].astype(dt)).reshape(n, nh, hd)
            v = (h @ layer["v"]["kernel"].astype(dt)).reshape(n, nh, hd)
            q = _rotary_rows(q, positions, cfg.rotary_dim)
            k = _rotary_rows(k, positions, cfg.rotary_dim)
        else:
            qkv = h @ layer["attn_qkv"]["kernel"].astype(dt) + layer["attn_qkv"][
                "bias"
            ].astype(dt)
            q, k, v = jnp.split(qkv, 3, axis=-1)
            q = q.reshape(n, nh, hd)
            k = k.reshape(n, nh, hd)
            v = v.reshape(n, nh, hd)
        return q, k, v

    def _mlp(self, layer, h):
        dt = h.dtype
        mid = jax.nn.gelu(
            h @ layer["mlp_in"]["kernel"].astype(dt) + layer["mlp_in"]["bias"].astype(dt)
        )
        return mid @ layer["mlp_out"]["kernel"].astype(dt) + layer["mlp_out"][
            "bias"
        ].astype(dt)

    def _attn_out(self, layer, att_flat):
        dt = att_flat.dtype
        out = att_flat @ layer["attn_out"]["kernel"].astype(dt)
        if self.arch == "gpt":
            out = out + layer["attn_out"]["bias"].astype(dt)
        return out

    def _embed(self, params, tokens, positions):
        # params flows through the TRACED argument, never self.params: the
        # jitted executables cache across weight hot-swaps
        # (LLMEngine.update_weights), so anything read from self here would
        # bake the ORIGINAL weights into the compiled step as constants —
        # a swap would then silently update only the layer stack
        cfg = self.cfg
        dt = jnp.dtype(cfg.dtype)
        x = params["embed"]["tokens"][tokens].astype(dt)
        if self.arch == "gpt":
            # clamp: padded prefill-tail positions may run past the table
            pos = jnp.minimum(positions, cfg.seq_len - 1)
            x = x + params["embed"]["pos"][pos].astype(dt)
        return x

    def _lm_head(self, params, h):
        h = _layernorm(h, params["ln_f"]["scale"], params["ln_f"]["bias"])
        logits = h.astype(jnp.float32) @ params["lm_head"]["kernel"]
        if self.arch == "gptj":
            logits = logits + params["lm_head"]["bias"]
        return logits

    # -- decode step -------------------------------------------------------

    def _decode_impl(
        self,
        params,
        k_pool,      # (L, NB, H, BS, D)
        v_pool,
        tokens,      # (S,) int32 — the token being FED per slot
        positions,   # (S,) int32 — its position (== cache length before it)
        tables,      # (S, T) int32
        temp,        # (S,) f32
        top_k,       # (S,) i32
        top_p,       # (S,) f32
        seeds,       # (S,) u32 — per-request sampling seed
        counters,    # (S,) i32 — index of the token being sampled
    ):
        cfg = self.cfg
        bs = self.block_size
        x = self._embed(params, tokens, positions)  # (S, d)
        phys = jnp.take_along_axis(tables, (positions // bs)[:, None], axis=1)[:, 0]
        off = positions % bs
        lengths = positions + 1
        runner = self

        def one_layer(carry, inputs):
            x = carry
            layer, k_l, v_l = inputs
            if runner.arch == "gptj":
                h = _layernorm(x, layer["ln1"]["scale"], layer["ln1"]["bias"])
                q, k, v = runner._qkv_rows(layer, h, positions)
                k_l = _scatter_kv(k_l, k.astype(k_l.dtype), phys, off)
                v_l = _scatter_kv(v_l, v.astype(v_l.dtype), phys, off)
                att = paged_attention(
                    q, k_l, v_l, tables, lengths, impl=runner.attn_impl
                ).astype(x.dtype)
                att = runner._attn_out(layer, att.reshape(x.shape[0], cfg.d_model))
                out = x + att + runner._mlp(layer, h)  # parallel residual
            else:
                ln1 = _layernorm(x, layer["ln1"]["scale"], layer["ln1"]["bias"])
                q, k, v = runner._qkv_rows(layer, ln1, positions)
                k_l = _scatter_kv(k_l, k.astype(k_l.dtype), phys, off)
                v_l = _scatter_kv(v_l, v.astype(v_l.dtype), phys, off)
                att = paged_attention(
                    q, k_l, v_l, tables, lengths, impl=runner.attn_impl
                ).astype(x.dtype)
                h = x + runner._attn_out(layer, att.reshape(x.shape[0], cfg.d_model))
                ln2 = _layernorm(h, layer["ln2"]["scale"], layer["ln2"]["bias"])
                out = h + runner._mlp(layer, ln2)
            return out, (k_l, v_l)

        x, (k_pool, v_pool) = jax.lax.scan(
            one_layer, x, (params["blocks"], k_pool, v_pool)
        )
        logits = self._lm_head(params, x)  # (S, V)
        nxt, logp = _sample_rows(logits, seeds, counters, temp, top_k, top_p)
        return k_pool, v_pool, nxt, logp

    def decode_step(self, k_pool, v_pool, tokens, positions, tables,
                    temp, top_k, top_p, seeds, counters):
        t0 = time.perf_counter()
        out = self._decode(
            self.params, k_pool, v_pool, tokens, positions, tables,
            temp, top_k, top_p, seeds, counters,
        )
        self._note_compile("decode", len(tokens), t0)
        self.prof.note("decode", self._decode, time.perf_counter() - t0)
        return out

    # -- speculative verification step -------------------------------------

    def _verify_impl(
        self,
        params,
        k_pool,      # (L, NB, H, BS, D)
        v_pool,
        tokens,      # (S, W) int32 — last emitted token + k drafts per slot
        base_pos,    # (S,) int32 — position of tokens[:, 0]
        tables,      # (S, T) int32
        temp,        # (S,) f32
        top_k,       # (S,) i32
        top_p,       # (S,) f32
        seeds,       # (S,) u32
        counters,    # (S,) i32 — output index of the window's first token
    ):
        cfg = self.cfg
        bs = self.block_size
        S, W = tokens.shape
        tmax = tables.shape[1]
        positions = base_pos[:, None] + jnp.arange(W, dtype=jnp.int32)[None, :]
        pos_flat = positions.reshape(-1)                     # (S*W,)
        x = self._embed(params, tokens.reshape(-1), pos_flat)  # (S*W, d)
        # window positions can provisionally run past the table's reach
        # (a slot one emit away from the model-length cap still feeds k
        # drafts): clamp the gather and scatter the overflow to trash —
        # the engine never emits tokens from those positions
        valid = pos_flat < tmax * bs
        logical = jnp.minimum(pos_flat // bs, tmax - 1)
        tables_rep = jnp.repeat(tables, W, axis=0)           # (S*W, T)
        phys = jnp.where(
            valid,
            jnp.take_along_axis(tables_rep, logical[:, None], axis=1)[:, 0],
            0,
        )
        off = pos_flat % bs
        runner = self

        def one_layer(carry, inputs):
            x = carry
            layer, k_l, v_l = inputs
            if runner.arch == "gptj":
                h = _layernorm(x, layer["ln1"]["scale"], layer["ln1"]["bias"])
                q, k, v = runner._qkv_rows(layer, h, pos_flat)
                k_l = _scatter_kv(k_l, k.astype(k_l.dtype), phys, off)
                v_l = _scatter_kv(v_l, v.astype(v_l.dtype), phys, off)
                att = paged_verify_attention(
                    q.reshape(S, W, cfg.n_heads, cfg.head_dim),
                    k_l, v_l, tables, positions, impl=runner.attn_impl,
                ).astype(x.dtype)
                att = runner._attn_out(layer, att.reshape(S * W, cfg.d_model))
                out = x + att + runner._mlp(layer, h)  # parallel residual
            else:
                ln1 = _layernorm(x, layer["ln1"]["scale"], layer["ln1"]["bias"])
                q, k, v = runner._qkv_rows(layer, ln1, pos_flat)
                k_l = _scatter_kv(k_l, k.astype(k_l.dtype), phys, off)
                v_l = _scatter_kv(v_l, v.astype(v_l.dtype), phys, off)
                att = paged_verify_attention(
                    q.reshape(S, W, cfg.n_heads, cfg.head_dim),
                    k_l, v_l, tables, positions, impl=runner.attn_impl,
                ).astype(x.dtype)
                h = x + runner._attn_out(layer, att.reshape(S * W, cfg.d_model))
                ln2 = _layernorm(h, layer["ln2"]["scale"], layer["ln2"]["bias"])
                out = h + runner._mlp(layer, ln2)
            return out, (k_l, v_l)

        x, (k_pool, v_pool) = jax.lax.scan(
            one_layer, x, (params["blocks"], k_pool, v_pool)
        )
        logits = self._lm_head(params, x).reshape(S, W, -1)  # (S, W, V)
        n_acc, out, logp = _verify_rows(
            logits, tokens[:, 1:], seeds, counters, temp, top_k, top_p
        )
        return k_pool, v_pool, n_acc, out, logp

    def verify_step(self, k_pool, v_pool, tokens, base_pos, tables,
                    temp, top_k, top_p, seeds, counters):
        t0 = time.perf_counter()
        out = self._verify(
            self.params, k_pool, v_pool, tokens, base_pos, tables,
            temp, top_k, top_p, seeds, counters,
        )
        self._note_compile("verify", tuple(jnp.shape(tokens)), t0)
        self.prof.note("verify", self._verify, time.perf_counter() - t0)
        return out

    # -- copy-on-write block fork (llm.prefix_cache) -----------------------

    def fork_blocks(self, k_pool, v_pool, src, dst):
        """Duplicate physical blocks ``src[i] → dst[i]`` across all
        layers (``(F,)`` int32 each, pad unused lanes with 0→0).  The
        engine calls this right after a cache-aware admission whose
        prompt diverges INSIDE a cached block: the copy makes the shared
        prefix positions of the fork valid, and prefill resumes at the
        divergence point."""
        t0 = time.perf_counter()
        out = self._fork(k_pool, v_pool, src, dst)
        self._note_compile("fork", len(src), t0)
        self.prof.note("fork", self._fork, time.perf_counter() - t0)
        return out

    # -- prefill chunk -----------------------------------------------------

    def _prefill_impl(
        self,
        params,
        k_pool,
        v_pool,
        tokens,     # (chunk,) int32, tail-padded
        start,      # scalar int32 — position of tokens[0]
        n_valid,    # scalar int32 — valid tokens in this chunk
        table,      # (T,) int32 — THIS sequence's block table
        *,
        chunk: int,
    ):
        cfg = self.cfg
        bs = self.block_size
        positions = start + jnp.arange(chunk, dtype=jnp.int32)
        valid = jnp.arange(chunk) < n_valid
        x = self._embed(params, tokens, positions)  # (chunk, d)
        phys = jnp.where(valid, table[positions // bs], 0)  # padded → trash
        off = positions % bs
        runner = self

        def one_layer(carry, inputs):
            x = carry
            layer, k_l, v_l = inputs
            if runner.arch == "gptj":
                h = _layernorm(x, layer["ln1"]["scale"], layer["ln1"]["bias"])
                q, k, v = runner._qkv_rows(layer, h, positions)
                k_l = _scatter_kv(k_l, k.astype(k_l.dtype), phys, off)
                v_l = _scatter_kv(v_l, v.astype(v_l.dtype), phys, off)
                att = paged_prefill_attention_xla(
                    q, k_l, v_l, table, positions
                ).astype(x.dtype)
                att = runner._attn_out(layer, att.reshape(chunk, cfg.d_model))
                out = x + att + runner._mlp(layer, h)
            else:
                ln1 = _layernorm(x, layer["ln1"]["scale"], layer["ln1"]["bias"])
                q, k, v = runner._qkv_rows(layer, ln1, positions)
                k_l = _scatter_kv(k_l, k.astype(k_l.dtype), phys, off)
                v_l = _scatter_kv(v_l, v.astype(v_l.dtype), phys, off)
                att = paged_prefill_attention_xla(
                    q, k_l, v_l, table, positions
                ).astype(x.dtype)
                h = x + runner._attn_out(layer, att.reshape(chunk, cfg.d_model))
                ln2 = _layernorm(h, layer["ln2"]["scale"], layer["ln2"]["bias"])
                out = h + runner._mlp(layer, ln2)
            return out, (k_l, v_l)

        x, (k_pool, v_pool) = jax.lax.scan(
            one_layer, x, (params["blocks"], k_pool, v_pool)
        )
        last = x[jnp.maximum(n_valid - 1, 0)]  # (d,)
        logits = self._lm_head(params, last[None, :])[0]  # (V,)
        return k_pool, v_pool, logits

    def prefill_chunk(self, k_pool, v_pool, tokens, start, n_valid, table):
        t0 = time.perf_counter()
        out = self._prefill(
            self.params, k_pool, v_pool, tokens,
            jnp.int32(start), jnp.int32(n_valid), table, chunk=len(tokens),
        )
        self._note_compile("prefill", len(tokens), t0)
        self.prof.note("prefill", self._prefill, time.perf_counter() - t0)
        return out
