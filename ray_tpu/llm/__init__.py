"""ray_tpu.llm: continuous-batching LLM inference on the paged KV cache.

The serving-side counterpart of the training stack (SURVEY §7 step 10):
``models.gptj``/``models.gpt`` give the forward math, this package turns
it into an *engine* — per-step admission of queued requests into fixed
decode slots, chunked prefill interleaved with batched decode, paged KV
blocks with preemption under pressure, per-request sampling params,
streaming token delivery — and ``serve.llm`` wraps the engine in a
deployment replica that streams tokens over the existing
streaming-generator machinery.

    from ray_tpu.llm import EngineConfig, LLMEngine, SamplingParams

    engine = LLMEngine(model_cfg, params, EngineConfig(max_slots=8))
    req = engine.submit(prompt_ids, SamplingParams(max_tokens=64,
                                                   temperature=0.8))
    for tok in engine.stream_tokens(req):   # a loop thread drives step()
        ...
"""

from ray_tpu.llm.cache import CacheConfig, KVBlockPool  # noqa: F401
from ray_tpu.llm.drafter import NGramDrafter, SmallModelDrafter  # noqa: F401
from ray_tpu.llm.engine import EngineConfig, LLMEngine  # noqa: F401
from ray_tpu.llm.prefix_cache import PrefixCache, PrefixMatch  # noqa: F401
from ray_tpu.llm.scheduler import Request, SamplingParams, Scheduler  # noqa: F401
from ray_tpu.llm.watchdog import EngineStalledError, EngineWatchdog  # noqa: F401
