"""ray_tpu.llm: continuous-batching LLM inference on the paged KV cache.

The serving-side counterpart of the training stack (SURVEY §7 step 10):
``models.gptj``/``models.gpt`` give the forward math, this package turns
it into an *engine* — per-step admission of queued requests into fixed
decode slots, chunked prefill interleaved with batched decode, paged KV
blocks with preemption under pressure, per-request sampling params,
streaming token delivery — and ``serve.llm`` wraps the engine in a
deployment replica that streams tokens over the existing
streaming-generator machinery.

    from ray_tpu.llm import EngineConfig, LLMEngine, SamplingParams

    engine = LLMEngine(model_cfg, params, EngineConfig(max_slots=8))
    req = engine.submit(prompt_ids, SamplingParams(max_tokens=64,
                                                   temperature=0.8))
    for tok in engine.stream_tokens(req):   # a loop thread drives step()
        ...
"""

#: Canonical lock order of the serving plane, outermost first. Any code
#: path that holds one of these may only acquire locks FURTHER RIGHT —
#: raylint RL010 builds the whole-program acquisition graph (including
#: locks taken inside methods called while another lock is held, across
#: modules) and fails the lint gate on any acquisition that contradicts
#: this declaration or closes a cycle. The watchdog deliberately sits
#: outside the order: it only ever takes the engine lock with a bounded
#: ``acquire(timeout=)`` (which cannot deadlock) and diagnoses wedges
#: through the lock-free liveness beat instead (RESILIENCE.md).
LOCK_ORDER = (
    "RolloutWorker._lock",   # rlhf rollout actor wraps engine submit/poll
    "LLMEngine._lock",       # the step/admission lock
    "PrefixCache._lock",     # radix tree over shared KV blocks
    "KVBlockPool._lock",     # free-list ledger; never calls back up
)

from ray_tpu.llm.cache import CacheConfig, KVBlockPool  # noqa: F401
from ray_tpu.llm.drafter import NGramDrafter, SmallModelDrafter  # noqa: F401
from ray_tpu.llm.engine import EngineConfig, LLMEngine  # noqa: F401
from ray_tpu.llm.multichip import (  # noqa: F401
    ShardedKVBlockPool,
    TensorParallelPagedModelRunner,
)
from ray_tpu.llm.prefix_cache import PrefixCache, PrefixMatch  # noqa: F401
from ray_tpu.llm.scheduler import Request, SamplingParams, Scheduler  # noqa: F401
from ray_tpu.llm.watchdog import EngineStalledError, EngineWatchdog  # noqa: F401
