"""Paged KV-cache block pool — static-shape JAX storage, host-side ledger.

vLLM-style paging on the TPU shape discipline: the device side is two
fixed arrays per model

    k, v : (layers, num_blocks, heads, block_size, head_dim)

allocated ONCE at engine start (no reallocation, no ragged shapes — the
decode step jits once and every admission/eviction pattern reuses it).
The host side is a free-list ledger mapping sequence ids to the physical
blocks they own; block tables (logical→physical per sequence, padded
with the reserved trash block) are plain int32 numpy rows the engine
stacks into the decode step's ``(slots, tmax)`` operand.

Block 0 is RESERVED as the trash block: inactive decode slots and
padded prefill positions scatter their k/v there, so masked lanes never
corrupt live cache and the jitted step needs no data-dependent control
flow.  Eviction under pressure is mechanism here (``free`` returns a
sequence's blocks), policy in ``llm.scheduler`` (preempt-youngest,
recompute on re-admission).
"""

from __future__ import annotations

import dataclasses
import threading
from typing import Optional

import numpy as np


@dataclasses.dataclass(frozen=True)
class CacheConfig:
    """Pool geometry. ``num_blocks`` INCLUDES the reserved trash block, so
    usable capacity is ``num_blocks - 1`` blocks of ``block_size`` tokens.
    ``max_blocks_per_seq`` fixes the block-table width (tmax) — it caps a
    single sequence's length at ``max_blocks_per_seq * block_size``."""

    num_blocks: int = 128
    block_size: int = 16
    max_blocks_per_seq: int = 32

    def __post_init__(self):
        if self.num_blocks < 2:
            raise ValueError("num_blocks must be >= 2 (block 0 is reserved)")
        if self.block_size < 1 or self.max_blocks_per_seq < 1:
            raise ValueError("block_size and max_blocks_per_seq must be >= 1")

    @property
    def max_seq_len(self) -> int:
        return self.max_blocks_per_seq * self.block_size


class KVBlockPool:
    """The pool: device arrays + thread-safe host ledger.

    Device arrays are plain attributes (``k``, ``v``) the engine threads
    through its jitted step functions and writes back — functional
    updates, the pool object just holds the current version.
    """

    def __init__(
        self,
        cfg: CacheConfig,
        n_layers: int,
        n_heads: int,
        head_dim: int,
        dtype="float32",
    ):
        import jax.numpy as jnp

        self.cfg = cfg
        shape = (n_layers, cfg.num_blocks, n_heads, cfg.block_size, head_dim)
        self.k = jnp.zeros(shape, jnp.dtype(dtype))
        self.v = jnp.zeros(shape, jnp.dtype(dtype))
        self._lock = threading.Lock()
        # LIFO free list of physical block ids; 0 reserved (trash)
        self._free = list(range(cfg.num_blocks - 1, 0, -1))
        self._owned: dict[str, list[int]] = {}

    # -- capacity ----------------------------------------------------------

    def blocks_for(self, n_tokens: int) -> int:
        return -(-max(n_tokens, 1) // self.cfg.block_size)

    @property
    def num_free_blocks(self) -> int:
        with self._lock:
            return len(self._free)

    @property
    def num_used_blocks(self) -> int:
        with self._lock:
            return sum(len(b) for b in self._owned.values())

    def utilization(self) -> float:
        """Fraction of usable (non-reserved) blocks currently owned."""
        usable = self.cfg.num_blocks - 1
        return self.num_used_blocks / max(usable, 1)

    def can_allocate(self, n_tokens: int) -> bool:
        need = self.blocks_for(n_tokens)
        if need > self.cfg.max_blocks_per_seq:
            return False
        with self._lock:
            return need <= len(self._free)

    # -- ledger ------------------------------------------------------------

    def allocate(self, seq_id: str, n_tokens: int) -> list[int]:
        """Claim enough blocks for ``n_tokens``; raises if the sequence
        already owns blocks, exceeds the table width, or the pool is dry
        (callers check ``can_allocate`` / preempt first)."""
        need = self.blocks_for(n_tokens)
        with self._lock:
            if seq_id in self._owned:
                raise ValueError(f"sequence {seq_id!r} already owns blocks")
            if need > self.cfg.max_blocks_per_seq:
                raise ValueError(
                    f"{n_tokens} tokens need {need} blocks > "
                    f"max_blocks_per_seq={self.cfg.max_blocks_per_seq}"
                )
            if need > len(self._free):
                raise MemoryError(
                    f"paged KV pool exhausted: need {need} blocks, "
                    f"{len(self._free)} free"
                )
            blocks = [self._free.pop() for _ in range(need)]
            self._owned[seq_id] = blocks
            return list(blocks)

    def grow_to(self, seq_id: str, n_tokens: int) -> bool:
        """Ensure ``seq_id`` owns enough blocks for ``n_tokens``.  Returns
        False (allocation unchanged) when the pool can't cover the growth —
        the scheduler then evicts someone and retries."""
        with self._lock:
            blocks = self._owned.get(seq_id)
            if blocks is None:
                raise KeyError(f"unknown sequence {seq_id!r}")
            need = self.blocks_for(n_tokens)
            if need > self.cfg.max_blocks_per_seq:
                return False
            extra = need - len(blocks)
            if extra <= 0:
                return True
            if extra > len(self._free):
                return False
            blocks.extend(self._free.pop() for _ in range(extra))
            return True

    def shrink_to(self, seq_id: str, n_tokens: int) -> int:
        """Return the sequence's TAIL blocks beyond what ``n_tokens`` needs
        to the free list; returns the number released.  The speculative-
        decode rollback: verification provisionally grows a sequence by
        ``k`` positions, and the rejected tail's blocks come back here.
        (The device-side k/v of rejected positions need no rollback — they
        sit beyond the sequence's length, every attention path masks by
        length, and the next window overwrites them before the length ever
        reaches them.)"""
        with self._lock:
            blocks = self._owned.get(seq_id)
            if blocks is None:
                raise KeyError(f"unknown sequence {seq_id!r}")
            keep = self.blocks_for(n_tokens)
            excess = len(blocks) - keep
            if excess <= 0:
                return 0
            tail = blocks[keep:]
            del blocks[keep:]
            self._free.extend(reversed(tail))
            return excess

    def free(self, seq_id: str) -> int:
        """Return a sequence's blocks to the pool (idempotent); returns the
        number of blocks released."""
        with self._lock:
            blocks = self._owned.pop(seq_id, None)
            if not blocks:
                return 0
            self._free.extend(reversed(blocks))
            return len(blocks)

    def owner_count(self) -> int:
        with self._lock:
            return len(self._owned)

    def audit(self) -> dict:
        """Free-list ledger invariant check (the watchdog's leak audit):
        every usable block is either free or owned exactly once, and every
        id is in range.  Runs under the pool lock alone — safe while the
        engine lock is wedged.  Returns counts plus the owner ids so the
        caller can cross-check owners against live requests."""
        with self._lock:
            free = list(self._free)
            owned = {k: list(v) for k, v in self._owned.items()}
        usable = self.cfg.num_blocks - 1
        owned_blocks = [b for bs in owned.values() for b in bs]
        all_blocks = free + owned_blocks
        duplicates = len(all_blocks) != len(set(all_blocks))
        out_of_range = sum(
            1 for b in all_blocks if not (1 <= b < self.cfg.num_blocks)
        )
        missing = usable - len(all_blocks)
        return {
            "ok": not duplicates and not out_of_range and missing == 0,
            "free": len(free),
            "owned": len(owned_blocks),
            "owners": list(owned),
            "missing": missing,          # >0 leaked, <0 double-counted
            "duplicates": duplicates,
            "out_of_range": out_of_range,
        }

    def table_row(self, seq_id: Optional[str]) -> np.ndarray:
        """(max_blocks_per_seq,) int32 block table, padded with the trash
        block.  ``None`` (an inactive slot) is all-trash."""
        row = np.zeros(self.cfg.max_blocks_per_seq, np.int32)
        if seq_id is not None:
            with self._lock:
                blocks = self._owned.get(seq_id)
                if blocks is None:
                    raise KeyError(f"unknown sequence {seq_id!r}")
                row[: len(blocks)] = blocks
        return row
