"""Paged KV-cache block pool — static-shape JAX storage, host-side ledger.

vLLM-style paging on the TPU shape discipline: the device side is two
fixed arrays per model

    k, v : (layers, num_blocks, heads, block_size, head_dim)

allocated ONCE at engine start (no reallocation, no ragged shapes — the
decode step jits once and every admission/eviction pattern reuses it).
The host side is a free-list ledger mapping sequence ids to the physical
blocks they own; block tables (logical→physical per sequence, padded
with the reserved trash block) are plain int32 numpy rows the engine
stacks into the decode step's ``(slots, tmax)`` operand.

Block 0 is RESERVED as the trash block: inactive decode slots and
padded prefill positions scatter their k/v there, so masked lanes never
corrupt live cache and the jitted step needs no data-dependent control
flow.  Eviction under pressure is mechanism here (``free`` returns a
sequence's blocks), policy in ``llm.scheduler`` (preempt-youngest,
recompute on re-admission).

Sharing (``llm.prefix_cache``): every allocated block carries a
REFERENCE COUNT — one per owning sequence plus one while the prefix
tree retains it (``cache_retain``/``cache_release``).  ``allocate`` can
seed a sequence's table with already-resident ``shared`` blocks (the
matched prefix), and a block returns to the free list only when its
count reaches zero.  A block whose only reference is the cache's is
*evictable* — reclaimable capacity the scheduler drains before it
preempts live requests.  Copy-on-write is split: the LEDGER fork (a
fresh exclusive block for the divergent tail) happens here, the device
copy in ``model_runner.fork_blocks``.  Shared blocks are read-only by
construction — prefill starts past the matched prefix and decode writes
only at the sequence tail, so no jitted step ever scatters into a
position a shared block covers.
"""

from __future__ import annotations

import dataclasses
import threading
from typing import Optional, Sequence

import numpy as np


@dataclasses.dataclass(frozen=True)
class CacheConfig:
    """Pool geometry. ``num_blocks`` INCLUDES the reserved trash block, so
    usable capacity is ``num_blocks - 1`` blocks of ``block_size`` tokens.
    ``max_blocks_per_seq`` fixes the block-table width (tmax) — it caps a
    single sequence's length at ``max_blocks_per_seq * block_size``."""

    num_blocks: int = 128
    block_size: int = 16
    max_blocks_per_seq: int = 32

    def __post_init__(self):
        if self.num_blocks < 2:
            raise ValueError("num_blocks must be >= 2 (block 0 is reserved)")
        if self.block_size < 1 or self.max_blocks_per_seq < 1:
            raise ValueError("block_size and max_blocks_per_seq must be >= 1")

    @property
    def max_seq_len(self) -> int:
        return self.max_blocks_per_seq * self.block_size


class KVBlockPool:
    """The pool: device arrays + thread-safe host ledger.

    Device arrays are plain attributes (``k``, ``v``) the engine threads
    through its jitted step functions and writes back — functional
    updates, the pool object just holds the current version.
    """

    def __init__(
        self,
        cfg: CacheConfig,
        n_layers: int,
        n_heads: int,
        head_dim: int,
        dtype="float32",
        sharding=None,
    ):
        import jax.numpy as jnp

        self.cfg = cfg
        shape = (n_layers, cfg.num_blocks, n_heads, cfg.block_size, head_dim)
        self.k = jnp.zeros(shape, jnp.dtype(dtype))
        self.v = jnp.zeros(shape, jnp.dtype(dtype))
        if sharding is not None:
            # multichip: place the pool arrays head-sharded over the tp
            # mesh at creation so the engine's jitted steps never move
            # them; the host ledger below is unchanged — block ids are
            # global, every device holds the same blocks' local heads
            import jax

            self.k = jax.device_put(self.k, sharding)
            self.v = jax.device_put(self.v, sharding)
        self._lock = threading.Lock()
        # LIFO free list of physical block ids; 0 reserved (trash)
        self._free = list(range(cfg.num_blocks - 1, 0, -1))
        self._owned: dict[str, list[int]] = {}
        # reference counts for every non-free block: one per owning
        # sequence + one while the prefix tree retains it; a block is
        # freed only at zero (llm.prefix_cache shares blocks across
        # sequences, so ownership alone no longer implies exclusivity)
        self._ref: dict[int, int] = {}
        self._cache_held: set[int] = set()

    # -- capacity ----------------------------------------------------------

    def blocks_for(self, n_tokens: int) -> int:
        return -(-max(n_tokens, 1) // self.cfg.block_size)

    @property
    def block_bytes(self) -> int:
        """Device bytes ONE physical block occupies across both pool
        arrays and every layer (k + v) — the unit the HBM ledger gauges
        multiply block counts by."""
        return (self.k.nbytes + self.v.nbytes) // self.cfg.num_blocks

    @property
    def device_bytes(self) -> int:
        """Total device footprint of the pool arrays (k + v), trash
        block included — allocated once at engine start, never resized."""
        return self.k.nbytes + self.v.nbytes

    @property
    def num_free_blocks(self) -> int:
        with self._lock:
            return len(self._free)

    @property
    def num_used_blocks(self) -> int:
        """DISTINCT blocks referenced by at least one sequence (a block
        shared by N sequences counts once; cache-only residents count
        zero — they are reclaimable, not in use)."""
        with self._lock:
            return len({b for bs in self._owned.values() for b in bs})

    @property
    def num_cached_blocks(self) -> int:
        with self._lock:
            return len(self._cache_held)

    @property
    def num_evictable_blocks(self) -> int:
        """Blocks whose ONLY reference is the prefix cache's — capacity
        the scheduler can reclaim without preempting anyone."""
        with self._lock:
            return sum(1 for b in self._cache_held if self._ref.get(b) == 1)

    def ledger_counts(self) -> dict:
        """One consistent snapshot of the block partition for the HBM
        ledger gauges (a single lock acquisition — the per-property reads
        could interleave with an allocation between them): ``free`` +
        ``seq_owned`` (distinct blocks owned by ≥1 sequence, shared or
        not) + ``cache_only`` (resident purely for the prefix tree)
        partition the usable blocks, the same invariant ``audit()``
        checks."""
        with self._lock:
            owned = {b for bs in self._owned.values() for b in bs}
            return {
                "free": len(self._free),
                "seq_owned": len(owned),
                "cache_only": len(self._cache_held - owned),
            }

    def utilization(self) -> float:
        """Fraction of usable (non-reserved) blocks currently owned by
        live sequences.  Cache-only blocks are excluded on purpose: they
        are evictable on demand, and counting them would page the
        kv-pool-exhaustion SLO on a healthy warm cache."""
        usable = self.cfg.num_blocks - 1
        return self.num_used_blocks / max(usable, 1)

    def can_allocate(self, n_tokens: int, shared: int = 0) -> bool:
        """True when a fresh allocation for ``n_tokens`` fits, with the
        first ``shared`` blocks coming from the prefix cache (only the
        remainder needs the free list)."""
        need = self.blocks_for(n_tokens)
        if need > self.cfg.max_blocks_per_seq:
            return False
        with self._lock:
            return need - shared <= len(self._free)

    # -- ledger ------------------------------------------------------------

    def allocate(
        self, seq_id: str, n_tokens: int, shared: Sequence[int] = ()
    ) -> list[int]:
        """Claim enough blocks for ``n_tokens``; raises if the sequence
        already owns blocks, exceeds the table width, or the pool is dry
        (callers check ``can_allocate`` / preempt first).

        ``shared`` — already-resident cache blocks forming the head of
        the table (the matched prefix, in prompt order): each gains a
        reference instead of leaving the free list.  Only the remainder
        is drawn fresh.  All-or-nothing: validation precedes any
        mutation, so a failed allocate changes no counts."""
        need = self.blocks_for(n_tokens)
        shared = list(shared)
        with self._lock:
            if seq_id in self._owned:
                raise ValueError(f"sequence {seq_id!r} already owns blocks")
            if need > self.cfg.max_blocks_per_seq:
                raise ValueError(
                    f"{n_tokens} tokens need {need} blocks > "
                    f"max_blocks_per_seq={self.cfg.max_blocks_per_seq}"
                )
            if len(shared) >= need and shared:
                raise ValueError(
                    f"{len(shared)} shared blocks >= {need} needed: the "
                    "tail block must be exclusive (prefill writes there)"
                )
            for b in shared:
                if b not in self._cache_held or self._ref.get(b, 0) < 1:
                    raise ValueError(
                        f"shared block {b} is not cache-resident"
                    )
            fresh = need - len(shared)
            if fresh > len(self._free):
                raise MemoryError(
                    f"paged KV pool exhausted: need {fresh} blocks, "
                    f"{len(self._free)} free"
                )
            for b in shared:
                self._ref[b] += 1
            new = [self._free.pop() for _ in range(fresh)]
            for b in new:
                self._ref[b] = 1
            blocks = shared + new
            self._owned[seq_id] = blocks
            return list(blocks)

    def grow_to(self, seq_id: str, n_tokens: int) -> bool:
        """Ensure ``seq_id`` owns enough blocks for ``n_tokens``.  Returns
        False (allocation unchanged) when the pool can't cover the growth —
        the scheduler then evicts someone and retries."""
        with self._lock:
            blocks = self._owned.get(seq_id)
            if blocks is None:
                raise KeyError(f"unknown sequence {seq_id!r}")
            need = self.blocks_for(n_tokens)
            if need > self.cfg.max_blocks_per_seq:
                return False
            extra = need - len(blocks)
            if extra <= 0:
                return True
            if extra > len(self._free):
                return False
            for _ in range(extra):
                b = self._free.pop()
                self._ref[b] = 1
                blocks.append(b)
            return True

    def _deref_locked(self, block: int) -> bool:
        """Drop one reference (lock held); returns True when the block
        actually hit zero and went back to the free list."""
        n = self._ref.get(block, 0) - 1
        if n > 0:
            self._ref[block] = n
            return False
        self._ref.pop(block, None)
        self._cache_held.discard(block)
        self._free.append(block)
        return True

    def shrink_to(self, seq_id: str, n_tokens: int) -> int:
        """Return the sequence's TAIL blocks beyond what ``n_tokens`` needs
        to the free list; returns the number released.  The speculative-
        decode rollback: verification provisionally grows a sequence by
        ``k`` positions, and the rejected tail's blocks come back here.
        (The device-side k/v of rejected positions need no rollback — they
        sit beyond the sequence's length, every attention path masks by
        length, and the next window overwrites them before the length ever
        reaches them.)"""
        with self._lock:
            blocks = self._owned.get(seq_id)
            if blocks is None:
                raise KeyError(f"unknown sequence {seq_id!r}")
            keep = self.blocks_for(n_tokens)
            excess = len(blocks) - keep
            if excess <= 0:
                return 0
            tail = blocks[keep:]
            del blocks[keep:]
            for b in reversed(tail):
                self._deref_locked(b)
            return excess

    def free(self, seq_id: str) -> int:
        """Drop the sequence's references (idempotent); returns how many
        blocks actually reached zero and returned to the free list
        (shared/cached blocks survive on their remaining references)."""
        with self._lock:
            blocks = self._owned.pop(seq_id, None)
            if not blocks:
                return 0
            return sum(1 for b in reversed(blocks) if self._deref_locked(b))

    def owner_count(self) -> int:
        with self._lock:
            return len(self._owned)

    def blocks_of(self, seq_id: str) -> list[int]:
        """Copy of the sequence's block list (table order)."""
        with self._lock:
            blocks = self._owned.get(seq_id)
            if blocks is None:
                raise KeyError(f"unknown sequence {seq_id!r}")
            return list(blocks)

    # -- prefix-cache residency (llm.prefix_cache) -------------------------

    def cache_retain(self, block: int) -> bool:
        """Take the prefix tree's reference on an allocated block (False
        if the block is free/unknown — a freed block cannot resurrect, or
        already retained — one tree node per block)."""
        with self._lock:
            if block not in self._ref or block in self._cache_held:
                return False
            self._cache_held.add(block)
            self._ref[block] += 1
            return True

    def cache_release(self, block: int) -> bool:
        """Drop the prefix tree's reference (eviction/flush); frees the
        block when no sequence still holds it."""
        with self._lock:
            if block not in self._cache_held:
                return False
            self._cache_held.discard(block)
            return self._deref_locked(block)

    def ref(self, block: int) -> int:
        with self._lock:
            return self._ref.get(block, 0)

    def is_cache_held(self, block: int) -> bool:
        with self._lock:
            return block in self._cache_held

    def is_evictable(self, block: int) -> bool:
        """Only the cache references it: reclaimable without preemption."""
        with self._lock:
            return block in self._cache_held and self._ref.get(block) == 1

    def cache_held_blocks(self) -> set:
        with self._lock:
            return set(self._cache_held)

    def audit(self) -> dict:
        """Free-list ledger invariant check (the watchdog's leak audit):
        free + exclusively-owned + shared-with-refcount + cache-only must
        still PARTITION the usable blocks, every id must be in range, and
        every refcount must equal its observable references (#owning
        sequences + 1 if cache-held).  Runs under the pool lock alone —
        safe while the engine lock is wedged.  Returns counts plus the
        owner ids so the caller can cross-check owners against live
        requests (and the prefix tree via ``PrefixCache.audit``)."""
        with self._lock:
            free = list(self._free)
            owned = {k: list(v) for k, v in self._owned.items()}
            cache_held = set(self._cache_held)
            ref = dict(self._ref)
        usable = self.cfg.num_blocks - 1
        owner_count: dict[int, int] = {}
        for bs in owned.values():
            for b in bs:
                owner_count[b] = owner_count.get(b, 0) + 1
        live = set(owner_count) | cache_held
        # a shared block appears ONCE in the live set — the partition is
        # over distinct blocks, the sharing is what the refcounts carry
        all_blocks = free + sorted(live)
        duplicates = len(all_blocks) != len(set(all_blocks))
        out_of_range = sum(
            1 for b in all_blocks if not (1 <= b < self.cfg.num_blocks)
        )
        missing = usable - len(all_blocks)
        ref_errors = sum(
            1
            for b in live
            if ref.get(b, 0)
            != owner_count.get(b, 0) + (1 if b in cache_held else 0)
        ) + sum(1 for b in ref if b not in live)
        return {
            "ok": not duplicates and not out_of_range and missing == 0
            and ref_errors == 0,
            "free": len(free),
            "owned": len(owner_count),
            "owners": list(owned),
            "shared": sum(1 for n in owner_count.values() if n > 1),
            "cached": len(cache_held),
            "cached_only": sum(
                1 for b in cache_held if b not in owner_count
            ),
            "ref_errors": ref_errors,
            "missing": missing,          # >0 leaked, <0 double-counted
            "duplicates": duplicates,
            "out_of_range": out_of_range,
        }

    def table_row(self, seq_id: Optional[str]) -> np.ndarray:
        """(max_blocks_per_seq,) int32 block table, padded with the trash
        block.  ``None`` (an inactive slot) is all-trash."""
        row = np.zeros(self.cfg.max_blocks_per_seq, np.int32)
        if seq_id is not None:
            with self._lock:
                blocks = self._owned.get(seq_id)
                if blocks is None:
                    raise KeyError(f"unknown sequence {seq_id!r}")
                row[: len(blocks)] = blocks
        return row
