"""Continuous-batching scheduler: request lifecycle + slot/block policy.

Reference shape: vLLM's scheduler (waiting / running queues over a paged
block pool) recast onto this repo's static-shape discipline — the engine
has a FIXED number of decode slots (the jitted step's batch dimension);
the scheduler's job is to keep those slots full:

* **admission** — FIFO: a waiting request takes a free slot when the pool
  can cover its prompt plus one generated block (headroom so a fresh
  admission can't instantly deadlock on its first decode step).  With a
  prefix cache (``llm.prefix_cache``) admission is CACHE-AWARE: the
  longest cached prefix is matched at admit, its blocks are shared into
  the new table, only the uncached suffix is charged to chunked prefill
  (``prefill_pos`` starts at the match), and an intra-block divergence
  queues a copy-on-write fork (``pending_cow``) the engine applies
  before the first prefill chunk.
* **cache eviction before preemption** — when the pool is dry, capacity
  held only by the prefix tree (finished requests' cached prefixes) is
  reclaimed LRU-first; live requests are preempted only when the cache
  has nothing left to give.
* **chunked prefill** — an admitted request prefills
  ``prefill_chunk``-sized pieces, one chunk per engine step, interleaved
  with decode for the already-running slots — long prompts never stall
  in-flight generations (TTFT of running streams is protected).
* **preemption** — when a running sequence needs a block and the pool is
  dry, the YOUNGEST running request (latest admission) is evicted:
  blocks freed, generated-so-far tokens folded into its prompt, request
  requeued at the FRONT of the waiting queue (recompute-style preemption
  — re-prefill is cheap next to stalling the whole batch, and
  oldest-first survival preserves FIFO fairness).

All state transitions happen under the engine's lock; this module holds
no thread of its own.
"""

from __future__ import annotations

import dataclasses
import itertools
import queue
import threading
import time
from collections import deque
from typing import Optional

from ray_tpu._private import events as _events
from ray_tpu.llm.cache import KVBlockPool
from ray_tpu.util import phases as _phases
from ray_tpu.util import tracing as _tracing

_req_counter = itertools.count()

# request states
WAITING = "waiting"
PREFILL = "prefill"     # owns a slot + blocks; prompt partially processed
RUNNING = "running"     # decode steps produce tokens
FINISHED = "finished"

# finish reasons
FINISH_LENGTH = "length"
FINISH_STOP = "stop"
FINISH_CANCELLED = "cancelled"
FINISH_DEADLINE = "deadline"


@dataclasses.dataclass(frozen=True)
class SamplingParams:
    """Per-request sampling knobs (see ``models.sampling``).
    ``temperature <= 0`` is greedy; ``stop_token_ids`` ends generation
    AFTER emitting a listed token (the stop token is included in the
    output, HF-eos style)."""

    max_tokens: int = 16
    temperature: float = 0.0
    top_k: int = 0
    top_p: float = 1.0
    stop_token_ids: tuple = ()
    seed: int = 0


class Request:
    """One generation request; carries its own stream queue so a serve
    replica thread can iterate tokens while the engine thread steps.

    ``resume_tokens`` is the mid-stream-failover handshake (RESILIENCE.md):
    tokens a PREVIOUS replica already generated and delivered for this
    request before dying. They pre-fold into ``out`` exactly like a
    preemption's recompute — the re-prefill replays prompt + out to rebuild
    the cache, generation continues at output index ``len(out)``, and the
    per-token PRNG keys (``models.sampling``: fold_in(seed, output index))
    make the continuation token-identical to the unkilled run. Only NEW
    tokens are streamed; the resumed prefix counts toward ``max_tokens``.
    """

    def __init__(
        self,
        prompt: list[int],
        params: SamplingParams,
        deadline: Optional[float] = None,  # absolute time.time() cutoff
        resume_tokens: tuple = (),
    ):
        if not prompt:
            raise ValueError("prompt must contain at least one token")
        self.id = f"req-{next(_req_counter)}"
        # end-to-end correlation id: the submitting thread's trace context
        # (proxy-minted for served traffic, set via tracing.trace_context
        # for direct engine use); falls back to the engine-local id so
        # every request is traceable through `obs req <id>` either way
        self.trace_id = _tracing.current_request_id() or self.id
        self.prompt = list(prompt)
        self.params = params
        self.deadline = deadline
        self.arrival_t = time.time()
        self.state = WAITING
        self.finish_reason: Optional[str] = None
        self.out: list[int] = [int(t) for t in resume_tokens]
        # behavior logprob of out[i] under the distribution it was sampled
        # from (models.sampling logprob convention), aligned 1:1 with
        # ``out``. Resumed tokens were sampled by a DEAD replica — their
        # logprobs are unknown here and recorded as NaN; every token this
        # engine generates gets the exact captured value (the rlhf rollout
        # path reads this list).
        self.out_logprobs: list[float] = [float("nan")] * len(self.out)
        # engine weights_version at submit (rlhf weight-sync staleness
        # accounting; None until the engine stamps it)
        self.weights_version: Optional[int] = None
        self.resumed_from = len(self.out)  # output index generation restarts at
        self.prefill_pos = 0          # prompt tokens already in the cache
        # prefix-cache flush epoch at admission: a weight swap mid-prefill
        # bumps the cache's epoch, and this request's (partly old-weight)
        # blocks then must not enter the tree (prefix_cache.insert)
        self.cache_epoch = 0
        self.first_token_t: Optional[float] = None
        self.last_token_t: Optional[float] = None
        # phase-attribution ledger (util.phases): cursor + per-phase
        # accumulators, anchored at submit. A resumed request gets a FRESH
        # ledger — only THIS attempt's time is attributed (the dead
        # replica's never folded). None when RAY_TPU_PHASES=0.
        self.phase_led: Optional[list] = (
            _phases.new_ledger(self.arrival_t) if _phases.enabled() else None
        )
        # True from preemption until the recompute's prefill completes:
        # queue/admit/prefill charges reroute to the `preempt` phase so
        # recompute cost is attributed, not lumped into first-time phases
        self.phase_recompute = False
        # cross-process dispatch leg (engine submit − proxy dispatch
        # anchor), stamped by phases.note_dispatch when the trace context
        # carries the anchor
        self.phase_dispatch_s: Optional[float] = None
        self.cancelled = threading.Event()
        # stream events: ("token", id) ... ("done", reason)
        self.stream: queue.SimpleQueue = queue.SimpleQueue()

    @property
    def seq_len(self) -> int:
        """Tokens currently in (or destined for) the cache."""
        return len(self.prompt) + len(self.out)

    @property
    def finished(self) -> bool:
        return self.state == FINISHED


class Scheduler:
    """Slot + block bookkeeping. NOT thread-safe on its own — the engine
    serializes access under its step lock."""

    def __init__(self, pool: KVBlockPool, max_slots: int, prefix_cache=None):
        self.pool = pool
        self.max_slots = max_slots
        self.prefix_cache = prefix_cache  # llm.prefix_cache.PrefixCache | None
        self.waiting: deque[Request] = deque()
        self.slots: list[Optional[Request]] = [None] * max_slots
        self._admit_seq = itertools.count()
        self._admitted_at: dict[str, int] = {}  # request id -> admission tick
        self.preempt_count = 0
        self.finish_count = 0  # lifetime finishes (engine rates this per step)
        # copy-on-write forks queued by cache-aware admission:
        # (src_block, dst_block, request_id) — the engine drains these
        # right after admit() (same lock, same step), device-copying
        # src→dst before any prefill chunk reads the forked block
        self.pending_cow: list[tuple[int, int, str]] = []

    # -- queries -----------------------------------------------------------

    @property
    def num_waiting(self) -> int:
        return len(self.waiting)

    @property
    def running(self) -> list[Request]:
        return [r for r in self.slots if r is not None]

    @property
    def num_running(self) -> int:
        return sum(1 for r in self.slots if r is not None)

    def has_work(self) -> bool:
        return bool(self.waiting) or self.num_running > 0

    # -- lifecycle ---------------------------------------------------------

    def add(self, req: Request) -> None:
        self.waiting.append(req)

    def admit(self) -> list[Request]:
        """Move waiting → slots while a slot is free and the pool can cover
        prompt + one generation block. Returns the newly admitted.

        With a prefix cache, the longest cached prefix of the replay
        sequence (prompt + already-generated tokens — recompute and
        failover-resume prefixes match too, content is content) is shared
        into the table and ``prefill_pos`` starts past it; a pool
        shortfall first reclaims cache-only blocks (LRU), protecting the
        blocks this very admission is about to share."""
        admitted = []
        while self.waiting:
            free = [i for i, r in enumerate(self.slots) if r is None]
            if not free:
                break
            req = self.waiting[0]
            if req.phase_led is not None:
                # close the queue leg HERE so the admission work that
                # follows (prefix match, evict-to-fit, allocate, install)
                # lands in `admit`; a failed attempt (break below) merges
                # back into queue at the next inspection
                _phases.charge(
                    req.phase_led,
                    _phases.PREEMPT if req.phase_recompute else _phases.QUEUE,
                    time.time(),
                )
            # prompt (+ recomputed tokens after preempt) + one generation
            # block of headroom, capped at the table width for sequences
            # already near the model-length limit
            need_tokens = min(
                req.seq_len + self.pool.cfg.block_size, self.pool.cfg.max_seq_len
            )
            match = None
            shared: list[int] = []
            if self.prefix_cache is not None:
                match = self.prefix_cache.match(req.prompt + req.out)
                shared = list(match.blocks)
            if not self.pool.can_allocate(need_tokens, shared=len(shared)):
                # reclaim cache-only residents before declaring pressure;
                # the matched blocks (and CoW source) must survive the
                # sweep — they may themselves be cache-only right now
                deficit = (
                    self.pool.blocks_for(need_tokens)
                    - len(shared)
                    - self.pool.num_free_blocks
                )
                if self.prefix_cache is not None and deficit > 0:
                    protect = set(shared)
                    if match is not None and match.cow_src is not None:
                        protect.add(match.cow_src)
                    self.prefix_cache.evict(deficit, protect=frozenset(protect))
                if not self.pool.can_allocate(need_tokens, shared=len(shared)):
                    break  # FIFO head blocked on memory: don't starve it
            self.waiting.popleft()
            slot = free[0]
            blocks = self.pool.allocate(req.id, need_tokens, shared=shared)
            try:
                self.slots[slot] = req
                req.state = PREFILL
                req.prefill_pos = match.matched if match is not None else 0
                if self.prefix_cache is not None:
                    req.cache_epoch = self.prefix_cache.epoch
                if match is not None and match.cow_src is not None:
                    # the forked block sits right after the shared prefix;
                    # its first cow_tokens positions become valid at copy
                    # time
                    self.pending_cow.append(
                        (match.cow_src, blocks[len(shared)], req.id)
                    )
                if self.prefix_cache is not None:
                    self.prefix_cache.record(
                        req, match, len(req.prompt) + len(req.out)
                    )
                self._admitted_at[req.id] = next(self._admit_seq)
            except BaseException:
                # exception-path block release (RL015's bug class): an
                # admission that fails AFTER taking blocks but before the
                # request is fully installed would otherwise leave the
                # ledger entry owned by a request in no slot and no queue
                # — a leak only the watchdog audit would ever notice.
                # Roll the whole admission back and let the error surface.
                self.slots[slot] = None
                self.pool.free(req.id)
                self._admitted_at.pop(req.id, None)
                self._drop_pending_cow(req.id)
                req.state = WAITING
                req.prefill_pos = 0
                self.waiting.appendleft(req)
                raise
            if req.phase_led is not None:
                _phases.charge(
                    req.phase_led,
                    _phases.PREEMPT if req.phase_recompute else _phases.ADMIT,
                    time.time(),
                )
            admitted.append(req)
            _events.record(
                "llm.admit", request_id=req.trace_id, engine_req=req.id,
                slot=slot, seq_len=req.seq_len,
                cached_tokens=req.prefill_pos,
                wait_s=round(time.time() - req.arrival_t, 6),
            )
        return admitted

    def grow_for_decode(self, req: Request, extra: int = 0) -> bool:
        """Ensure the positions the next step writes (``seq_len - 1`` plus
        ``extra`` provisional speculative positions) have cache slots,
        preempting younger requests if the pool is dry.  The target clamps
        at the table width — window positions past it scatter to the trash
        block, so they need no allocation.  Returns False when ``req``
        itself had to be preempted (nobody younger to evict)."""
        target = min(req.seq_len + extra, self.pool.cfg.max_seq_len)
        while not self.pool.grow_to(req.id, target):
            # cheapest capacity first: cached blocks nobody is running on
            if self.prefix_cache is not None and self.prefix_cache.evict(1) > 0:
                continue
            victim = self._youngest_running(exclude=req.id)
            if victim is None:
                self.preempt(req)
                return False
            self.preempt(victim)
        return True

    def _youngest_running(self, exclude: str) -> Optional[Request]:
        cands = [
            r for r in self.slots
            if r is not None and r.id != exclude
        ]
        if not cands:
            return None
        return max(cands, key=lambda r: self._admitted_at.get(r.id, -1))

    def preempt(self, req: Request) -> None:
        """Evict a running/prefilling request: free its blocks and requeue
        it at the FRONT of the waiting queue. Recompute on re-admission:
        ``req.out`` is untouched (already-streamed tokens stay delivered
        and keep counting toward ``max_tokens``) — the re-prefill replays
        prompt + out to rebuild the cache, then generation continues."""
        slot = self._slot_of(req)
        if slot is not None:
            self.slots[slot] = None
        self.pool.free(req.id)
        self._admitted_at.pop(req.id, None)
        self._drop_pending_cow(req.id)
        self.preempt_count += 1
        if req.phase_led is not None:
            # the evicted step's partial work is lost to the recompute —
            # charge it to `preempt` and reroute everything until the
            # re-prefill completes (engine clears the flag at RUNNING)
            _phases.charge(req.phase_led, _phases.PREEMPT, time.time())
            req.phase_recompute = True
        req.prefill_pos = 0
        req.state = WAITING
        self.waiting.appendleft(req)
        _events.record(
            "llm.preempt", request_id=req.trace_id, engine_req=req.id,
            tokens_out=len(req.out), recompute_len=req.seq_len,
        )

    def finish(self, req: Request, reason: str) -> None:
        if req.phase_led is not None:
            # tail charge: attribute the interval since the last stamp by
            # what the request was doing, then fold — Σ phases now equals
            # finish − submit exactly
            now = time.time()
            if req.phase_recompute:
                idx = _phases.PREEMPT
            elif req.state == RUNNING:
                idx = _phases.DECODE
            elif req.state == PREFILL:
                idx = _phases.PREFILL
            else:
                idx = _phases.QUEUE
            _phases.charge(req.phase_led, idx, now)
            _phases.fold_engine(req, now, reason)
        slot = self._slot_of(req)
        if slot is not None:
            self.slots[slot] = None
        try:
            self.waiting.remove(req)
        except ValueError:
            pass
        self.pool.free(req.id)
        self._admitted_at.pop(req.id, None)
        self._drop_pending_cow(req.id)
        req.state = FINISHED
        req.finish_reason = reason
        self.finish_count += 1
        _events.record(
            "llm.finish", request_id=req.trace_id, engine_req=req.id,
            reason=reason, tokens_out=len(req.out),
            dur_s=round(time.time() - req.arrival_t, 6),
        )
        req.stream.put(("done", reason))

    def _drop_pending_cow(self, req_id: str) -> None:
        """A request leaving its slot (preempt/finish) before the engine
        drained its fork: the dst block just went back to the pool, the
        copy must not happen (defensive — the engine drains forks in the
        same step as admission, but reap runs first next step)."""
        if self.pending_cow:
            self.pending_cow = [c for c in self.pending_cow if c[2] != req_id]

    def _slot_of(self, req: Request) -> Optional[int]:
        for i, r in enumerate(self.slots):
            if r is not None and r.id == req.id:
                return i
        return None
