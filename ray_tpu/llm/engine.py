"""LLMEngine: the continuous-batching step loop.

One engine owns one model (GPT or GPT-J params), one paged KV pool, and
one scheduler.  ``step()`` is the whole design:

1. reap cancellations and blown deadlines;
2. admit waiting requests into free decode slots (FIFO, memory-gated);
3. run ONE chunked-prefill piece for the oldest still-prefilling
   admission — interleaved with, never instead of, decode;
4. run ONE batched decode step across every running slot (single jitted
   call, static slot count), sample per-slot tokens (per-request
   temperature/top-k/top-p/seed), stream them out, finish requests that
   hit ``max_tokens``/stop tokens, preempting the youngest when the
   block pool runs dry.

Observability: every step is a ``util.tracing`` span; tokens/s, TTFT,
inter-token latency, running/waiting counts, KV-block utilization and
preemptions publish through ``util.metrics`` (the same surface the serve
autoscaler and Grafana boards read).

Threading: ``step()`` serializes on an internal lock — any number of
submitter threads (serve replica handlers) can feed the engine while one
driver thread (or several, harmlessly) turns the crank.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Iterator, Optional

import numpy as np

from ray_tpu.llm.cache import CacheConfig, KVBlockPool
from ray_tpu.llm.model_runner import PagedModelRunner, _sample_rows
from ray_tpu.llm.scheduler import (
    FINISH_CANCELLED,
    FINISH_DEADLINE,
    FINISH_LENGTH,
    FINISH_STOP,
    PREFILL,
    RUNNING,
    Request,
    SamplingParams,
    Scheduler,
)

_METRICS = None
_METRICS_LOCK = threading.Lock()


def _metrics() -> dict:
    """Engine metric set, created once per process (util.metrics registers
    globally; duplicates would fight in collect())."""
    global _METRICS
    if _METRICS is not None:
        return _METRICS  # lock-free fast path: called per token in _emit
    with _METRICS_LOCK:
        if _METRICS is not None:
            return _METRICS
        from ray_tpu.util.metrics import Counter, Gauge, Histogram

        _METRICS = {
            "tokens": Counter("llm_generated_tokens", "tokens sampled by the engine"),
            "steps": Counter("llm_engine_steps", "engine step-loop iterations"),
            "preempt": Counter("llm_preemptions", "requests evicted under KV pressure"),
            "running": Gauge("llm_running_requests", "requests holding decode slots"),
            "waiting": Gauge("llm_waiting_requests", "requests queued for admission"),
            "kv_util": Gauge("llm_kv_block_utilization", "fraction of KV blocks in use"),
            "ttft": Histogram("llm_time_to_first_token_s", "submit → first token"),
            "itl": Histogram(
                "llm_inter_token_latency_s",
                "gap between consecutive streamed tokens",
                boundaries=(0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 1.0),
            ),
        }
    return _METRICS


@dataclasses.dataclass(frozen=True)
class EngineConfig:
    """Engine geometry. ``num_blocks`` includes the reserved trash block;
    ``max_blocks_per_seq * block_size`` caps a sequence (prompt + output),
    additionally clamped by the model's positional table for GPT."""

    max_slots: int = 4
    num_blocks: int = 128
    block_size: int = 16
    max_blocks_per_seq: int = 32
    prefill_chunk: int = 32
    attn_impl: str = "auto"


class LLMEngine:
    def __init__(self, model_cfg, params: dict, engine_cfg: Optional[EngineConfig] = None):
        self.cfg = engine_cfg or EngineConfig()
        self.model_cfg = model_cfg
        cache_cfg = CacheConfig(
            num_blocks=self.cfg.num_blocks,
            block_size=self.cfg.block_size,
            max_blocks_per_seq=self.cfg.max_blocks_per_seq,
        )
        self.runner = PagedModelRunner(
            model_cfg, params, self.cfg.block_size, attn_impl=self.cfg.attn_impl
        )
        self.pool = KVBlockPool(
            cache_cfg,
            n_layers=model_cfg.n_layers,
            n_heads=model_cfg.n_heads,
            head_dim=model_cfg.head_dim,
            dtype=model_cfg.dtype,
        )
        self.scheduler = Scheduler(self.pool, self.cfg.max_slots)
        self._lock = threading.Lock()
        self._requests: dict[str, Request] = {}
        self._step_n = 0
        self._tokens_generated = 0
        self._preemptions = 0
        # model-length cap: paged table width, and the learned positional
        # table for GPT (rotary GPT-J has no absolute cap of its own)
        self.max_model_len = cache_cfg.max_seq_len
        if self.runner.arch == "gpt":
            self.max_model_len = min(self.max_model_len, model_cfg.seq_len)
        import jax

        self._sample1 = jax.jit(_sample_rows)

    # -- public API --------------------------------------------------------

    def submit(
        self,
        prompt: list[int],
        params: Optional[SamplingParams] = None,
        deadline_s: Optional[float] = None,
    ) -> Request:
        """Queue a request; returns immediately (drive with ``step()`` or a
        loop thread; consume with ``stream_tokens``)."""
        params = params or SamplingParams()
        if params.max_tokens < 1:
            raise ValueError("max_tokens must be >= 1")
        total = len(prompt) + params.max_tokens
        if total > self.max_model_len:
            raise ValueError(
                f"prompt ({len(prompt)}) + max_tokens ({params.max_tokens}) "
                f"exceeds max model length {self.max_model_len}"
            )
        # the request must be able to COMPLETE with the pool to itself —
        # admission's worst case is a re-admission one token before the end
        # plus one block of headroom. Without this check an oversized
        # request passes validation, can never be admitted, and livelocks
        # the FIFO head (starving everything queued behind it).
        worst = min(total - 1 + self.pool.cfg.block_size, self.pool.cfg.max_seq_len)
        usable = self.pool.cfg.num_blocks - 1
        if self.pool.blocks_for(worst) > usable:
            raise ValueError(
                f"request needs up to {self.pool.blocks_for(worst)} KV blocks "
                f"but the pool has only {usable} usable blocks "
                f"(num_blocks={self.pool.cfg.num_blocks}, block 0 reserved)"
            )
        deadline = time.time() + deadline_s if deadline_s is not None else None
        req = Request(prompt, params, deadline=deadline)
        with self._lock:
            self._requests[req.id] = req
            self.scheduler.add(req)
        return req

    def cancel(self, req_id: str) -> bool:
        """Flag a request for cancellation; the next step reaps it (frees
        its slot and blocks, ends its stream)."""
        req = self._requests.get(req_id)
        if req is None:
            return False
        req.cancelled.set()
        return True

    def has_work(self) -> bool:
        with self._lock:
            return self.scheduler.has_work()

    def stream_tokens(self, req: Request, timeout: float = 60.0) -> Iterator[int]:
        """Yield the request's tokens as the engine produces them."""
        import queue as _q

        while True:
            try:
                kind, val = req.stream.get(timeout=timeout)
            except _q.Empty:
                raise TimeoutError(
                    f"no token from {req.id} within {timeout}s "
                    f"(state={req.state})"
                ) from None
            if kind == "token":
                yield val
            else:
                return

    def generate(
        self,
        prompt: list[int],
        params: Optional[SamplingParams] = None,
        deadline_s: Optional[float] = None,
    ) -> list[int]:
        """Blocking convenience: submit and drive until finished. Safe to
        call while a loop thread is also stepping (steps serialize)."""
        req = self.submit(prompt, params, deadline_s)
        while not req.finished:
            if not self.step():
                time.sleep(0.001)
        return list(req.out)

    def stats(self) -> dict:
        with self._lock:
            return {
                "running": self.scheduler.num_running,
                "waiting": self.scheduler.num_waiting,
                "queue_depth": self.scheduler.num_waiting,
                "kv_utilization": self.pool.utilization(),
                "free_blocks": self.pool.num_free_blocks,
                "steps": self._step_n,
                "tokens_generated": self._tokens_generated,
                "preemptions": self._preemptions,
            }

    def run_loop(self, stop: threading.Event, idle_sleep_s: float = 0.002) -> None:
        """Drive ``step()`` until ``stop`` is set (serve replicas run this
        in a daemon thread)."""
        while not stop.is_set():
            if not self.step():
                stop.wait(idle_sleep_s)

    # -- the step ----------------------------------------------------------

    def step(self) -> bool:
        """One engine iteration; returns True when any work was done."""
        from ray_tpu.util import tracing

        with self._lock:
            sched = self.scheduler
            if not sched.has_work():
                self._publish_gauges()
                return False
            self._step_n += 1
            m = _metrics()
            m["steps"].inc()
            with tracing.span(
                "llm_engine_step",
                step=self._step_n,
                running=sched.num_running,
                waiting=sched.num_waiting,
            ):
                self._reap()
                sched.admit()
                did = self._prefill_one()
                did = self._decode_all() or did
            # prune finished requests: the registry otherwise retains every
            # Request (prompt, output, stream queue) for the replica's
            # lifetime. Callers keep their own Request references; cancel()
            # of a pruned id is a no-op, which is correct for finished work.
            self._requests = {
                k: r for k, r in self._requests.items() if not r.finished
            }
            self._publish_gauges()
            return did or sched.has_work()

    # -- internals (all called under the lock) -----------------------------

    def _reap(self) -> None:
        now = time.time()
        for req in list(self.scheduler.waiting) + self.scheduler.running:
            if req.cancelled.is_set():
                self.scheduler.finish(req, FINISH_CANCELLED)
            elif req.deadline is not None and now >= req.deadline:
                self.scheduler.finish(req, FINISH_DEADLINE)

    def _prefill_one(self) -> bool:
        """One chunk for the oldest admission still prefilling."""
        pre = [r for r in self.scheduler.slots if r is not None and r.state == PREFILL]
        if not pre:
            return False
        req = min(pre, key=lambda r: self.scheduler._admitted_at.get(r.id, 0))
        chunk = self.cfg.prefill_chunk
        # a preempted request replays prompt + already-generated tokens to
        # rebuild its cache; a fresh one just prefills its prompt
        full = req.prompt + req.out
        piece = full[req.prefill_pos : req.prefill_pos + chunk]
        n_valid = len(piece)
        tokens = np.zeros(chunk, np.int32)
        tokens[:n_valid] = piece
        table = self.pool.table_row(req.id)
        k, v, last_logits = self.runner.prefill_chunk(
            self.pool.k, self.pool.v, tokens, req.prefill_pos, n_valid, table
        )
        self.pool.k, self.pool.v = k, v
        req.prefill_pos += n_valid
        if req.prefill_pos >= len(full):
            # final chunk: its last position's logits seed generation
            p = req.params
            tok = int(
                self._sample1(
                    last_logits[None, :],
                    np.asarray([p.seed & 0xFFFFFFFF], np.uint32),
                    np.asarray([len(req.out)], np.int32),
                    np.asarray([p.temperature], np.float32),
                    np.asarray([p.top_k], np.int32),
                    np.asarray([p.top_p], np.float32),
                )[0]
            )
            req.state = RUNNING
            self._emit(req, tok)
        return True

    def _decode_all(self) -> bool:
        """One batched decode step over every RUNNING slot."""
        sched = self.scheduler
        # memory first: every runner needs space for the token it is about
        # to write; the youngest gets evicted when the pool is dry
        for req in list(sched.running):
            if req.state != RUNNING:
                continue
            before = sched.preempt_count
            if not sched.grow_for_decode(req):
                pass  # req itself was preempted; it re-prefills later
            self._preemptions += sched.preempt_count - before
            _metrics()["preempt"].inc(sched.preempt_count - before)
        active = [
            (i, r)
            for i, r in enumerate(sched.slots)
            if r is not None and r.state == RUNNING
        ]
        if not active:
            return False
        S = self.cfg.max_slots
        tokens = np.zeros(S, np.int32)
        positions = np.zeros(S, np.int32)
        tables = np.zeros((S, self.pool.cfg.max_blocks_per_seq), np.int32)
        temp = np.zeros(S, np.float32)
        top_k = np.zeros(S, np.int32)
        top_p = np.ones(S, np.float32)
        seeds = np.zeros(S, np.uint32)
        counters = np.zeros(S, np.int32)
        for i, req in active:
            tokens[i] = req.out[-1] if req.out else req.prompt[-1]
            positions[i] = req.seq_len - 1  # the fed token's position
            tables[i] = self.pool.table_row(req.id)
            p = req.params
            temp[i] = p.temperature
            top_k[i] = p.top_k
            top_p[i] = p.top_p
            # mask, don't assign raw: a negative seed overflows a uint32
            # cell on NumPy >= 2 and the OverflowError would kill the
            # engine loop thread
            seeds[i] = p.seed & 0xFFFFFFFF
            counters[i] = len(req.out)
        k, v, nxt = self.runner.decode_step(
            self.pool.k, self.pool.v, tokens, positions, tables,
            temp, top_k, top_p, seeds, counters,
        )
        self.pool.k, self.pool.v = k, v
        nxt = np.asarray(nxt)  # ONE host sync for the whole batch
        for i, req in active:
            self._emit(req, int(nxt[i]))
        return True

    def _emit(self, req: Request, tok: int) -> None:
        """Record one sampled token: stream it, update latency metrics,
        finish on stop token / max_tokens / model-length cap."""
        now = time.time()
        m = _metrics()
        if req.first_token_t is None:
            req.first_token_t = now
            m["ttft"].observe(now - req.arrival_t)
        elif req.last_token_t is not None:
            m["itl"].observe(now - req.last_token_t)
        req.last_token_t = now
        req.out.append(tok)
        req.stream.put(("token", tok))
        self._tokens_generated += 1
        m["tokens"].inc()
        p = req.params
        if tok in p.stop_token_ids:
            self.scheduler.finish(req, FINISH_STOP)
        elif len(req.out) >= p.max_tokens or req.seq_len >= self.max_model_len:
            self.scheduler.finish(req, FINISH_LENGTH)

    def _publish_gauges(self) -> None:
        m = _metrics()
        m["running"].set(self.scheduler.num_running)
        m["waiting"].set(self.scheduler.num_waiting)
        m["kv_util"].set(self.pool.utilization())
