"""LLMEngine: the continuous-batching step loop.

One engine owns one model (GPT or GPT-J params), one paged KV pool, and
one scheduler.  ``step()`` is the whole design:

1. reap cancellations and blown deadlines;
2. admit waiting requests into free decode slots (FIFO, memory-gated,
   and — with the prefix cache on — CACHE-AWARE: the longest cached
   prefix is shared into the new block table, LRU cache eviction runs
   before anyone is preempted, and queued copy-on-write forks are
   applied as one batched device copy);
3. run ONE chunked-prefill piece for the oldest still-prefilling
   admission — interleaved with, never instead of, decode; completed
   prompt blocks are inserted into the prefix tree as they fill;
4. run ONE batched decode step across every running slot (single jitted
   call, static slot count), sample per-slot tokens (per-request
   temperature/top-k/top-p/seed), stream them out, finish requests that
   hit ``max_tokens``/stop tokens, preempting the youngest when the
   block pool runs dry.  With ``spec_k > 0`` the decode step is
   SPECULATIVE: a drafter (``llm.drafter``) proposes ``k`` tokens per
   slot, the target model verifies all ``k+1`` positions in one jitted
   call (``model_runner.verify_step``), and each slot emits its accepted
   prefix plus a correction/bonus token — up to ``k+1`` tokens per step
   at one target-model invocation, token-identical under greedy and
   distribution-exact under sampling (``models.sampling``).

Observability: every step is a ``util.tracing`` span; tokens/s, TTFT,
inter-token latency, running/waiting counts, KV-block utilization and
preemptions publish through ``util.metrics`` (the same surface the serve
autoscaler and Grafana boards read).

Threading: ``step()`` serializes on an internal lock — any number of
submitter threads (serve replica handlers) can feed the engine while one
driver thread (or several, harmlessly) turns the crank.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Iterator, Optional

import numpy as np

from ray_tpu._private import events as _events
from ray_tpu.llm.cache import CacheConfig, KVBlockPool
from ray_tpu.llm.model_runner import PagedModelRunner, _sample_rows
from ray_tpu.util import phases as _phases
from ray_tpu.util import tracing as _tracing
from ray_tpu.llm.scheduler import (
    FINISH_CANCELLED,
    FINISH_DEADLINE,
    FINISH_LENGTH,
    FINISH_STOP,
    FINISHED,
    PREFILL,
    RUNNING,
    Request,
    SamplingParams,
    Scheduler,
)

#: every metric the engine exports — the RL012 drift gate cross-checks
#: this registry against the constructors in ``_metrics()`` (both
#: directions), so a renamed metric cannot silently orphan its dashboard
#: panel or doc row
METRIC_NAMES = (
    "llm_generated_tokens",
    "llm_prefill_tokens",
    "llm_engine_steps",
    "llm_finished_requests",
    "llm_preemptions",
    "llm_running_requests",
    "llm_waiting_requests",
    "llm_kv_block_utilization",
    "llm_time_to_first_token_s",
    "llm_inter_token_latency_s",
    "llm_spec_draft_tokens",
    "llm_spec_accepted_tokens",
    "llm_spec_acceptance_rate",
    "llm_spec_draft_seconds",
    "llm_tokens_per_step",
    "llm_shed_requests",
    # HBM ledger: who holds device memory (the tiered-KV spill decision's
    # signal) — params, pool blocks split seq-owned vs cache-only
    # resident vs free, drafter state; conservation against
    # KVBlockPool.audit() is pinned by tests/test_profiling_plane.py
    "llm_hbm_params_bytes",
    "llm_hbm_kv_pool_bytes",
    "llm_hbm_kv_seq_bytes",
    "llm_hbm_kv_cache_bytes",
    "llm_hbm_kv_free_bytes",
    "llm_hbm_drafter_bytes",
)

_METRICS = None
_METRICS_LOCK = threading.Lock()


def _metrics() -> dict:
    """Engine metric set, created once per process (util.metrics registers
    globally; duplicates would fight in collect())."""
    global _METRICS
    if _METRICS is not None:
        return _METRICS  # lock-free fast path: called per token in _emit
    with _METRICS_LOCK:
        if _METRICS is not None:
            return _METRICS
        from ray_tpu.util.metrics import Counter, Gauge, Histogram

        _METRICS = {
            "tokens": Counter("llm_generated_tokens", "tokens sampled by the engine"),
            "prefill_tokens": Counter(
                "llm_prefill_tokens",
                "prompt tokens actually computed by prefill (a prefix-cache "
                "hit skips the matched head, so this is the MISS work)",
            ),
            "steps": Counter("llm_engine_steps", "engine step-loop iterations"),
            "finished": Counter(
                "llm_finished_requests", "requests finished for any reason"
            ),
            "preempt": Counter("llm_preemptions", "requests evicted under KV pressure"),
            "running": Gauge("llm_running_requests", "requests holding decode slots"),
            "waiting": Gauge("llm_waiting_requests", "requests queued for admission"),
            "kv_util": Gauge("llm_kv_block_utilization", "fraction of KV blocks in use"),
            "ttft": Histogram("llm_time_to_first_token_s", "submit → first token"),
            "itl": Histogram(
                "llm_inter_token_latency_s",
                "gap between consecutive streamed tokens",
                boundaries=(0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 1.0),
            ),
            # speculative decode: drafted vs accepted counters give the
            # lifetime acceptance rate; the gauges give the latest step's
            "spec_proposed": Counter(
                "llm_spec_draft_tokens", "draft tokens proposed for verification"
            ),
            "spec_accepted": Counter(
                "llm_spec_accepted_tokens", "draft tokens accepted by verification"
            ),
            "spec_accept_rate": Gauge(
                "llm_spec_acceptance_rate", "accepted/proposed of the last step"
            ),
            "spec_draft_s": Counter(
                "llm_spec_draft_seconds", "cumulative wall time inside the drafter"
            ),
            "tokens_per_step": Gauge(
                "llm_tokens_per_step", "tokens emitted by the last decode step"
            ),
            "shed": Counter(
                "llm_shed_requests",
                "requests rejected by deadline-aware admission (429 upstream)",
            ),
            # HBM ledger gauges: live byte accounting of device memory.
            # params + pool + drafter is (approximately) the resident
            # footprint; the three kv_* gauges PARTITION the pool's
            # usable blocks (seq-owned + cache-only + free), so the
            # tiered-KV spill decision can read exactly how much HBM a
            # host-RAM tier would reclaim (cache-only bytes).
            # tag_keys: under tp>1 (llm.multichip) every family is
            # ADDITIONALLY published per mesh device as {device=<id>};
            # the untagged series stays the pool-wide truth either way
            "hbm_params": Gauge(
                "llm_hbm_params_bytes", "device bytes held by model params",
                tag_keys=("device",),
            ),
            "hbm_pool": Gauge(
                "llm_hbm_kv_pool_bytes",
                "total device bytes of the KV pool arrays (fixed at start)",
                tag_keys=("device",),
            ),
            "hbm_seq": Gauge(
                "llm_hbm_kv_seq_bytes",
                "bytes of KV blocks owned by at least one live sequence",
                tag_keys=("device",),
            ),
            "hbm_cache": Gauge(
                "llm_hbm_kv_cache_bytes",
                "bytes of KV blocks resident ONLY in the prefix cache "
                "(reclaimable without preempting anyone)",
                tag_keys=("device",),
            ),
            "hbm_free": Gauge(
                "llm_hbm_kv_free_bytes", "bytes of free-list KV blocks",
                tag_keys=("device",),
            ),
            "hbm_drafter": Gauge(
                "llm_hbm_drafter_bytes",
                "device bytes held by the speculative drafter's params",
                tag_keys=("device",),
            ),
        }
    return _METRICS


def _tree_device_bytes(params) -> int:
    """Total ``nbytes`` across a param pytree (0 for None — the n-gram
    drafter holds no device state)."""
    if params is None:
        return 0
    import jax

    return sum(
        int(getattr(leaf, "nbytes", 0))
        for leaf in jax.tree_util.tree_leaves(params)
    )


@dataclasses.dataclass(frozen=True)
class EngineConfig:
    """Engine geometry. ``num_blocks`` includes the reserved trash block;
    ``max_blocks_per_seq * block_size`` caps a sequence (prompt + output),
    additionally clamped by the model's positional table for GPT.

    Speculative decoding: ``spec_k > 0`` turns it on — each step a drafter
    proposes ``spec_k`` tokens per running slot and the target model
    verifies all of them plus a bonus position in ONE jitted call
    (``llm.drafter`` module doc).  ``spec_drafter`` is ``"ngram"``
    (model-free prompt lookup; ``spec_ngram_max`` caps the matched n-gram)
    or ``"model"`` (a small draft model passed to ``LLMEngine`` as
    ``draft_model_cfg``/``draft_params``; ``spec_draft_ctx`` fixes its
    context window).  Greedy output is token-identical either way; ``k``
    trades verification width against acceptance — 2-4 fits most
    workloads, higher only pays when acceptance stays near 1.

    Adversarial (low-acceptance) workloads are bounded by backoff: when a
    verify step accepts less than ``spec_min_accept`` of its drafts, the
    engine falls back to plain decode for exponentially more steps
    (doubling up to ``spec_backoff_max``) before probing speculation
    again — a regime change (output entering a repetitive stretch) is
    picked back up at the next probe, while steady low acceptance decays
    to plain-decode cost plus one probe in ``spec_backoff_max``.  Both
    step shapes are jitted once; toggling never retraces.

    Prefix cache: ``prefix_cache`` (default ON) shares KV blocks across
    requests through a radix tree over block contents
    (``llm.prefix_cache``): admission matches the longest cached prefix
    and prefills only the uncached suffix, with copy-on-write forks on
    intra-block divergence (``prefix_cow_min_tokens`` sets the minimum
    intra-block match worth a device block copy).  Outputs are
    token-identical with the cache on or off — prefix reuse is exact,
    never approximate — and cached blocks are evicted LRU-first under
    pool pressure before any live request is preempted."""

    max_slots: int = 4
    num_blocks: int = 128
    block_size: int = 16
    max_blocks_per_seq: int = 32
    prefill_chunk: int = 32
    attn_impl: str = "auto"
    #: tensor parallelism (llm.multichip): tp > 1 shards the KV pool's
    #: head axis, attention heads and MLP weights over the first ``tp``
    #: devices (``parallel.mesh.make_tp_mesh``) — same engine semantics,
    #: same token stream (greedy/seeded), per-device HBM attribution on
    #: the ledger gauges. Requires n_heads % tp == 0 and d_ff % tp == 0.
    tp: int = 1
    spec_k: int = 0
    spec_drafter: str = "ngram"
    spec_ngram_max: int = 3
    spec_draft_ctx: int = 16
    spec_min_accept: float = 0.3
    spec_backoff_max: int = 32
    prefix_cache: bool = True
    prefix_cow_min_tokens: int = 1
    #: deadline-aware overload shedding (RESILIENCE.md): a submit carrying
    #: ``deadline_s`` is REJECTED with ``OverloadedError`` (429 at the
    #: proxy) when backlog ÷ observed service rate says the deadline
    #: cannot be met — queueing doomed work only steals KV blocks and
    #: decode slots from requests that could still make their deadlines.
    #: Requests without a deadline are never shed.
    shed: bool = True
    #: engine watchdog (llm.watchdog): stall-detection deadline and check
    #: cadence. The watchdog thread itself is started by the owner
    #: (serve.llm replicas start one; bare engines opt in via
    #: ``start_watchdog()``).
    watchdog_stall_s: float = 30.0
    watchdog_interval_s: float = 1.0


class LLMEngine:
    def __init__(
        self,
        model_cfg,
        params: dict,
        engine_cfg: Optional[EngineConfig] = None,
        draft_model_cfg=None,
        draft_params: Optional[dict] = None,
    ):
        self.cfg = engine_cfg or EngineConfig()
        self.model_cfg = model_cfg
        if self.cfg.spec_k < 0:
            raise ValueError("spec_k must be >= 0")
        cache_cfg = CacheConfig(
            num_blocks=self.cfg.num_blocks,
            block_size=self.cfg.block_size,
            max_blocks_per_seq=self.cfg.max_blocks_per_seq,
        )
        if self.cfg.tp > 1:
            # tensor-parallel substrate (llm.multichip): sharded runner +
            # head-sharded pool over the same tp mesh; everything below
            # (scheduler, prefix cache, drafter, watchdog) is mesh-blind
            from ray_tpu.llm.multichip import (
                ShardedKVBlockPool,
                TensorParallelPagedModelRunner,
            )

            self.runner = TensorParallelPagedModelRunner(
                model_cfg, params, self.cfg.block_size,
                attn_impl=self.cfg.attn_impl, tp=self.cfg.tp,
            )
            self.pool = ShardedKVBlockPool(
                cache_cfg,
                n_layers=model_cfg.n_layers,
                n_heads=model_cfg.n_heads,
                head_dim=model_cfg.head_dim,
                dtype=model_cfg.dtype,
                tp=self.cfg.tp,
            )
        else:
            self.runner = PagedModelRunner(
                model_cfg, params, self.cfg.block_size, attn_impl=self.cfg.attn_impl
            )
            self.pool = KVBlockPool(
                cache_cfg,
                n_layers=model_cfg.n_layers,
                n_heads=model_cfg.n_heads,
                head_dim=model_cfg.head_dim,
                dtype=model_cfg.dtype,
            )
        self.prefix_cache = None
        if self.cfg.prefix_cache:
            from ray_tpu.llm.prefix_cache import PrefixCache

            self.prefix_cache = PrefixCache(
                self.pool, cow_min_tokens=self.cfg.prefix_cow_min_tokens
            )
        self.scheduler = Scheduler(
            self.pool, self.cfg.max_slots, prefix_cache=self.prefix_cache
        )
        self._drafter = None
        if self.cfg.spec_k > 0:
            from ray_tpu.llm.drafter import make_drafter

            self._drafter = make_drafter(
                self.cfg.spec_drafter,
                self.cfg.spec_k,
                self.cfg.max_slots,
                ngram_max=self.cfg.spec_ngram_max,
                draft_cfg=draft_model_cfg,
                draft_params=draft_params,
                draft_ctx=self.cfg.spec_draft_ctx,
            )
            # prefix-aware drafting: the n-gram drafter extends its
            # lookup past the local prompt into the shared radix paths —
            # a warm request's continuation often already sits on a path
            # another request prefilled (drafts affect throughput only;
            # verification keeps output exact either way)
            if self.prefix_cache is not None and hasattr(self._drafter, "corpus"):
                self._drafter.corpus = self.prefix_cache.paths
        # HBM ledger inputs fixed at init: params/drafter footprints never
        # change size (update_weights validates identical leaf shapes),
        # and the pool arrays are allocated once
        self._params_bytes = _tree_device_bytes(params)
        self._drafter_bytes = _tree_device_bytes(
            getattr(self._drafter, "_params", None)
        )
        self._lock = threading.Lock()
        self._requests: dict[str, Request] = {}
        self._step_n = 0
        self._tokens_generated = 0
        self._prefill_tokens = 0
        self._preemptions = 0
        self._finished_published = 0  # scheduler.finish_count already counted
        self._spec_proposed = 0
        self._spec_accepted = 0
        self._spec_draft_s = 0.0
        self._spec_skip = 0      # plain-decode steps left before re-probing
        self._spec_backoff = 0   # current backoff length (0 = speculating)
        # liveness beat, read LOCK-FREE by the watchdog and stream_tokens'
        # stall diagnosis (a wedged step holds the engine lock, so the
        # observers must never need it): (monotonic t of the last completed
        # step — idle ticks count, a wedge does not — , pending work then).
        # One-tuple assignment keeps the read torn-free under the GIL.
        self._beat: tuple[float, int] = (time.monotonic(), 0)
        self._watchdog = None
        # observed decode throughput (EWMA tokens/s) for deadline-aware
        # admission: backlog ÷ rate estimates a new request's completion
        self._rate = 0.0
        self._rate_mark = (time.monotonic(), 0)  # (t, tokens_generated)
        # learner→engine weight sync (rlhf.sync): monotonic version of the
        # params the jitted steps currently close over; update_weights
        # hot-swaps between step() iterations and every submit stamps the
        # version it was admitted under onto its Request
        self._weights_version = 0
        # model-length cap: paged table width, and the learned positional
        # table for GPT (rotary GPT-J has no absolute cap of its own)
        self.max_model_len = cache_cfg.max_seq_len
        if self.runner.arch == "gpt":
            self.max_model_len = min(self.max_model_len, model_cfg.seq_len)
        import jax

        self._sample1 = jax.jit(_sample_rows)

    # -- public API --------------------------------------------------------

    def submit(
        self,
        prompt: list[int],
        params: Optional[SamplingParams] = None,
        deadline_s: Optional[float] = None,
        resume_tokens: tuple = (),
    ) -> Request:
        """Queue a request; returns immediately (drive with ``step()`` or a
        loop thread; consume with ``stream_tokens``).

        ``resume_tokens`` — tokens a previous replica already generated for
        this request before dying (mid-stream failover, RESILIENCE.md).
        They pre-fold into the request's output: the cache is rebuilt by
        re-prefilling prompt + resumed tokens, generation continues at
        output index ``len(resume_tokens)`` with the same per-index PRNG
        keys, and only NEW tokens are streamed — token-identical to the
        unkilled run under greedy and seeded sampling alike.

        With a ``deadline_s`` and ``EngineConfig.shed`` on, admission is
        deadline-aware: when queue backlog ÷ observed service rate says the
        deadline cannot be met, the request is REJECTED with
        ``ray_tpu.exceptions.OverloadedError`` (``retry_after_s`` attached)
        instead of queued as doomed work.
        """
        params = params or SamplingParams()
        if params.max_tokens < 1:
            raise ValueError("max_tokens must be >= 1")
        if deadline_s is not None:
            import math

            # json.loads happily produces NaN/Infinity; a non-finite
            # deadline would make every "now >= deadline" reap check False
            # forever and poison the stream-timeout arithmetic downstream
            if not math.isfinite(deadline_s):
                raise ValueError(f"deadline_s must be finite, got {deadline_s}")
        if len(resume_tokens) > params.max_tokens:
            raise ValueError(
                f"resume_tokens ({len(resume_tokens)}) exceeds max_tokens "
                f"({params.max_tokens})"
            )
        total = len(prompt) + params.max_tokens
        if total > self.max_model_len:
            raise ValueError(
                f"prompt ({len(prompt)}) + max_tokens ({params.max_tokens}) "
                f"exceeds max model length {self.max_model_len}"
            )
        # the request must be able to COMPLETE with the pool to itself —
        # admission's worst case is a re-admission one token before the end
        # plus one block of headroom (or, speculating, plus the window's k
        # provisional positions). Without this check an oversized request
        # passes validation, can never be admitted, and livelocks the FIFO
        # head (starving everything queued behind it).
        headroom = max(self.pool.cfg.block_size, self.cfg.spec_k)
        worst = min(total - 1 + headroom, self.pool.cfg.max_seq_len)
        usable = self.pool.cfg.num_blocks - 1
        if self.pool.blocks_for(worst) > usable:
            raise ValueError(
                f"request needs up to {self.pool.blocks_for(worst)} KV blocks "
                f"but the pool has only {usable} usable blocks "
                f"(num_blocks={self.pool.cfg.num_blocks}, block 0 reserved)"
            )
        deadline = time.time() + deadline_s if deadline_s is not None else None
        req = Request(prompt, params, deadline=deadline, resume_tokens=resume_tokens)
        if req.phase_led is not None:
            # cross-process dispatch leg: the proxy's stream thread stamped
            # its dispatch anchor into the sampled trace-ctx dict it minted
            _phases.note_dispatch(req, _tracing.get_trace_context())
        # staleness stamp: the policy version this trajectory STARTS under
        # (a mid-generation hot-swap is fine — per-token behavior logprobs
        # stay exact regardless; the stamp drives the rlhf admission gate)
        req.weights_version = self._weights_version
        _events.record(
            "llm.submit", request_id=req.trace_id, engine_req=req.id,
            prompt_len=len(prompt), max_tokens=params.max_tokens,
            resumed=len(req.out),
        )
        # a resume that already satisfies its stopping condition finishes
        # without touching the scheduler: the previous replica died between
        # delivering the final token and the stream's "done" sentinel
        done_reason = None
        if req.out and req.out[-1] in params.stop_token_ids:
            done_reason = FINISH_STOP
        elif len(req.out) >= params.max_tokens:
            done_reason = FINISH_LENGTH
        if done_reason is not None:
            req.state = FINISHED
            req.finish_reason = done_reason
            if req.phase_led is not None:
                # fold the (near-empty) ledger so obs attribute still sees
                # this attempt — its whole life was the submit check
                now = time.time()
                _phases.charge(req.phase_led, _phases.QUEUE, now)
                _phases.fold_engine(req, now, done_reason)
            _events.record(
                "llm.finish", request_id=req.trace_id, engine_req=req.id,
                reason=done_reason, tokens_out=len(req.out),
            )
            req.stream.put(("done", done_reason))
            return req
        with self._lock:
            if self.cfg.shed and deadline_s is not None:
                est = self._estimate_completion_s_locked(
                    params.max_tokens - len(req.out)
                )
                if est is not None and est > deadline_s:
                    from ray_tpu.exceptions import OverloadedError

                    retry_after = max(0.1, round(est - deadline_s, 2))
                    _events.record(
                        "llm.shed", request_id=req.trace_id,
                        engine_req=req.id, estimate_s=round(est, 3),
                        deadline_s=deadline_s, retry_after_s=retry_after,
                    )
                    _metrics()["shed"].inc()
                    raise OverloadedError(
                        f"engine overloaded: estimated completion in "
                        f"{est:.2f}s exceeds the {deadline_s:.2f}s deadline "
                        f"(backlog at {self._rate:.1f} tokens/s)",
                        retry_after_s=retry_after,
                    )
            # re-stamp under the lock: a push that landed between Request
            # construction and admission is the version this trajectory
            # actually starts decoding under
            req.weights_version = self._weights_version
            self._requests[req.id] = req
            self.scheduler.add(req)
            # liveness beat: raise the pending count so the watchdog sees
            # the new work, and if the engine was IDLE until now, restart
            # the age clock — the stall timer must measure "work waited
            # this long", not "the engine was idle this long before work
            # arrived" (a stale timestamp here false-paged the stall SLO)
            t, prev_pending = self._beat
            self._beat = (
                time.monotonic() if prev_pending == 0 else t,
                self.scheduler.num_running + self.scheduler.num_waiting,
            )
        return req

    def _estimate_completion_s_locked(self, new_tokens: int) -> Optional[float]:
        """Seconds until a request needing ``new_tokens`` more tokens would
        finish, from the backlog of promised-but-ungenerated tokens and the
        observed service rate. None (no shedding evidence) when there is no
        backlog or no measured rate — an EMPTY engine never sheds, whatever
        a stale rate says (it will finish a lone request as fast as it can;
        the estimate only means something when the request must wait its
        turn behind real work that keeps the rate sample fresh)."""
        rate = self._rate
        if rate <= 1e-6:
            return None
        backlog = sum(
            max(r.params.max_tokens - len(r.out), 0)
            for r in list(self.scheduler.waiting) + self.scheduler.running
        )
        if backlog <= 0:
            return None
        return (backlog + new_tokens) / rate

    def cancel(self, req_id: str) -> bool:
        """Flag a request for cancellation; the next step reaps it (frees
        its slot and blocks, ends its stream)."""
        req = self._requests.get(req_id)
        if req is None:
            return False
        req.cancelled.set()
        return True

    def has_work(self) -> bool:
        with self._lock:
            return self.scheduler.has_work()

    @property
    def weights_version(self) -> int:
        """Version of the params the engine currently decodes with."""
        return self._weights_version

    def update_weights(self, params: dict, version: Optional[int] = None) -> int:
        """Hot-swap the model parameters between step() iterations WITHOUT
        draining in-flight requests (the rlhf learner→engine sync path;
        ``rlhf.sync.apply_weight_update`` wraps this for chunked
        object-plane pushes).

        The new pytree must match the current one's structure and leaf
        shapes/dtypes — then the jitted step functions never retrace (they
        cache on shape, and params are a traced argument, not a captured
        constant). Leaves are ``device_put`` once here so steady-state
        steps don't re-upload host arrays every call. In-flight requests
        simply continue under the new weights from their next step —
        exactly the semantics async RL wants (and their per-token behavior
        logprobs were captured at sample time, so off-policy correction
        stays exact across the swap).

        ``version`` must be monotonically increasing (default: current+1).
        Returns the installed version.
        """
        import jax

        # prepare_params owns placement: plain device conversion single-
        # chip, sharded device_put (+ fused-qkv permutation) under tp>1 —
        # either way the swap lands with the compiled steps' exact layout
        new = self.runner.prepare_params(params)
        t0 = time.perf_counter()
        with self._lock:
            old_struct = jax.tree_util.tree_structure(self.runner.params)
            new_struct = jax.tree_util.tree_structure(new)
            if old_struct != new_struct:
                raise ValueError(
                    "update_weights pytree structure mismatch: "
                    f"{new_struct} != {old_struct}"
                )
            for a, b in zip(
                jax.tree_util.tree_leaves(self.runner.params),
                jax.tree_util.tree_leaves(new),
            ):
                if a.shape != b.shape or a.dtype != b.dtype:
                    raise ValueError(
                        f"update_weights leaf mismatch: {b.shape}/{b.dtype} "
                        f"!= {a.shape}/{a.dtype} (a retrace mid-traffic is "
                        "never acceptable)"
                    )
            if version is None:
                version = self._weights_version + 1
            if version < self._weights_version:
                raise ValueError(
                    f"weights_version must not go backwards: "
                    f"{version} < {self._weights_version}"
                )
            self.runner.params = new
            self._weights_version = version
            # cached prefix KV was computed under the OLD weights: flush
            # the tree so no new request seeds from it (in-flight
            # requests keep their own references — same mid-swap
            # semantics as their continued decode under new weights)
            if self.prefix_cache is not None:
                self.prefix_cache.flush(reason="weights_update")
            in_flight = self.scheduler.num_running + self.scheduler.num_waiting
        _events.record(
            "llm.weights_update", version=version,
            apply_s=round(time.perf_counter() - t0, 6), in_flight=in_flight,
        )
        return version

    def stream_tokens(self, req: Request, timeout: float = 60.0) -> Iterator[int]:
        """Yield the request's tokens as the engine produces them.

        A timeout raises ``EngineStalledError`` (a ``TimeoutError``
        subclass) carrying the stall diagnosis — last-step age, queue
        depth, and KV utilization — gathered WITHOUT the engine lock, so
        the diagnosis works precisely when the step loop is wedged holding
        it."""
        import queue as _q

        while True:
            try:
                kind, val = req.stream.get(timeout=timeout)
            except _q.Empty:
                from ray_tpu.llm.watchdog import EngineStalledError

                age, pending = self.progress()
                kv = self.pool.utilization()
                _events.record(
                    "llm.watchdog.stall", request_id=req.trace_id,
                    engine_req=req.id, source="stream_tokens",
                    last_step_age_s=round(age, 3), queue_depth=pending,
                    kv_utilization=round(kv, 4), timeout_s=timeout,
                )
                raise EngineStalledError(
                    f"no token from {req.id} within {timeout}s "
                    f"(state={req.state})",
                    last_step_age_s=age,
                    queue_depth=pending,
                    kv_utilization=kv,
                ) from None
            if kind == "token":
                yield val
            else:
                return

    def progress(self) -> tuple[float, int]:
        """(seconds since the last completed step tick, pending work at
        that tick) — lock-free, safe to call while a step is wedged."""
        t, pending = self._beat
        return time.monotonic() - t, pending

    def start_watchdog(self):
        """Start (once) the engine watchdog thread — stall detection,
        deadline/cancel reaping that works around a wedged step loop, and
        the KV-pool leak audit (``llm.watchdog`` module doc). Serve
        replicas call this; bare engines may too."""
        if self._watchdog is None:
            from ray_tpu.llm.watchdog import EngineWatchdog

            self._watchdog = EngineWatchdog(
                self,
                stall_deadline_s=self.cfg.watchdog_stall_s,
                interval_s=self.cfg.watchdog_interval_s,
            ).start()
        return self._watchdog

    def generate(
        self,
        prompt: list[int],
        params: Optional[SamplingParams] = None,
        deadline_s: Optional[float] = None,
    ) -> list[int]:
        """Blocking convenience: submit and drive until finished. Safe to
        call while a loop thread is also stepping (steps serialize)."""
        req = self.submit(prompt, params, deadline_s)
        while not req.finished:
            if not self.step():
                time.sleep(0.001)
        return list(req.out)

    def warmup(self) -> None:
        """Compile every jitted step path — prefill, decode, and (when
        speculating) verify — so the first real request runs at
        steady-state latency.  A speculating engine routes decode through
        ``verify_step`` until acceptance drops, so one generate would
        leave the PLAIN decode path (the backoff fallback) cold.  The
        verify jit is driven DIRECTLY with a dummy batch rather than via
        generate: whether a generate ever reaches verification is gated
        on the drafter finding a confident match in the (model-dependent)
        warmup output, so only a direct call guarantees the compile.  The
        dummy batch's all-zero block tables route every provisional write
        to the reserved trash block — real pool contents are untouched."""
        self.generate([0], SamplingParams(max_tokens=2))
        if self.prefix_cache is not None:
            # compile the CoW fork jit with trash→trash lanes (block 0
            # copied onto itself: identity, real pool contents untouched)
            with self._lock:
                z = np.zeros(self.cfg.max_slots, np.int32)
                self.pool.k, self.pool.v = self.runner.fork_blocks(
                    self.pool.k, self.pool.v, z, z
                )
        if self._drafter is not None:
            with self._lock:
                self._spec_skip = 1 << 30  # force the plain-decode path
            self.generate([0], SamplingParams(max_tokens=2))
            with self._lock:
                self._spec_skip = 0
                self._spec_backoff = 0
                S, W = self.cfg.max_slots, self.cfg.spec_k + 1
                k, v, _, _, _ = self.runner.verify_step(
                    self.pool.k, self.pool.v,
                    np.zeros((S, W), np.int32),
                    np.zeros(S, np.int32),
                    np.zeros((S, self.pool.cfg.max_blocks_per_seq), np.int32),
                    np.zeros(S, np.float32),
                    np.zeros(S, np.int32),
                    np.ones(S, np.float32),
                    np.zeros(S, np.uint32),
                    np.zeros(S, np.int32),
                )
                self.pool.k, self.pool.v = k, v

    def stats(self) -> dict:
        with self._lock:
            # ONE pool-ledger snapshot feeds utilization, free_blocks and
            # the hbm section — three separate property reads could each
            # interleave with an allocation and disagree in one response
            led = self.hbm_ledger()
            s = {
                "running": self.scheduler.num_running,
                "waiting": self.scheduler.num_waiting,
                "queue_depth": self.scheduler.num_waiting,
                "kv_utilization": led["utilization"],
                "free_blocks": led["free_blocks"],
                "steps": self._step_n,
                "tokens_generated": self._tokens_generated,
                "prefill_tokens_computed": self._prefill_tokens,
                "preemptions": self._preemptions,
                "service_rate_tokens_per_s": self._rate,
                "weights_version": self._weights_version,
            }
            if self.prefix_cache is not None:
                s["prefix_cache"] = self.prefix_cache.stats()
            s["hbm"] = led
            s["retraces"] = self.runner.prof.retraces
            if self._drafter is not None:
                s["spec_proposed"] = self._spec_proposed
                s["spec_accepted"] = self._spec_accepted
                s["spec_acceptance_rate"] = self._spec_accepted / max(
                    self._spec_proposed, 1
                )
                s["spec_draft_seconds"] = self._spec_draft_s
            return s

    def run_loop(self, stop: threading.Event, idle_sleep_s: float = 0.002) -> None:
        """Drive ``step()`` until ``stop`` is set (serve replicas run this
        in a daemon thread)."""
        while not stop.is_set():
            if not self.step():
                stop.wait(idle_sleep_s)

    # -- the step ----------------------------------------------------------

    def step(self) -> bool:
        """One engine iteration; returns True when any work was done."""
        from ray_tpu.util import tracing

        with self._lock:
            sched = self.scheduler
            if not sched.has_work():
                self._publish_gauges()
                self._beat = (time.monotonic(), 0)
                return False
            self._step_n += 1
            m = _metrics()
            m["steps"].inc()
            # spec stats fill in during the step; span attributes serialize
            # at span EXIT, so the dict lands populated in the trace
            spec_info: dict = {}
            attrs = dict(
                step=self._step_n,
                running=sched.num_running,
                waiting=sched.num_waiting,
            )
            if self._drafter is not None:
                attrs["spec"] = spec_info
            with tracing.span("llm_engine_step", **attrs):
                self._reap()
                sched.admit()
                self._apply_cow()
                did = self._prefill_one()
                if self._drafter is not None and self._spec_skip == 0:
                    did = self._spec_decode_all(spec_info) or did
                else:
                    did_decode = self._decode_all()
                    if did_decode and self._spec_skip > 0:
                        self._spec_skip -= 1  # backoff ticks on real decodes
                    did = did_decode or did
            # prune finished requests: the registry otherwise retains every
            # Request (prompt, output, stream queue) for the replica's
            # lifetime. Callers keep their own Request references; cancel()
            # of a pruned id is a no-op, which is correct for finished work.
            self._requests = {
                k: r for k, r in self._requests.items() if not r.finished
            }
            self._publish_gauges()
            self._beat = (
                time.monotonic(), sched.num_running + sched.num_waiting
            )
            return did or sched.has_work()

    # -- internals (all called under the lock) -----------------------------

    def _reap(self) -> int:
        """Finish cancelled and deadline-blown requests (lock held). Also
        the watchdog's locked reap path — ONE copy of the doomed-request
        predicate. Returns how many were finished."""
        now = time.time()
        n = 0
        for req in list(self.scheduler.waiting) + self.scheduler.running:
            if req.cancelled.is_set():
                self.scheduler.finish(req, FINISH_CANCELLED)
                n += 1
            elif req.deadline is not None and now >= req.deadline:
                self.scheduler.finish(req, FINISH_DEADLINE)
                n += 1
        return n

    def _apply_cow(self) -> None:
        """Drain the scheduler's queued copy-on-write forks (cache-aware
        admissions that diverged inside a cached block): one batched
        device copy duplicates each src block into the request's fresh
        dst block BEFORE any prefill chunk attends through it."""
        pend = self.scheduler.pending_cow
        if not pend:
            return
        self.scheduler.pending_cow = []
        F = self.cfg.max_slots
        for start in range(0, len(pend), F):
            batch = pend[start : start + F]
            src = np.zeros(F, np.int32)
            dst = np.zeros(F, np.int32)
            for j, (s, d, _rid) in enumerate(batch):
                src[j], dst[j] = s, d
            self.pool.k, self.pool.v = self.runner.fork_blocks(
                self.pool.k, self.pool.v, src, dst
            )
        now = time.time()
        for _s, _d, rid in pend:
            req = self._requests.get(rid)
            if req is not None and req.phase_led is not None:
                _phases.charge(req.phase_led, _phases.COW_FORK, now)

    def _prefill_one(self) -> bool:
        """One chunk for the oldest admission still prefilling."""
        pre = [r for r in self.scheduler.slots if r is not None and r.state == PREFILL]
        if not pre:
            return False
        req = min(pre, key=lambda r: self.scheduler._admitted_at.get(r.id, 0))
        chunk = self.cfg.prefill_chunk
        # a preempted request replays prompt + already-generated tokens to
        # rebuild its cache; a fresh one just prefills its prompt — and a
        # prefix-cache hit starts past the matched prefix either way
        full = req.prompt + req.out
        piece = full[req.prefill_pos : req.prefill_pos + chunk]
        n_valid = len(piece)
        tokens = np.zeros(chunk, np.int32)
        tokens[:n_valid] = piece
        table = self.pool.table_row(req.id)
        k, v, last_logits = self.runner.prefill_chunk(
            self.pool.k, self.pool.v, tokens, req.prefill_pos, n_valid, table
        )
        self.pool.k, self.pool.v = k, v
        req.prefill_pos += n_valid
        self._prefill_tokens += n_valid
        if req.phase_led is not None:
            # a recompute's re-prefill is preemption cost, not prefill
            _phases.charge(
                req.phase_led,
                _phases.PREEMPT if req.phase_recompute else _phases.PREFILL,
                time.time(),
            )
        _metrics()["prefill_tokens"].inc(n_valid)
        _events.record(
            "llm.prefill_chunk", request_id=req.trace_id, engine_req=req.id,
            pos=req.prefill_pos, of=len(full), n=n_valid,
        )
        if self.prefix_cache is not None:
            # register the now-complete PROMPT blocks (generated tokens
            # never enter the tree — only prompt content is matchable);
            # the admission epoch keeps a request whose prefill straddled
            # a weight-swap flush from re-inserting old-weight KV
            self.prefix_cache.insert(
                req.prompt,
                self.pool.blocks_of(req.id),
                limit=min(req.prefill_pos, len(req.prompt)),
                epoch=req.cache_epoch,
            )
        if req.prefill_pos >= len(full):
            # final chunk: its last position's logits seed generation
            p = req.params
            tok, lp = self._sample1(
                last_logits[None, :],
                np.asarray([p.seed & 0xFFFFFFFF], np.uint32),
                np.asarray([len(req.out)], np.int32),
                np.asarray([p.temperature], np.float32),
                np.asarray([p.top_k], np.int32),
                np.asarray([p.top_p], np.float32),
            )
            req.state = RUNNING
            req.phase_recompute = False  # recompute ends where decode resumes
            self._emit(req, int(tok[0]), float(lp[0]))
        return True

    def _grow_all(self, extra: int = 0) -> None:
        """Ensure every RUNNING slot has cache room for the position(s)
        the next step writes (plus ``extra`` provisional speculative
        ones), evicting the youngest when the pool is dry, with
        preemption accounting."""
        sched = self.scheduler
        for req in list(sched.running):
            if req.state != RUNNING:
                continue
            before = sched.preempt_count
            if not sched.grow_for_decode(req, extra=extra):
                pass  # req itself was preempted; it re-prefills later
            self._preemptions += sched.preempt_count - before
            _metrics()["preempt"].inc(sched.preempt_count - before)

    def _decode_all(self) -> bool:
        """One batched decode step over every RUNNING slot."""
        sched = self.scheduler
        # memory first: every runner needs space for the token it is about
        # to write; the youngest gets evicted when the pool is dry
        self._grow_all()
        active = [
            (i, r)
            for i, r in enumerate(sched.slots)
            if r is not None and r.state == RUNNING
        ]
        if not active:
            return False
        S = self.cfg.max_slots
        tokens = np.zeros(S, np.int32)
        positions = np.zeros(S, np.int32)
        tables = np.zeros((S, self.pool.cfg.max_blocks_per_seq), np.int32)
        temp = np.zeros(S, np.float32)
        top_k = np.zeros(S, np.int32)
        top_p = np.ones(S, np.float32)
        seeds = np.zeros(S, np.uint32)
        counters = np.zeros(S, np.int32)
        for i, req in active:
            tokens[i] = req.out[-1] if req.out else req.prompt[-1]
            positions[i] = req.seq_len - 1  # the fed token's position
            tables[i] = self.pool.table_row(req.id)
            p = req.params
            temp[i] = p.temperature
            top_k[i] = p.top_k
            top_p[i] = p.top_p
            # mask, don't assign raw: a negative seed overflows a uint32
            # cell on NumPy >= 2 and the OverflowError would kill the
            # engine loop thread
            seeds[i] = p.seed & 0xFFFFFFFF
            counters[i] = len(req.out)
        k, v, nxt, logp = self.runner.decode_step(
            self.pool.k, self.pool.v, tokens, positions, tables,
            temp, top_k, top_p, seeds, counters,
        )
        self.pool.k, self.pool.v = k, v
        import jax

        nxt, logp = jax.device_get((nxt, logp))  # ONE host sync for the batch
        now = time.time()
        for i, req in active:
            if req.phase_led is not None:
                _phases.charge(req.phase_led, _phases.DECODE, now)
        for i, req in active:
            _events.record(
                "llm.decode", request_id=req.trace_id, engine_req=req.id,
                step=self._step_n, token=int(nxt[i]),
            )
            self._emit(req, int(nxt[i]), float(logp[i]))
        _metrics()["tokens_per_step"].set(len(active))
        return True

    def _spec_decode_all(self, spec_info: dict) -> bool:
        """One speculative step over every RUNNING slot: draft k tokens
        per slot, verify k+1 positions in one jitted call, emit the
        accepted prefix + correction/bonus, roll the ledger back."""
        import jax

        sched = self.scheduler
        kd = self.cfg.spec_k
        active = [
            (i, r)
            for i, r in enumerate(sched.slots)
            if r is not None and r.state == RUNNING
        ]
        if not active:
            return False
        t0 = time.perf_counter()
        draft = self._drafter.propose([r.prompt + r.out for _, r in active])
        draft_s = time.perf_counter() - t0
        self._spec_draft_s += draft_s
        _metrics()["spec_draft_s"].inc(draft_s)
        # drafter confidence gate: when NO slot's proposal is backed by a
        # real match (NGramDrafter.last_matched), the whole window would
        # be a doomed probe — plain-decode this step instead of paying a
        # w-wide verify to learn it.  Hostile workloads thus cost the
        # (host-side, near-free) drafting only; model drafters have no
        # such signal and rely on the acceptance backoff alone.
        matched = getattr(self._drafter, "last_matched", None)
        if matched is not None and not bool(matched.any()):
            return self._decode_all()
        draft_by_id = {r.id: draft[row] for row, (_, r) in enumerate(active)}
        # memory next: the window provisionally writes positions
        # seq_len-1 .. seq_len-1+k; the youngest gets evicted when dry
        self._grow_all(extra=kd)
        active = [(i, r) for i, r in active if r.state == RUNNING]
        if not active:
            return False
        S, W = self.cfg.max_slots, kd + 1
        tokens = np.zeros((S, W), np.int32)
        base_pos = np.zeros(S, np.int32)
        tables = np.zeros((S, self.pool.cfg.max_blocks_per_seq), np.int32)
        temp = np.zeros(S, np.float32)
        top_k = np.zeros(S, np.int32)
        top_p = np.ones(S, np.float32)
        seeds = np.zeros(S, np.uint32)
        counters = np.zeros(S, np.int32)
        for i, req in active:
            tokens[i, 0] = req.out[-1] if req.out else req.prompt[-1]
            tokens[i, 1:] = draft_by_id[req.id]
            base_pos[i] = req.seq_len - 1  # the fed token's position
            tables[i] = self.pool.table_row(req.id)
            p = req.params
            temp[i] = p.temperature
            top_k[i] = p.top_k
            top_p[i] = p.top_p
            seeds[i] = p.seed & 0xFFFFFFFF
            counters[i] = len(req.out)
        k, v, n_acc, out, out_lp = self.runner.verify_step(
            self.pool.k, self.pool.v, tokens, base_pos, tables,
            temp, top_k, top_p, seeds, counters,
        )
        self.pool.k, self.pool.v = k, v
        n_acc, out, out_lp = jax.device_get((n_acc, out, out_lp))  # ONE host sync
        now = time.time()
        for i, req in active:
            if req.phase_led is not None:
                _phases.charge(req.phase_led, _phases.SPEC_VERIFY, now)
        emitted = 0
        accepted = 0
        for i, req in active:
            n = int(n_acc[i])
            accepted += n
            _events.record(
                "llm.verify", request_id=req.trace_id, engine_req=req.id,
                step=self._step_n, proposed=kd, accepted=n,
            )
            for j in range(n + 1):
                self._emit(req, int(out[i, j]), float(out_lp[i, j]))
                emitted += 1
                if req.finished:
                    # stop token / length cap hit inside the window: the
                    # rest of the acceptance is after-the-end, discard it
                    break
            if not req.finished:
                # ledger rollback: return the rejected tail's provisional
                # blocks (device k/v needs none — see cache.shrink_to)
                self.pool.shrink_to(req.id, req.seq_len)
        proposed = kd * len(active)
        self._spec_proposed += proposed
        self._spec_accepted += accepted
        step_rate = accepted / max(proposed, 1)
        if step_rate < self.cfg.spec_min_accept:
            # low acceptance: back off to plain decode, doubling the pause
            # while probes keep failing (EngineConfig docstring)
            self._spec_backoff = min(
                max(self._spec_backoff * 2, 2), self.cfg.spec_backoff_max
            )
            self._spec_skip = self._spec_backoff
        else:
            self._spec_backoff = 0
        m = _metrics()
        m["spec_proposed"].inc(proposed)
        m["spec_accepted"].inc(accepted)
        m["spec_accept_rate"].set(step_rate)
        m["tokens_per_step"].set(emitted)
        spec_info.update(
            k=kd,
            slots=len(active),
            proposed=proposed,
            accepted=accepted,
            emitted=emitted,
            draft_s=round(draft_s, 6),
            backoff=self._spec_backoff,
        )
        return True

    def _emit(self, req: Request, tok: int, logp: float = float("nan")) -> None:
        """Record one sampled token: stream it, capture its behavior
        logprob, update latency metrics, finish on stop token /
        max_tokens / model-length cap."""
        now = time.time()
        m = _metrics()
        if req.first_token_t is None:
            req.first_token_t = now
            m["ttft"].observe(now - req.arrival_t)
            _events.record(
                "llm.first_token", request_id=req.trace_id,
                engine_req=req.id, ttft_s=round(now - req.arrival_t, 6),
            )
        elif req.last_token_t is not None:
            m["itl"].observe(now - req.last_token_t)
        req.last_token_t = now
        req.out.append(tok)
        req.out_logprobs.append(logp)
        req.stream.put(("token", tok))
        self._tokens_generated += 1
        m["tokens"].inc()
        p = req.params
        if tok in p.stop_token_ids:
            self.scheduler.finish(req, FINISH_STOP)
        elif len(req.out) >= p.max_tokens or req.seq_len >= self.max_model_len:
            self.scheduler.finish(req, FINISH_LENGTH)

    def hbm_ledger(self) -> dict:
        """Live HBM byte accounting (the gauges' source of truth, also
        handy for tests/stats): params, pool total, and the seq-owned /
        cache-only / free partition of usable blocks × block bytes.

        Under ``tp > 1`` a ``per_device`` section attributes the same
        families per device: pool/params from the arrays actually
        resident (head shards + replicated copies — params per device
        EXCEEDS ``params_bytes / tp`` because replicated leaves are a
        full copy each), the block partition scaled by each device's
        local block bytes, the drafter (single-chip) on device 0.  The
        top-level numbers stay pool-wide — the ledger is host-global,
        block ids are not per-shard."""
        bb = self.pool.block_bytes
        counts = self.pool.ledger_counts()
        led = {
            "params_bytes": self._params_bytes,
            "pool_bytes": self.pool.device_bytes,
            "block_bytes": bb,
            "seq_bytes": counts["seq_owned"] * bb,
            "cache_bytes": counts["cache_only"] * bb,
            "free_bytes": counts["free"] * bb,
            "drafter_bytes": self._drafter_bytes,
            # utilization/free_blocks derived from the SAME snapshot —
            # one pool-lock acquisition serves the SLO gauge, stats()
            # and the ledger, and the numbers cannot disagree within one
            # response (separate property reads could interleave with an
            # allocation between the lock acquisitions)
            "free_blocks": counts["free"],
            "utilization": counts["seq_owned"]
            / max(self.pool.cfg.num_blocks - 1, 1),
        }
        if self.cfg.tp > 1:
            pool_dev = self.pool.per_device_bytes()
            par_dev = self.runner.per_device_param_bytes()
            nb = self.pool.cfg.num_blocks
            first = next(iter(pool_dev), None)
            led["per_device"] = {
                dev: {
                    "params_bytes": par_dev.get(dev, 0),
                    "pool_bytes": pool_b,
                    "seq_bytes": counts["seq_owned"] * (pool_b // nb),
                    "cache_bytes": counts["cache_only"] * (pool_b // nb),
                    "free_bytes": counts["free"] * (pool_b // nb),
                    "drafter_bytes": self._drafter_bytes if dev == first else 0,
                }
                for dev, pool_b in pool_dev.items()
            }
        return led

    def _publish_gauges(self) -> None:
        m = _metrics()
        m["running"].set(self.scheduler.num_running)
        m["waiting"].set(self.scheduler.num_waiting)
        led = self.hbm_ledger()
        m["kv_util"].set(led["utilization"])
        m["hbm_params"].set(led["params_bytes"])
        m["hbm_pool"].set(led["pool_bytes"])
        m["hbm_seq"].set(led["seq_bytes"])
        m["hbm_cache"].set(led["cache_bytes"])
        m["hbm_free"].set(led["free_bytes"])
        m["hbm_drafter"].set(led["drafter_bytes"])
        # tp>1: the same gauge NAMES split by a device tag (RL012 keeps
        # the name registry honest — tags are free); the untagged series
        # above stays pool-wide for every existing consumer
        for dev, row in led.get("per_device", {}).items():
            tags = {"device": dev}
            m["hbm_params"].set(row["params_bytes"], tags=tags)
            m["hbm_pool"].set(row["pool_bytes"], tags=tags)
            m["hbm_seq"].set(row["seq_bytes"], tags=tags)
            m["hbm_cache"].set(row["cache_bytes"], tags=tags)
            m["hbm_free"].set(row["free_bytes"], tags=tags)
            m["hbm_drafter"].set(row["drafter_bytes"], tags=tags)
        done = self.scheduler.finish_count
        if done > self._finished_published:
            m["finished"].inc(done - self._finished_published)
            self._finished_published = done
        # service-rate EWMA for deadline-aware admission: sampled at most
        # twice a second so one burst step doesn't whipsaw the estimate.
        # Only GENERATING windows update the average — an idle window is
        # not evidence of slowness, it is no evidence at all, so going
        # idle RESETS the rate (decaying it instead leaves a tiny stale
        # rate that would inflate estimates and spuriously shed the first
        # requests of the next burst).
        now = time.monotonic()
        t0, n0 = self._rate_mark
        if now - t0 >= 0.5:
            new_tokens = self._tokens_generated - n0
            if new_tokens > 0:
                inst = new_tokens / (now - t0)
                self._rate = (
                    inst if self._rate <= 0 else 0.7 * self._rate + 0.3 * inst
                )
            elif not self.scheduler.has_work():
                self._rate = 0.0
            # work pending but zero tokens this window (long prefill,
            # compile): keep the last measured rate
            self._rate_mark = (now, self._tokens_generated)
