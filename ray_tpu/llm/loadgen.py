"""Open-loop load harness for the served LLM path.

Closed-loop load tests (N workers, each waiting for its response before
sending the next) let the system set its own arrival rate — under
saturation the clients slow down WITH the server and queueing delay
hides (coordinated omission).  This harness is OPEN-LOOP: an arrival
curve fixes *when* every request fires before the run starts, each
request gets its own connection and coroutine, and a slow server just
accumulates in-flight streams — exactly what a production p99 sees.

Everything rides the REAL serving path: raw HTTP/1.1 over loopback
sockets into the asyncio proxy (chunked streaming responses, the
``x-request-id`` correlation header, ``x-deadline-s`` shed opt-in) — no
handle shortcuts, so proxy dispatch, router admission and stream
delivery are all inside the measurement.  Client-side timings (TTFT,
e2e, status) pair with the server-side phase ledgers (``util.phases``)
through the request id; ``obs.attribute_rows`` joins them into the
per-phase decomposition the ``LOADGEN_r01.json`` artifact reports.

Arrival curves: ``constant`` (fixed rate), ``poisson`` (exponential
gaps — real traffic's burstiness at the same average rate), ``ramp``
(linear rate growth — find the knee), ``burst`` (quiet base rate with a
simultaneous clump — recovery behavior).

The standard report (``run_report``) drives three arms against one
served app: healthy (sustained rate the engine can hold), overload
(arrival rate past capacity with a declared deadline — the shed plane
answers 429 and the report shows where the SURVIVORS' latency went),
and replica-kill (a SIGKILL mid-stream — failover resume shows up as
the ``failover`` phase, never as re-counted token time).  Driver-side
arithmetic is plain-Python sorts over small lists — no device values,
no per-loop host syncs (RL006 has nothing to flag here by design).

CLI::

    python -m ray_tpu.llm.loadgen --smoke -o LOADGEN_r01.json
"""

from __future__ import annotations

import argparse
import asyncio
import json
import math
import os
import random
import sys
import time
from typing import Callable, Optional

#: default request shape: small prompts, short completions — the harness
#: measures the serving plane, not the model
_PROMPT_BASE = [5, 6, 7, 8] * 3
_MAX_TOKENS = 8

CURVES = ("constant", "poisson", "ramp", "burst")


# ---------------------------------------------------------------------------
# arrival curves
# ---------------------------------------------------------------------------


def arrivals(
    curve: str,
    rate: float,
    duration_s: float,
    seed: int = 0,
    ramp_to: Optional[float] = None,
    burst_n: int = 0,
) -> list[float]:
    """Offsets (seconds from arm start) at which requests fire — computed
    up front so the schedule cannot react to server behavior (the open-
    loop property lives HERE)."""
    if rate <= 0 or duration_s <= 0:
        return []
    if curve == "constant":
        n = int(rate * duration_s)
        return [i / rate for i in range(n)]
    if curve == "poisson":
        rng = random.Random(seed)
        out, t = [], 0.0
        while True:
            t += rng.expovariate(rate)
            if t >= duration_s:
                return out
            out.append(t)
    if curve == "ramp":
        # linear rate(t) = rate + (ramp_to - rate) * t/D; fire request i
        # where the cumulative count crosses i (quadratic inverse)
        r1 = ramp_to if ramp_to is not None else rate * 3.0
        total = (rate + r1) / 2.0 * duration_s
        a = (r1 - rate) / (2.0 * duration_s)
        out = []
        for i in range(int(total)):
            if abs(a) < 1e-12:
                out.append(i / rate)
            else:
                t = (-rate + math.sqrt(rate * rate + 4.0 * a * i)) / (2.0 * a)
                out.append(min(t, duration_s))
        return out
    if curve == "burst":
        base = [i / rate for i in range(int(rate * duration_s))]
        mid = duration_s / 2.0
        # the clump lands together: same offset, thousands of coroutines
        return sorted(base + [mid] * burst_n)
    raise ValueError(f"unknown curve {curve!r}; expected one of {CURVES}")


# ---------------------------------------------------------------------------
# the client (raw HTTP/1.1, streaming-aware)
# ---------------------------------------------------------------------------


async def _one_stream(
    port: int, app: str, payload: dict, deadline_s: Optional[float] = None
) -> dict:
    """One request over its own connection: send, read the streamed
    response to EOF, record status / x-request-id / TTFT / e2e."""
    rec: dict = {"t_send": time.time()}
    writer = None
    try:
        reader, writer = await asyncio.open_connection("127.0.0.1", port)
        body = json.dumps(payload).encode()
        head = (
            f"POST /{app} HTTP/1.1\r\nhost: loadgen\r\n"
            f"content-type: application/json\r\n"
            f"content-length: {len(body)}\r\nconnection: close\r\n"
        )
        if deadline_s is not None:
            head += f"x-deadline-s: {deadline_s}\r\n"
        writer.write(head.encode() + b"\r\n" + body)
        await writer.drain()
        raw_head = await reader.readuntil(b"\r\n\r\n")
        rec["status"] = int(raw_head.split(b" ", 2)[1])
        for line in raw_head.split(b"\r\n")[1:]:
            if line.lower().startswith(b"x-request-id:"):
                rec["request_id"] = line.split(b":", 1)[1].strip().decode()
        t_first = None
        while True:
            data = await reader.read(1 << 16)
            if not data:
                break
            if t_first is None:
                t_first = time.time()
        now = time.time()
        if t_first is not None and rec["status"] == 200:
            rec["ttft_s"] = round(t_first - rec["t_send"], 6)
        rec["e2e_s"] = round(now - rec["t_send"], 6)
    except Exception as e:  # noqa: BLE001 — a failed request is a data point
        rec.setdefault("status", 0)
        rec["error"] = repr(e)
        rec["e2e_s"] = round(time.time() - rec["t_send"], 6)
    finally:
        if writer is not None:
            try:
                writer.close()
            except Exception:
                pass
    return rec


async def _run_curve_async(
    port: int,
    app: str,
    offsets: list[float],
    make_payload: Callable[[int], dict],
    deadline_s: Optional[float] = None,
) -> list[dict]:
    t0 = time.time()

    async def fire(i: int, off: float) -> dict:
        delay = (t0 + off) - time.time()
        if delay > 0:
            await asyncio.sleep(delay)
        rec = await _one_stream(port, app, make_payload(i), deadline_s)
        rec["offset_s"] = round(off, 4)
        return rec

    tasks = [
        asyncio.ensure_future(fire(i, off)) for i, off in enumerate(offsets)
    ]
    return list(await asyncio.gather(*tasks))


def run_curve(
    port: int,
    app: str,
    offsets: list[float],
    make_payload: Callable[[int], dict],
    deadline_s: Optional[float] = None,
) -> list[dict]:
    """Drive one arrival curve against a served app; one record per
    request (open-loop: every request fires at its scheduled offset no
    matter how the previous ones are doing)."""
    return asyncio.run(
        _run_curve_async(port, app, offsets, make_payload, deadline_s)
    )


# ---------------------------------------------------------------------------
# client-side summaries
# ---------------------------------------------------------------------------


def _pcts(vals: list[float]) -> dict:
    vals = sorted(vals)
    n = len(vals)

    def q(p: float):
        return round(vals[min(n - 1, int(round(p * (n - 1))))], 6) if n else None

    return {"count": n, "p50": q(0.50), "p95": q(0.95), "p99": q(0.99)}


def summarize_client(records: list[dict], duration_s: float) -> dict:
    """What the CLIENTS saw: achieved rate, status mix, shed rate, and
    e2e/TTFT percentiles over the successful streams."""
    ok = [r for r in records if r.get("status") == 200]
    shed = [r for r in records if r.get("status") == 429]
    errors = [r for r in records if r.get("status") not in (200, 429)]
    return {
        "requests": len(records),
        "duration_s": round(duration_s, 3),
        "offered_rate_rps": round(len(records) / duration_s, 2)
        if duration_s > 0 else None,
        "ok": len(ok),
        "shed_429": len(shed),
        "shed_rate": round(len(shed) / len(records), 4) if records else 0.0,
        "errors": len(errors),
        "e2e_s": _pcts([r["e2e_s"] for r in ok if "e2e_s" in r]),
        "ttft_s": _pcts([r["ttft_s"] for r in ok if "ttft_s" in r]),
    }


# ---------------------------------------------------------------------------
# the standard three-arm report (LOADGEN_r01.json)
# ---------------------------------------------------------------------------


def _drain_phase_events() -> list[dict]:
    """Server-side phase events drained through the head NOW — called per
    arm so a later arm's traffic can't evict an earlier arm's ledgers
    from the bounded rings.  Crash-flushed files are merged in for
    workers that died by SIGTERM; a SIGKILLed replica's ring is simply
    gone (requests it finished pre-kill lose attribution — the per-arm
    ``attributed_frac`` makes that loss visible instead of silent)."""
    from ray_tpu._private import events as ev

    evs = list(ev.collect_cluster_events()) + ev.load_crash_files()
    return [
        e for e in evs if str(e.get("type", "")).startswith("llm.phase.")
    ]


def _attribution_for(evs: list[dict], rids: set, eps: float) -> dict:
    from ray_tpu.obs import attribute_rows, attribution_report

    rows = [
        r for r in attribute_rows(evs) if r["request_id"] in rids
    ]
    return attribution_report(rows, top=5, eps=eps)


def _kill_active_replica_soon(delay_s: float, dep: str) -> "object":
    """Background thread: after ``delay_s``, SIGKILL the replica whose
    engine is actively generating (the chaos-suite idiom) so the arm's
    in-flight streams exercise mid-stream failover resume."""
    import signal
    import threading

    import ray_tpu
    from ray_tpu._private import chaos

    result: dict = {}

    def kill():
        time.sleep(delay_s)
        controller = ray_tpu.get_actor("SERVE_CONTROLLER")
        deadline = time.time() + 15.0
        while time.time() < deadline:
            _, replicas, _ = ray_tpu.get(
                controller.get_replicas.remote(dep), timeout=10
            )
            for r in replicas:
                st = ray_tpu.get(
                    r.handle_request.remote("stats", (), {}), timeout=10
                )
                if st["running"] > 0:
                    pid = chaos.pid_of_actor(r._actor_id.hex())
                    if pid is not None:
                        os.kill(pid, signal.SIGKILL)
                        result["pid"] = pid
                        return
            time.sleep(0.01)

    t = threading.Thread(target=kill, name="loadgen-killer", daemon=True)
    t.start()
    return t, result


def run_report(
    smoke: bool = False,
    kill: bool = True,
    eps: float = 0.05,
    seed: int = 0,
) -> dict:
    """Boot a tiny served LLM app and drive the three standard arms
    (healthy / overload / replica-kill), returning the LOADGEN report:
    client-side percentiles + server-side phase attribution per arm, and
    the overall phase-sum identity. The caller owns writing the JSON."""
    import ray_tpu
    from ray_tpu import serve
    from ray_tpu.llm.engine import EngineConfig
    from ray_tpu.models.gptj import GPTJConfig
    from ray_tpu.serve.llm import build_llm_app

    # phase ledgers land in bounded per-process rings; a load run emits
    # per-token events far faster than the default capacity holds
    os.environ.setdefault("RAY_TPU_EVENTS_CAPACITY", "65536")
    # fresh crash-flush dir per run unless the caller (CI) directs one —
    # stale flushes from earlier runs must not leak into attribution
    import tempfile

    os.environ.setdefault(
        "RAY_TPU_EVENTS_DIR", tempfile.mkdtemp(prefix="loadgen-events-")
    )

    tiny = GPTJConfig(
        vocab_size=128, seq_len=64, d_model=32, n_layers=2, n_heads=2,
        rotary_dim=8, dtype="float32", remat=False, attn_impl="xla",
        fused_loss=False,
    )
    ecfg = EngineConfig(
        max_slots=4, num_blocks=128, block_size=4, max_blocks_per_seq=16,
        prefill_chunk=8,
    )
    scale = 0.4 if smoke else 1.0
    t_wall = time.time()
    ray_tpu.init(num_cpus=8, num_tpus=0)
    report: dict = {
        "smoke": smoke,
        "eps": eps,
        "arms": {},
    }
    try:
        app = build_llm_app(
            model="gptj", model_cfg=tiny, engine_config=ecfg,
            num_replicas=2, max_ongoing_requests=64,
        )
        serve.run(app, name="llm", http=True, http_port=0)
        controller = ray_tpu.get_actor("SERVE_CONTROLLER")
        port = ray_tpu.get(controller.get_proxy_port.remote(), timeout=30)

        def payload(i: int) -> dict:
            # half the fleet shares one prompt (prefix-cache hits land in
            # `admit`), half varies (real prefill)
            prompt = (
                _PROMPT_BASE
                if i % 2 == 0
                else [(i * 7 + j) % 128 for j in range(len(_PROMPT_BASE))]
            )
            return {
                "prompt": prompt,
                "max_tokens": _MAX_TOKENS,
                "temperature": 0.0,
                "seed": i,
            }

        all_rows_ok = 0
        all_rows = 0

        def run_arm(
            name: str,
            offsets: list[float],
            deadline_s: Optional[float] = None,
            overload_payload: bool = False,
            killer: Optional[float] = None,
        ) -> None:
            nonlocal all_rows, all_rows_ok
            mk = payload
            if overload_payload:
                # the engine-side shed gate reads deadline_s from the
                # payload and trips when PROMISED tokens ÷ observed service
                # rate exceeds it — so the overload arm promises long
                # completions against a deadline the backlog cannot meet
                # (the header drives the proxy capacity probe separately)
                def mk(i: int, _p=payload):
                    d = _p(i)
                    d["max_tokens"] = _MAX_TOKENS * 4
                    d["deadline_s"] = deadline_s
                    return d
            k = None
            if killer is not None:
                k = _kill_active_replica_soon(killer, "llm_LLMDeployment")
            t0 = time.time()
            recs = run_curve(port, "llm", offsets, mk, deadline_s)
            dur = time.time() - t0
            if k is not None:
                k[0].join(timeout=20.0)
            evs = _drain_phase_events()
            rids = {r["request_id"] for r in recs if r.get("request_id")}
            attr = _attribution_for(evs, rids, eps)
            client = summarize_client(recs, dur)
            arm = {
                "curve_n": len(offsets),
                "client": client,
                "attribution": attr,
                # fraction of successful streams that kept their server-side
                # ledger (a SIGKILLed replica's ring dies with it)
                "attributed_frac": round(
                    attr["n_requests"] / client["ok"], 4
                ) if client["ok"] else None,
            }
            if k is not None:
                arm["killed_pid"] = k[1].get("pid")
            report["arms"][name] = arm
            if attr["n_requests"]:
                all_rows += attr["n_requests"]
                all_rows_ok += attr["within_eps"]

        # healthy: a Poisson arrival stream the engine sustains
        run_arm(
            "healthy",
            arrivals("poisson", rate=20 * scale, duration_s=6 * scale,
                     seed=seed),
        )
        # overload: offered rate past capacity, every request declaring a
        # deadline its backlog cannot meet — the shed plane answers 429 and
        # the survivors' decomposition shows where the latency went (queue)
        run_arm(
            "overload",
            arrivals("constant", rate=80 * scale, duration_s=4 * scale),
            deadline_s=0.3,
            overload_payload=True,
        )
        if kill:
            # replica-kill: SIGKILL mid-stream; resumed requests report a
            # `failover` component instead of re-counting delivered tokens
            run_arm(
                "replica_kill",
                arrivals("constant", rate=8 * scale, duration_s=6 * scale),
                killer=1.5 * scale,
            )
        report["identity"] = {
            "eps": eps,
            "attributed_requests": all_rows,
            "within_eps": all_rows_ok,
            "within_eps_frac": (all_rows_ok / all_rows) if all_rows else None,
        }
        report["wall_s"] = round(time.time() - t_wall, 1)
        return report
    finally:
        try:
            serve.shutdown()
        finally:
            ray_tpu.shutdown()


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m ray_tpu.llm.loadgen",
        description="open-loop load harness over the served LLM HTTP path",
    )
    ap.add_argument("-o", "--output", default="LOADGEN_r01.json")
    ap.add_argument("--smoke", action="store_true",
                    help="scaled-down curves (CI)")
    ap.add_argument("--no-kill", action="store_true",
                    help="skip the replica-kill arm")
    ap.add_argument("--eps", type=float, default=0.05,
                    help="phase-sum identity tolerance")
    ap.add_argument("--assert-identity", action="store_true",
                    help="exit non-zero unless ≥99%% of attributed "
                    "requests satisfy the phase-sum identity")
    args = ap.parse_args(argv)
    report = run_report(smoke=args.smoke, kill=not args.no_kill, eps=args.eps)
    with open(args.output, "w") as fh:
        json.dump(report, fh, indent=1, sort_keys=True)
    ident = report["identity"]
    print(
        f"loadgen: wrote {args.output} — "
        + " ".join(
            f"{name}: ok={arm['client']['ok']}/{arm['client']['requests']}"
            f" shed={arm['client']['shed_429']}"
            f" p99={arm['client']['e2e_s'].get('p99')}s"
            for name, arm in report["arms"].items()
        )
    )
    print(
        f"phase-sum identity: {ident['within_eps']}/"
        f"{ident['attributed_requests']} within ε={ident['eps']:.0%}"
    )
    if args.assert_identity:
        frac = ident["within_eps_frac"]
        if frac is None or frac < 0.99:
            print(f"IDENTITY FAILED: {frac}", file=sys.stderr)
            return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
