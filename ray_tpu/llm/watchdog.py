"""Engine watchdog: stall detection, wedge-proof reaping, KV leak audit.

The step loop is the engine's single point of failure: a wedged jitted
call (device hang, runaway compile, deadlocked host callback) freezes
every stream at once while holding the engine lock — so nothing that
shares that lock can even *diagnose* the freeze.  The watchdog is a small
per-engine daemon thread built around that constraint:

* **stall detection** — the engine publishes a lock-free liveness beat
  (``LLMEngine._beat``: last completed step tick + pending work).  Work
  pending with no step progress for ``stall_deadline_s`` is a stall: one
  ``llm.watchdog.stall`` flight-recorder event per episode, the
  ``llm_watchdog_stalls`` counter, and the ``llm_watchdog_step_age_s``
  gauge (0 while idle/healthy) that the default ``engine-stall`` SLO rule
  (``util.slo``) pages on.
* **reaping** — cancelled and deadline-blown requests are reaped every
  tick.  With the engine lock (bounded acquire) this is the full
  scheduler reap, freeing slots and KV blocks even when no caller is
  driving ``step()``.  When the lock can't be had — the wedge case — the
  watchdog falls back to unblocking the CONSUMERS: it puts the ``done``
  sentinel on each doomed request's stream queue (thread-safe, lockless)
  and flags the request cancelled so the scheduler finishes it properly
  if the step loop ever revives.  A stream caller never hangs on a
  request the deadline already killed.
* **KV leak audit** — ``KVBlockPool.audit()`` checks the refcounted
  free-list ledger invariant (free + exclusively-owned +
  shared-with-refcount + cache-only still partition the usable blocks;
  no duplicate, out-of-range, or ref-inconsistent ids) under the pool
  lock alone, and ``PrefixCache.audit()`` cross-checks the radix tree
  against the pool's cache-held set (no dangling tree references after
  eviction, no retained block without a node); with the engine lock the
  watchdog also cross-checks that every block owner is a live
  slot-holding request.  A violation is a ``llm.watchdog.leak`` event +
  counter — leaked blocks are the silent capacity death of a
  long-running replica.

``EngineStalledError`` (raised by ``LLMEngine.stream_tokens`` on token
timeout) carries the same lock-free diagnosis so a caller's timeout names
the cause — wedged step vs saturated queue vs drained pool — instead of
a bare TimeoutError.
"""

from __future__ import annotations

import threading
import time
from typing import Optional

from ray_tpu._private import events as _events
from ray_tpu._private.log_util import warn_throttled
from ray_tpu.llm.scheduler import FINISH_CANCELLED, FINISH_DEADLINE

#: watchdog metric family — RL012 cross-checks this registry against the
#: constructors in ``_metrics()`` and the observability docs
METRIC_NAMES = (
    "llm_watchdog_step_age_s",
    "llm_watchdog_stalls",
    "llm_watchdog_reaped",
    "llm_watchdog_leaks",
    "llm_watchdog_audit_ok",
)

_WD_METRICS = None
_WD_LOCK = threading.Lock()


def _metrics() -> dict:
    global _WD_METRICS
    if _WD_METRICS is not None:
        return _WD_METRICS
    with _WD_LOCK:
        if _WD_METRICS is not None:
            return _WD_METRICS
        from ray_tpu.util.metrics import Counter, Gauge

        _WD_METRICS = {
            "step_age": Gauge(
                "llm_watchdog_step_age_s",
                "age of the last engine step while work is pending (0 = "
                "idle or healthy); the engine-stall SLO rule reads this",
            ),
            "stalls": Counter(
                "llm_watchdog_stalls", "stall episodes detected (wedged step loop)"
            ),
            "reaped": Counter(
                "llm_watchdog_reaped",
                "cancelled/deadline-blown requests reaped by the watchdog",
            ),
            "leaks": Counter(
                "llm_watchdog_leaks", "KV block-pool ledger audit failures"
            ),
            "audit_ok": Gauge(
                "llm_watchdog_audit_ok", "1 while the last KV-pool audit passed"
            ),
        }
    return _WD_METRICS


class EngineStalledError(TimeoutError):
    """``stream_tokens`` timed out, with the engine's stall diagnosis
    attached (gathered lock-free — valid even while the step loop is
    wedged holding the engine lock)."""

    def __init__(
        self,
        msg: str,
        *,
        last_step_age_s: float = 0.0,
        queue_depth: int = 0,
        kv_utilization: float = 0.0,
    ):
        self.last_step_age_s = last_step_age_s
        self.queue_depth = queue_depth
        self.kv_utilization = kv_utilization
        super().__init__(
            f"{msg} [last step {last_step_age_s:.1f}s ago, "
            f"queue_depth={queue_depth}, kv_utilization={kv_utilization:.2f}]"
        )

    def __reduce__(self):
        # rebuild through kwargs so the error pickles across actor hops
        return (
            _rebuild_stalled,
            (
                self.args[0] if self.args else "",
                self.last_step_age_s,
                self.queue_depth,
                self.kv_utilization,
            ),
        )


def _rebuild_stalled(msg, age, depth, kv):
    err = EngineStalledError.__new__(EngineStalledError)
    TimeoutError.__init__(err, msg)
    err.last_step_age_s, err.queue_depth, err.kv_utilization = age, depth, kv
    return err


class EngineWatchdog:
    """One monitor thread per engine (``LLMEngine.start_watchdog``)."""

    def __init__(
        self,
        engine,
        stall_deadline_s: float = 30.0,
        interval_s: float = 1.0,
        lock_timeout_s: float = 0.1,
    ):
        self.engine = engine
        self.stall_deadline_s = stall_deadline_s
        self.interval_s = interval_s
        self.lock_timeout_s = lock_timeout_s
        self.stall_count = 0
        self.leak_count = 0
        self._stalled = False        # inside a stall episode (event fired)
        self._leaked = False         # inside a leak episode (event fired)
        self._unblocked: set[str] = set()  # emergency-reaped request ids
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> "EngineWatchdog":
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._run, name="llm-engine-watchdog", daemon=True
            )
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)

    def is_alive(self) -> bool:
        return self._thread is not None and self._thread.is_alive()

    def _run(self) -> None:
        while not self._stop.is_set():
            try:
                self.check_once()
            except Exception as e:
                warn_throttled("llm watchdog: check", e)
            self._stop.wait(self.interval_s)

    # -- one tick (also the unit-test surface) -----------------------------

    def check_once(self) -> dict:
        """Run one watchdog pass; returns what it saw/did (golden-testable
        without a thread): ``{stalled, step_age_s, pending, reaped,
        unblocked, audit}``. Staleness comes from the engine's monotonic
        beat — tests pin time by setting ``engine._beat`` directly."""
        m = _metrics()
        age, pending = self.engine.progress()
        stalled = pending > 0 and age >= self.stall_deadline_s
        m["step_age"].set(age if pending > 0 else 0.0)
        if stalled and not self._stalled:
            # one event per episode, not per tick — the recorder ring is
            # shared and a day-long wedge must not wrap it
            self.stall_count += 1
            m["stalls"].inc()
            _events.record(
                "llm.watchdog.stall", source="watchdog",
                last_step_age_s=round(age, 3), queue_depth=pending,
                kv_utilization=round(self.engine.pool.utilization(), 4),
                deadline_s=self.stall_deadline_s,
            )
        self._stalled = stalled

        reaped = unblocked = 0
        audit: dict = {}
        got_lock = self.engine._lock.acquire(timeout=self.lock_timeout_s)
        if got_lock:
            try:
                reaped = self._reap_locked()
                audit = self._audit_locked()
            finally:
                self.engine._lock.release()
        else:
            # the wedge case: the step loop owns the lock and is not
            # moving — unblock doomed requests' CONSUMERS without touching
            # scheduler state (pool-only audit still runs: its lock is
            # never held across device calls, and the prefix-tree
            # cross-check needs only the cache + pool locks)
            unblocked = self._unblock_doomed()
            audit = self._check_audit(
                self.engine.pool.audit(), orphans=(),
                cache_audit=self._cache_audit(),
            )
        if reaped or unblocked:
            m["reaped"].inc(reaped + unblocked)
        return {
            "stalled": stalled,
            "step_age_s": age,
            "pending": pending,
            "reaped": reaped,
            "unblocked": unblocked,
            "audit": audit,
        }

    # -- internals ---------------------------------------------------------

    def _reap_locked(self) -> int:
        """Full reap under the engine lock: finish cancelled/deadline-blown
        requests through the scheduler (slots and blocks come back) even
        when nobody is driving ``step()``. The predicate lives in
        ``LLMEngine._reap`` — one copy, shared with the step loop."""
        eng = self.engine
        n = eng._reap()
        if n:
            eng._requests = {
                k: r for k, r in eng._requests.items() if not r.finished
            }
            _events.record("llm.watchdog.reap", n=n, mode="locked")
        return n

    def _unblock_doomed(self) -> int:
        """Lockless fallback: end the STREAMS of cancelled/deadline-blown
        requests so consumers stop waiting on a wedged engine. Scheduler
        state is deliberately untouched (no lock) — both conditions are
        permanent, so the engine's own ``_reap`` finishes these requests
        with the SAME reason if the step loop ever revives; flagging a
        deadline-blown request cancelled here would misreport its
        finish_reason there."""
        try:
            reqs = list(self.engine._requests.values())
        except RuntimeError:  # dict mutated mid-iteration: try next tick
            return 0
        now = time.time()
        n = 0
        for req in reqs:
            if req.finished or req.id in self._unblocked:
                continue
            if req.cancelled.is_set():
                reason = FINISH_CANCELLED
            elif req.deadline is not None and now >= req.deadline:
                reason = FINISH_DEADLINE
            else:
                continue
            req.stream.put(("done", reason))
            self._unblocked.add(req.id)
            n += 1
        if n:
            _events.record("llm.watchdog.reap", n=n, mode="emergency")
        return n

    def _cache_audit(self):
        """Prefix-tree ↔ pool cross-check (``PrefixCache.audit``): no
        dangling tree references after eviction, no cache-held pool block
        without a node.  None when the engine runs without a cache."""
        cache = getattr(self.engine, "prefix_cache", None)
        return cache.audit() if cache is not None else None

    def _audit_locked(self) -> dict:
        """Pool-ledger audit plus the owner cross-check that needs the
        engine lock: every block owner must be a request holding a slot
        (waiting/preempted requests own nothing)."""
        pool_audit = self.engine.pool.audit()
        slot_ids = {
            r.id for r in self.engine.scheduler.slots if r is not None
        }
        orphans = tuple(o for o in pool_audit["owners"] if o not in slot_ids)
        return self._check_audit(pool_audit, orphans, self._cache_audit())

    def _check_audit(
        self, pool_audit: dict, orphans: tuple, cache_audit=None
    ) -> dict:
        m = _metrics()
        cache_ok = cache_audit is None or cache_audit["ok"]
        ok = pool_audit["ok"] and not orphans and cache_ok
        result = dict(pool_audit, orphans=list(orphans), ok=ok)
        if cache_audit is not None:
            result["prefix_cache"] = cache_audit
        m["audit_ok"].set(1.0 if ok else 0.0)
        if not ok and not self._leaked:
            self.leak_count += 1
            m["leaks"].inc()
            _events.record(
                "llm.watchdog.leak",
                missing=pool_audit.get("missing", 0),
                duplicates=pool_audit.get("duplicates", False),
                out_of_range=pool_audit.get("out_of_range", 0),
                ref_errors=pool_audit.get("ref_errors", 0),
                orphans=list(orphans)[:8],
                cache_dangling=(
                    len(cache_audit["dangling"]) if cache_audit else 0
                ),
                cache_unindexed=(
                    len(cache_audit["unindexed"]) if cache_audit else 0
                ),
            )
        self._leaked = not ok
        return result
