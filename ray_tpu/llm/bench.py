"""LLM engine benchmarks: continuous batching + speculative decoding.

``python -m ray_tpu.llm.bench`` prints TWO JSON lines:

* ``llm_continuous_batching_tokens_per_sec`` — aggregate decode tokens/s
  of the continuous-batching engine against the same workload run as
  sequential static-batch ``gptj_decode`` calls (the pre-``ray_tpu.llm``
  serving story), under staggered arrivals so the engine's advantage —
  new requests join the running batch mid-flight — is what gets measured.
* ``llm_speculative_decode_speedup`` — the spec_k=3 n-gram-drafted engine
  against the non-speculative engine on two workloads: a REPETITIVE one
  (patterned prompts whose greedy continuations go periodic early — the
  prompt-lookup drafter's home turf) and an ADVERSARIAL one (random
  prompts, short outputs: acceptance near zero, so what's measured is the
  backoff bound on regression).  Both paths must produce byte-identical
  greedy tokens — asserted, or the comparison is comparing different
  work.
* ``llm_prefix_cache_warm_ttft_speedup`` — the shared-system-prompt
  workload (N requests with a common 256-token prefix, distinct
  suffixes) through the prefix cache vs the same engine with the cache
  off: prefill-tokens-computed and warm-request TTFT are the headline
  numbers (the production chat regime the cache targets); outputs must
  be token-identical across the two arms — asserted.
* ``llm_multichip_tp_tokens_per_sec`` (``--only multichip``) — the
  tensor-parallel engine (``llm.multichip``, ``EngineConfig(tp=N)``)
  against the single-chip engine on the same workload: tokens/s, mean
  TTFT and per-device KV-pool bytes per mesh size, token identity
  asserted between every arm.  On the CPU host-device substrate the
  ratio measures shard_map/psum OVERHEAD (there is no real parallel
  hardware underneath — expect < 1x); on real TPUs the same pairing
  measures the multi-chip speedup.  The ``MULTICHIP_r0x`` CI artifact
  records these numbers.
* ``llm_loadgen_healthy_p99_s`` (``--only loadgen``) — the open-loop
  load harness (``llm.loadgen``): boots a served app and drives the
  three standard arms (healthy / overload / replica-kill), reporting the
  healthy-arm client p99 with the full per-phase attribution report in
  ``detail``.  Excluded from ``--only all`` — it boots a serve cluster
  and belongs to its own CI job (``loadgen-smoke``).

Sized to run on CPU in seconds (the same comparison holds on TPU with
the real model; the ratio is what travels).  ``--smoke`` shrinks the
workloads for CI.  Invoked by the top-level ``bench.py`` as a subprocess
so a failure never costs the headline metric.
"""

from __future__ import annotations

import json
import time

N_REQUESTS = 8
PROMPT_LEN = 8
MAX_TOKENS = 32
ARRIVAL_GAP_S = 0.01
WINDOWS = 2  # best-of per side: robust to one scheduler stall on a shared box


def _model():
    import jax

    from ray_tpu.models.gptj import GPTJConfig, gptj_init

    cfg = GPTJConfig(
        vocab_size=256, seq_len=128, d_model=128, n_layers=4, n_heads=4,
        rotary_dim=16, dtype="float32", remat=False, attn_impl="xla",
        fused_loss=False,
    )
    return cfg, gptj_init(jax.random.PRNGKey(0), cfg)


def run_bench() -> dict:
    import jax
    import jax.numpy as jnp
    import numpy as np

    from ray_tpu.llm import EngineConfig, LLMEngine, SamplingParams
    from ray_tpu.models.gptj import gptj_decode

    cfg, params = _model()
    rng = np.random.RandomState(0)
    prompts = [
        list(rng.randint(0, cfg.vocab_size, PROMPT_LEN)) for _ in range(N_REQUESTS)
    ]
    arrivals = [i * ARRIVAL_GAP_S for i in range(N_REQUESTS)]
    total_tokens = N_REQUESTS * MAX_TOKENS

    # -- static baseline: sequential gptj_decode per request ---------------
    decode = jax.jit(
        lambda p, t: gptj_decode(cfg, p, t, MAX_TOKENS), static_argnums=()
    )
    warm = decode(params, jnp.asarray([prompts[0]], jnp.int32))
    int(warm[0, -1])  # compile + transfer barrier before timing

    def run_static():
        t0 = time.perf_counter()
        out = []
        for arr, prompt in zip(arrivals, prompts):
            now = time.perf_counter() - t0
            if now < arr:
                time.sleep(arr - now)
            toks = decode(params, jnp.asarray([prompt], jnp.int32))
            # the per-request host sync IS the static baseline being
            # measured: sequential whole-completion decode was the
            # pre-ray_tpu.llm serving story this bench compares against
            out.append(list(np.asarray(toks)[0, PROMPT_LEN:]))  # raylint: disable=RL006
        return time.perf_counter() - t0, out

    static_wall, static_out = min(
        (run_static() for _ in range(WINDOWS)), key=lambda r: r[0]
    )
    static_tps = total_tokens / static_wall

    # -- continuous engine -------------------------------------------------
    # table width sized to the workload: decode cost scales with the table
    # width, not the live length, so an over-provisioned table would tax
    # every step
    blocks_per_seq = -(-(PROMPT_LEN + MAX_TOKENS) // 8)
    engine = LLMEngine(
        cfg, params,
        EngineConfig(
            max_slots=N_REQUESTS, block_size=8,
            num_blocks=N_REQUESTS * blocks_per_seq + 2,
            max_blocks_per_seq=blocks_per_seq, prefill_chunk=PROMPT_LEN,
        ),
    )
    engine.warmup()  # compile the step jits outside the timed windows

    def run_continuous():
        t0 = time.perf_counter()
        reqs = []
        pending = list(zip(arrivals, prompts))
        while pending or not all(r.finished for r in reqs):
            now = time.perf_counter() - t0
            while pending and pending[0][0] <= now:
                _, prompt = pending.pop(0)
                reqs.append(
                    engine.submit(prompt, SamplingParams(max_tokens=MAX_TOKENS))
                )
            if not engine.step():
                time.sleep(0.0005)
        return time.perf_counter() - t0, [r.out for r in reqs]

    cont_wall, cont_out = min(
        (run_continuous() for _ in range(WINDOWS)), key=lambda r: r[0]
    )
    cont_tps = total_tokens / cont_wall

    # greedy determinism: both paths must produce identical tokens, or the
    # throughput comparison is comparing different work
    assert cont_out == static_out, "continuous/static token mismatch"

    return {
        "metric": "llm_continuous_batching_tokens_per_sec",
        "value": round(cont_tps, 1),
        "unit": "tokens/s",
        "vs_baseline": round(cont_tps / static_tps, 3),
        "detail": {
            "static_tokens_per_sec": round(static_tps, 1),
            "requests": N_REQUESTS,
            "max_tokens": MAX_TOKENS,
            "arrival_gap_s": ARRIVAL_GAP_S,
            "static_wall_s": round(static_wall, 3),
            "continuous_wall_s": round(cont_wall, 3),
            "preemptions": engine.stats()["preemptions"],
        },
    }


# -- speculative decoding ----------------------------------------------------

SPEC_K = 3
SPEC_SLOTS = 4
SPEC_PROMPT_LEN = 16
# prompt seeds chosen (scanned offline) so the tiny model's greedy
# continuation of the patterned prompt goes periodic within ~8 tokens —
# the structured/templated-output regime prompt-lookup drafting targets
REPETITIVE_SEEDS = (1, 13, 22, 36)
ADVERSARIAL_SEEDS = (100, 101, 102, 103)


def _spec_model():
    import jax

    from ray_tpu.models.gptj import GPTJConfig, gptj_init

    cfg = GPTJConfig(
        vocab_size=256, seq_len=256, d_model=128, n_layers=4, n_heads=4,
        rotary_dim=16, dtype="float32", remat=False, attn_impl="xla",
        fused_loss=False,
    )
    return cfg, gptj_init(jax.random.PRNGKey(1), cfg)


def run_spec_bench(smoke: bool = False) -> dict:
    import numpy as np

    from ray_tpu.llm import EngineConfig, LLMEngine, SamplingParams

    cfg, params = _spec_model()
    windows = 1 if smoke else WINDOWS
    mt_rep = 24 if smoke else 64
    # the adversarial run stays 16 tokens even in smoke: shorter runs sit
    # entirely inside the backoff ramp and overstate the regression
    mt_adv = 16

    def patterned(seed):
        pat = list(np.random.RandomState(seed).randint(0, cfg.vocab_size, 4))
        return (pat * 8)[:SPEC_PROMPT_LEN]

    def random_prompt(seed):
        return list(
            np.random.RandomState(seed).randint(0, cfg.vocab_size, SPEC_PROMPT_LEN)
        )

    rep_prompts = [patterned(s) for s in REPETITIVE_SEEDS]
    adv_prompts = [random_prompt(s) for s in ADVERSARIAL_SEEDS]
    mt_max = max(mt_rep, mt_adv)

    def make_engine(spec_k):
        bps = -(-(SPEC_PROMPT_LEN + mt_max + SPEC_K + 1) // 8)
        return LLMEngine(
            cfg, params,
            EngineConfig(
                max_slots=SPEC_SLOTS, block_size=8,
                num_blocks=SPEC_SLOTS * bps + 2, max_blocks_per_seq=bps,
                prefill_chunk=SPEC_PROMPT_LEN, spec_k=spec_k,
            ),
        )

    def run(engine, prompts, mt):
        reqs = [engine.submit(p, SamplingParams(max_tokens=mt)) for p in prompts]
        t0 = time.perf_counter()
        while not all(r.finished for r in reqs):
            engine.step()
        return time.perf_counter() - t0, [r.out for r in reqs]

    base = make_engine(0)
    base.warmup()  # compile outside the timed windows
    spec = make_engine(SPEC_K)
    spec.warmup()  # both step paths: verify AND the backoff fallback

    results = {}
    for name, prompts, mt in (
        ("repetitive", rep_prompts, mt_rep),
        ("adversarial", adv_prompts, mt_adv),
    ):
        bt, bout = min(
            (run(base, prompts, mt) for _ in range(windows)), key=lambda r: r[0]
        )
        s0 = spec.stats()
        st, sout = min(
            (run(spec, prompts, mt) for _ in range(windows)), key=lambda r: r[0]
        )
        s1 = spec.stats()
        # greedy speculative decode must be token-identical to the plain
        # engine, or the throughput comparison is comparing different work
        assert sout == bout, f"spec/non-spec token mismatch on {name}"
        total = len(prompts) * mt
        results[name] = {
            "baseline_tokens_per_sec": round(total / bt, 1),
            "spec_tokens_per_sec": round(total / st, 1),
            "speedup": round(bt / st, 3),
            "acceptance_rate": round(
                (s1["spec_accepted"] - s0["spec_accepted"])
                / max(s1["spec_proposed"] - s0["spec_proposed"], 1),
                3,
            ),
            "drafter_overhead_s": round(
                s1["spec_draft_seconds"] - s0["spec_draft_seconds"], 4
            ),
        }
    return {
        "metric": "llm_speculative_decode_speedup",
        "value": results["repetitive"]["spec_tokens_per_sec"],
        "unit": "tokens/s",
        "vs_baseline": results["repetitive"]["speedup"],
        "detail": {
            **results,
            "drafter": "ngram",
            "spec_k": SPEC_K,
            "requests": SPEC_SLOTS,
            "smoke": smoke,
        },
    }


# -- cross-request prefix cache ----------------------------------------------

PREFIX_SHARED_LEN = 256   # the common system-prompt/few-shot head
PREFIX_SUFFIX_LEN = 16    # per-request distinct tail
PREFIX_N = 8
PREFIX_MAX_TOKENS = 8
PREFIX_BLOCK = 16


def run_prefix_bench(smoke: bool = False) -> dict:
    """Shared-system-prompt workload: request 0 is COLD (it populates the
    radix tree), requests 1..N-1 are WARM (their 256-token head matches).
    Requests run one at a time so each TTFT is a clean prefill+first-step
    measurement, not a batching artifact.  Reported: prefill tokens
    actually computed (engine counter) and mean warm TTFT, cache on vs
    off, with token-identity asserted between the arms."""
    import numpy as np

    from ray_tpu.llm import EngineConfig, LLMEngine, SamplingParams

    cfg, params = _spec_model()
    shared_len = 128 if smoke else PREFIX_SHARED_LEN
    n_req = 4 if smoke else PREFIX_N
    rng = np.random.RandomState(7)
    shared = list(rng.randint(0, cfg.vocab_size, shared_len))
    prompts = [
        shared + list(rng.randint(0, cfg.vocab_size, PREFIX_SUFFIX_LEN))
        for _ in range(n_req)
    ]
    total = shared_len + PREFIX_SUFFIX_LEN + PREFIX_MAX_TOKENS
    bps = -(-(total + 1) // PREFIX_BLOCK)

    def make_engine(cached: bool):
        e = LLMEngine(
            cfg, params,
            EngineConfig(
                max_slots=2, block_size=PREFIX_BLOCK,
                # room for the resident shared prefix + two live tables
                num_blocks=2 * bps + shared_len // PREFIX_BLOCK + 4,
                max_blocks_per_seq=bps, prefill_chunk=32,
                prefix_cache=cached,
            ),
        )
        e.warmup()
        return e

    def run(engine):
        outs, ttfts = [], []
        p0 = engine.stats()["prefill_tokens_computed"]
        for prompt in prompts:
            req = engine.submit(prompt, SamplingParams(max_tokens=PREFIX_MAX_TOKENS))
            while not req.finished:
                engine.step()
            outs.append(list(req.out))
            ttfts.append(req.first_token_t - req.arrival_t)
        prefill = engine.stats()["prefill_tokens_computed"] - p0
        return outs, ttfts, prefill

    on_out, on_ttft, on_prefill = run(make_engine(True))
    off_out, off_ttft, off_prefill = run(make_engine(False))
    # prefix reuse must be EXACT — or the TTFT comparison is meaningless
    assert on_out == off_out, "prefix-cache on/off token mismatch"
    warm_on = sum(on_ttft[1:]) / max(len(on_ttft) - 1, 1)
    warm_off = sum(off_ttft[1:]) / max(len(off_ttft) - 1, 1)
    return {
        "metric": "llm_prefix_cache_warm_ttft_speedup",
        "value": round(warm_off / max(warm_on, 1e-9), 3),
        "unit": "x",
        "vs_baseline": round(warm_off / max(warm_on, 1e-9), 3),
        "detail": {
            "requests": n_req,
            "shared_prefix_tokens": shared_len,
            "prefill_tokens_on": int(on_prefill),
            "prefill_tokens_off": int(off_prefill),
            "prefill_reduction": round(1.0 - on_prefill / max(off_prefill, 1), 3),
            "ttft_cold_on_s": round(on_ttft[0], 4),
            "ttft_warm_on_s": round(warm_on, 4),
            "ttft_warm_off_s": round(warm_off, 4),
            "smoke": smoke,
        },
    }


MULTICHIP_N = 6
MULTICHIP_MAX_TOKENS = 24


def run_multichip_bench(smoke: bool = False) -> dict:
    """Paired single-chip vs tensor-parallel engines on one workload:
    every arm must emit identical greedy tokens (asserted — otherwise
    the throughput comparison compares different work).  Reported per
    mesh size: aggregate tokens/s, mean TTFT, per-device KV-pool bytes
    (the ledger's per-device attribution — the pool splits 1/tp)."""
    import jax
    import numpy as np

    from ray_tpu.llm import EngineConfig, LLMEngine, SamplingParams

    n_dev = len(jax.devices())
    tps = [t for t in (2, 4) if t <= n_dev]
    if not tps:
        # single-device host (e.g. env without XLA_FLAGS): record the
        # skip rather than fake a ratio
        return {
            "metric": "llm_multichip_tp_tokens_per_sec",
            "value": 0.0,
            "unit": "tok/s",
            "vs_baseline": None,
            "detail": {"skipped": f"needs >=2 devices, have {n_dev}"},
        }

    cfg, params = _model()
    n_req = 3 if smoke else MULTICHIP_N
    mt = 12 if smoke else MULTICHIP_MAX_TOKENS
    rng = np.random.RandomState(11)
    prompts = [
        list(rng.randint(0, cfg.vocab_size, PROMPT_LEN)) for _ in range(n_req)
    ]

    def run(tp):
        eng = LLMEngine(
            cfg, params,
            EngineConfig(
                max_slots=4, num_blocks=64, block_size=8,
                max_blocks_per_seq=16, prefill_chunk=16, tp=tp,
            ),
        )
        eng.warmup()  # jit outside the measured window
        reqs = [eng.submit(p, SamplingParams(max_tokens=mt)) for p in prompts]
        t0 = time.perf_counter()
        while not all(r.finished for r in reqs):
            eng.step()
        dt = time.perf_counter() - t0
        ttft = sum(r.first_token_t - r.arrival_t for r in reqs) / len(reqs)
        led = eng.hbm_ledger()
        kv_per_dev = {
            dev: row["pool_bytes"]
            for dev, row in led.get("per_device", {}).items()
        } or {"0": led["pool_bytes"]}
        return (
            [list(r.out) for r in reqs],
            (n_req * mt) / dt,
            ttft,
            kv_per_dev,
        )

    base_out, base_tps, base_ttft, base_kv = run(1)
    arms = {
        "tp1": {
            "tokens_per_sec": round(base_tps, 2),
            "ttft_s": round(base_ttft, 4),
            "kv_pool_bytes_per_device": base_kv,
        }
    }
    best = base_tps
    for tp in tps:
        out, toks, ttft, kv = run(tp)
        assert out == base_out, f"tp={tp} token mismatch vs single-chip"
        arms[f"tp{tp}"] = {
            "tokens_per_sec": round(toks, 2),
            "ttft_s": round(ttft, 4),
            "kv_pool_bytes_per_device": kv,
        }
        best = toks
    return {
        "metric": "llm_multichip_tp_tokens_per_sec",
        "value": round(best, 2),
        "unit": "tok/s",
        "vs_baseline": round(best / max(base_tps, 1e-9), 3),
        "detail": {
            "requests": n_req,
            "max_tokens": mt,
            "mesh_sizes": tps,
            "arms": arms,
            "substrate": jax.default_backend(),
            "smoke": smoke,
        },
    }


def run_loadgen_bench(smoke: bool = False) -> dict:
    """Open-loop load harness over the served HTTP path (``llm.loadgen``):
    healthy / overload / replica-kill arms against a tiny 2-replica app,
    client-side percentiles joined with the server-side phase ledgers.
    The headline is the healthy-arm p99; ``vs_baseline`` carries the
    phase-sum identity fraction (1.0 = every attributed request's phases
    sum to its end-to-end latency within ε)."""
    from ray_tpu.llm import loadgen

    report = loadgen.run_report(smoke=smoke)
    healthy = report["arms"]["healthy"]["client"]
    ident = report["identity"]
    return {
        "metric": "llm_loadgen_healthy_p99_s",
        "value": healthy["e2e_s"].get("p99") or 0.0,
        "unit": "s",
        "vs_baseline": ident["within_eps_frac"] or 0.0,
        "detail": report,
    }


def main(argv=None) -> list:
    import argparse
    import os

    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument(
        "--smoke", action="store_true",
        help="shrunken workloads for CI (seconds, looser signal)",
    )
    ap.add_argument(
        "--only",
        choices=("all", "serving", "continuous", "spec", "prefix",
                 "multichip", "loadgen"),
        default="all",
        help="run a subset instead of the full set (bench.py's llm_serving "
        "section uses --only serving, its llm_prefix section --only prefix "
        "and its multichip section --only multichip, so none pays for the "
        "others' workloads)",
    )
    args = ap.parse_args(argv)
    benches = {
        "continuous": run_bench,
        "spec": lambda: run_spec_bench(smoke=args.smoke),
        "prefix": lambda: run_prefix_bench(smoke=args.smoke),
        "multichip": lambda: run_multichip_bench(smoke=args.smoke),
        "loadgen": lambda: run_loadgen_bench(smoke=args.smoke),
    }
    groups = {
        # loadgen boots a whole serve cluster — it runs only when asked
        "all": [n for n in benches if n != "loadgen"],
        "serving": ["continuous", "spec"],
    }
    names = groups.get(args.only, [args.only])
    if "multichip" in names \
            and "host_platform_device_count" not in os.environ.get("XLA_FLAGS", ""):
        # the tp arms need a host-device mesh; XLA reads this flag at
        # first backend init (lazy, none of the benches has run yet), so
        # bootstrap it here rather than ask every caller to export it
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + " --xla_force_host_platform_device_count=4"
        ).strip()
    records = []
    for name in names:
        rec = benches[name]()
        print(json.dumps(rec), flush=True)
        records.append(rec)
    return records


if __name__ == "__main__":
    main()
