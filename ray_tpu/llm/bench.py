"""Continuous vs. static batching under staggered arrivals.

``python -m ray_tpu.llm.bench`` prints one JSON line: aggregate decode
tokens/s of the continuous-batching engine against the same workload run
as sequential static-batch ``gptj_decode`` calls (the pre-``ray_tpu.llm``
serving story: each request is its own decode, one after another, each
waiting for its arrival time).  The workload staggers arrivals so the
engine's advantage — new requests join the running batch mid-flight
instead of queuing behind whole completions — is what gets measured.

Sized to run on CPU in seconds (the same comparison holds on TPU with
the real model; the ratio is what travels).  Invoked by the top-level
``bench.py`` as a subprocess so a failure never costs the headline
metric.
"""

from __future__ import annotations

import json
import time

N_REQUESTS = 8
PROMPT_LEN = 8
MAX_TOKENS = 32
ARRIVAL_GAP_S = 0.01
WINDOWS = 2  # best-of per side: robust to one scheduler stall on a shared box


def _model():
    import jax

    from ray_tpu.models.gptj import GPTJConfig, gptj_init

    cfg = GPTJConfig(
        vocab_size=256, seq_len=128, d_model=128, n_layers=4, n_heads=4,
        rotary_dim=16, dtype="float32", remat=False, attn_impl="xla",
        fused_loss=False,
    )
    return cfg, gptj_init(jax.random.PRNGKey(0), cfg)


def run_bench() -> dict:
    import jax
    import jax.numpy as jnp
    import numpy as np

    from ray_tpu.llm import EngineConfig, LLMEngine, SamplingParams
    from ray_tpu.models.gptj import gptj_decode

    cfg, params = _model()
    rng = np.random.RandomState(0)
    prompts = [
        list(rng.randint(0, cfg.vocab_size, PROMPT_LEN)) for _ in range(N_REQUESTS)
    ]
    arrivals = [i * ARRIVAL_GAP_S for i in range(N_REQUESTS)]
    total_tokens = N_REQUESTS * MAX_TOKENS

    # -- static baseline: sequential gptj_decode per request ---------------
    decode = jax.jit(
        lambda p, t: gptj_decode(cfg, p, t, MAX_TOKENS), static_argnums=()
    )
    warm = decode(params, jnp.asarray([prompts[0]], jnp.int32))
    int(warm[0, -1])  # compile + transfer barrier before timing

    def run_static():
        t0 = time.perf_counter()
        out = []
        for arr, prompt in zip(arrivals, prompts):
            now = time.perf_counter() - t0
            if now < arr:
                time.sleep(arr - now)
            toks = decode(params, jnp.asarray([prompt], jnp.int32))
            out.append(list(np.asarray(toks)[0, PROMPT_LEN:]))
        return time.perf_counter() - t0, out

    static_wall, static_out = min(
        (run_static() for _ in range(WINDOWS)), key=lambda r: r[0]
    )
    static_tps = total_tokens / static_wall

    # -- continuous engine -------------------------------------------------
    # table width sized to the workload: decode cost scales with the table
    # width, not the live length, so an over-provisioned table would tax
    # every step
    blocks_per_seq = -(-(PROMPT_LEN + MAX_TOKENS) // 8)
    engine = LLMEngine(
        cfg, params,
        EngineConfig(
            max_slots=N_REQUESTS, block_size=8,
            num_blocks=N_REQUESTS * blocks_per_seq + 2,
            max_blocks_per_seq=blocks_per_seq, prefill_chunk=PROMPT_LEN,
        ),
    )
    engine.generate(prompts[0], SamplingParams(max_tokens=2))  # warm the jits

    def run_continuous():
        t0 = time.perf_counter()
        reqs = []
        pending = list(zip(arrivals, prompts))
        while pending or not all(r.finished for r in reqs):
            now = time.perf_counter() - t0
            while pending and pending[0][0] <= now:
                _, prompt = pending.pop(0)
                reqs.append(
                    engine.submit(prompt, SamplingParams(max_tokens=MAX_TOKENS))
                )
            if not engine.step():
                time.sleep(0.0005)
        return time.perf_counter() - t0, [r.out for r in reqs]

    cont_wall, cont_out = min(
        (run_continuous() for _ in range(WINDOWS)), key=lambda r: r[0]
    )
    cont_tps = total_tokens / cont_wall

    # greedy determinism: both paths must produce identical tokens, or the
    # throughput comparison is comparing different work
    assert cont_out == static_out, "continuous/static token mismatch"

    return {
        "metric": "llm_continuous_batching_tokens_per_sec",
        "value": round(cont_tps, 1),
        "unit": "tokens/s",
        "vs_baseline": round(cont_tps / static_tps, 3),
        "detail": {
            "static_tokens_per_sec": round(static_tps, 1),
            "requests": N_REQUESTS,
            "max_tokens": MAX_TOKENS,
            "arrival_gap_s": ARRIVAL_GAP_S,
            "static_wall_s": round(static_wall, 3),
            "continuous_wall_s": round(cont_wall, 3),
            "preemptions": engine.stats()["preemptions"],
        },
    }


def main() -> dict:
    rec = run_bench()
    print(json.dumps(rec), flush=True)
    return rec


if __name__ == "__main__":
    main()
