"""Draft-token proposers for speculative decoding.

A drafter guesses the next ``k`` tokens of every running sequence; the
engine verifies all of them (plus one bonus position) in ONE jitted
target-model call (``model_runner.verify_step``).  Drafters only affect
THROUGHPUT, never output: verification accepts exactly the tokens the
target model would have produced (greedy) or an exact sample from its
distribution (``models.sampling.speculative_verify``), so a bad draft
just lowers the acceptance rate.

Two built-ins:

* ``NGramDrafter`` — model-free prompt lookup: match the longest recent
  n-gram of (prompt + generated history) against an earlier occurrence
  and propose the tokens that followed it.  Free to run (host-side
  numpy/lists, no device work) and very effective on repetitive or
  structured text — code, templated output, and self-repeating greedy
  continuations — where the future literally already appeared.
* ``SmallModelDrafter`` — a small KV-cached model proposes greedily via
  the existing ``gpt_decode``/``gptj_decode``.  Static shapes: ONE jit
  of ``(slots, ctx_window)`` prompts decoding ``k`` tokens, reused every
  step.  Contexts are truncated to the last ``ctx_window`` tokens and
  left-padded with 0 when shorter — padding skews short-context drafts
  (draft QUALITY only; verification keeps the output exact), and keeps
  the call from ever retracing.

Both expose ``propose(contexts) -> (n, k) int32`` where ``contexts`` is
a list of token-id lists (prompt + generated so far, most recent last).
Proposals are deterministic functions of the context, so re-drafting
after recompute preemption reproduces.
"""

from __future__ import annotations

from typing import Sequence

import numpy as np


class NGramDrafter:
    """Prompt-lookup drafting (model-free).

    For each context, the longest suffix n-gram (``max_ngram`` down to 1)
    is searched for a strictly-earlier occurrence; the ``k`` tokens that
    followed that occurrence become the proposal.  When the continuation
    runs off the end of the context (a match near the tail — the periodic
    case this drafter shines on), the proposal extends itself, which
    continues the period.  No match anywhere: propose the last token
    repeated (cheap, and correct for degenerate single-token loops).

    Cross-request prefix awareness: when the engine runs a prefix cache
    (``llm.prefix_cache``), it points ``corpus`` at
    ``PrefixCache.paths`` — a bounded list of recently-used radix paths
    (other requests' cached prompt prefixes).  A context whose local
    lookup finds no confident match re-runs the n-gram search over those
    shared paths: chat traffic repeats across requests at least as much
    as within one, so the future a local scan can't see often sits on a
    path some OTHER request already prefilled.  Corpus matches require
    n >= 2 (a lone cross-request token is pure noise) and report
    confident; drafts remain throughput-only — verification keeps the
    output exact whatever the corpus proposes.

    ``last_matched`` records, per context of the latest ``propose`` call,
    whether a CONFIDENT match backed the proposal: an n-gram of length
    >= 2, or a single-token match immediately adjacent to the tail (the
    last two tokens equal — a genuine repeat loop).  A lone token
    recurring somewhere far back is noise in anything resembling natural
    text (in a random-token stream it fires with probability ~len/vocab
    and its drafts essentially never verify), and the repeat-last
    fallback is a guess, not evidence — both report unmatched.  The
    engine uses the flag as the drafter's confidence signal: when NO
    running slot has a confident proposal it skips the multi-token
    verification step entirely and plain-decodes — which bounds the
    regression on hostile (low-match) workloads at the drafting cost,
    host-side and near-free, instead of paying a doomed ``w``-wide
    verify to learn what the drafter already knew.
    """

    def __init__(self, k: int, max_ngram: int = 3, scan_window: int = 1024):
        if k < 1 or max_ngram < 1 or scan_window < 2:
            raise ValueError("k and max_ngram must be >= 1, scan_window >= 2")
        self.k = k
        self.max_ngram = max_ngram
        #: cap on how much recent context the per-step scan walks — the
        #: engine drafts EVERY step under its lock, so an unbounded scan
        #: would make per-step host work grow with sequence length
        #: (O(L^2) over a generation).  Matches beyond the window are
        #: lost (acceptable: drafts are throughput-only) in exchange for
        #: a constant per-step bound.
        self.scan_window = scan_window
        self.last_matched = np.zeros(0, bool)
        #: optional zero-arg callable returning a list of token sequences
        #: to extend the lookup across requests (the engine wires
        #: ``PrefixCache.paths`` here when a prefix cache is active)
        self.corpus = None

    def _local_match(self, ctx: list) -> tuple[list[int], bool]:
        n_ctx = len(ctx)
        for n in range(min(self.max_ngram, n_ctx - 1), 0, -1):
            pat = list(ctx[-n:])
            # rightmost occurrence strictly before the suffix itself
            for pos in range(n_ctx - n - 1, -1, -1):
                if list(ctx[pos : pos + n]) == pat:
                    ext = list(ctx)
                    out = []
                    cur = pos + n
                    for _ in range(self.k):
                        tok = ext[cur]
                        out.append(tok)
                        ext.append(tok)
                        cur += 1
                    confident = n >= 2 or pos == n_ctx - 2
                    return out, confident
        return [int(ctx[-1])] * self.k, False

    def _corpus_match(self, ctx: list, corpus: list) -> list:
        """Rightmost n-gram match (n >= 2 only — cross-request single
        tokens are noise) over the shared radix paths; returns the k-token
        continuation or None.  Continuations running off a path's end
        self-extend periodically, same as the local scan."""
        n_ctx = len(ctx)
        for n in range(min(self.max_ngram, n_ctx), 1, -1):
            pat = list(ctx[-n:])
            for seq in corpus:
                seq = list(seq[-self.scan_window :])
                for pos in range(len(seq) - n - 1, -1, -1):
                    if seq[pos : pos + n] == pat:
                        ext = list(seq)
                        out = []
                        cur = pos + n
                        for _ in range(self.k):
                            tok = ext[cur]
                            out.append(int(tok))
                            ext.append(tok)
                            cur += 1
                        return out
        return None

    def _propose_one(
        self, ctx: Sequence[int], corpus_fn=None
    ) -> tuple[list[int], bool]:
        ctx = list(ctx[-self.scan_window :])
        out, confident = self._local_match(ctx)
        if confident:
            return out, True
        if corpus_fn is not None:
            shared = self._corpus_match(ctx, corpus_fn())
            if shared is not None:
                return shared, True
        return out, confident

    def propose(self, contexts: list[Sequence[int]]) -> np.ndarray:
        # the corpus (PrefixCache.paths: lock + tree walk) is fetched
        # LAZILY, once, and only if some row's local match is
        # unconfident — propose runs every decode step under the engine
        # lock, and steady-state repetitive decode (all rows locally
        # confident) must not pay the cache walk at all
        fetched: list = []

        def corpus_fn():
            if not fetched:
                fetched.append(self.corpus() or [])
            return fetched[0]

        fn = corpus_fn if self.corpus is not None else None
        rows = [self._propose_one(c, fn) for c in contexts]
        self.last_matched = np.asarray([m for _, m in rows], bool)
        return np.asarray(
            [p for p, _ in rows], np.int32
        ).reshape(len(contexts), self.k)


class SmallModelDrafter:
    """Greedy ``k``-token proposals from a small KV-cached draft model.

    ``model_cfg``/``params`` are a ``models.gpt`` or ``models.gptj``
    config + parameter pytree (typically a much smaller model than the
    target).  ``slots`` fixes the jitted batch dimension — callers pass
    the engine's ``max_slots`` and may propose for fewer contexts (the
    batch is padded; padded rows cost compute but never retrace).
    """

    def __init__(self, model_cfg, params, k: int, slots: int, ctx_window: int = 16):
        import jax

        from ray_tpu.models.gpt import GPTConfig, gpt_decode
        from ray_tpu.models.gptj import GPTJConfig, gptj_decode

        if k < 1 or slots < 1 or ctx_window < 1:
            raise ValueError("k, slots and ctx_window must be >= 1")
        if isinstance(model_cfg, GPTJConfig):
            decode = gptj_decode
        elif isinstance(model_cfg, GPTConfig):
            decode = gpt_decode
            if ctx_window + k > model_cfg.seq_len:
                raise ValueError(
                    f"ctx_window ({ctx_window}) + k ({k}) exceeds the draft "
                    f"model's positional table (seq_len={model_cfg.seq_len})"
                )
        else:
            raise TypeError(
                f"unsupported draft model config {type(model_cfg).__name__}"
            )
        self.k = k
        self.slots = slots
        self.ctx_window = ctx_window
        self._params = params
        self._fn = jax.jit(lambda p, t: decode(model_cfg, p, t, k))

    def propose(self, contexts: list[Sequence[int]]) -> np.ndarray:
        if len(contexts) > self.slots:
            raise ValueError(
                f"{len(contexts)} contexts > drafter batch of {self.slots}"
            )
        W = self.ctx_window
        batch = np.zeros((self.slots, W), np.int32)
        for i, ctx in enumerate(contexts):
            tail = list(ctx[-W:])
            batch[i, W - len(tail):] = tail
        out = np.asarray(self._fn(self._params, batch))  # (slots, W + k)
        return out[: len(contexts), W:].astype(np.int32)


def make_drafter(
    kind: str,
    k: int,
    slots: int,
    *,
    ngram_max: int = 3,
    draft_cfg=None,
    draft_params=None,
    draft_ctx: int = 16,
):
    """Engine-facing factory: ``kind`` is 'ngram' or 'model'."""
    if kind == "ngram":
        return NGramDrafter(k, max_ngram=ngram_max)
    if kind == "model":
        if draft_cfg is None or draft_params is None:
            raise ValueError(
                "drafter='model' needs draft_model_cfg and draft_params "
                "(a small gpt/gptj config + parameter pytree)"
            )
        return SmallModelDrafter(
            draft_cfg, draft_params, k, slots, ctx_window=draft_ctx
        )
    raise ValueError(f"unknown drafter {kind!r}; expected 'ngram' or 'model'")
