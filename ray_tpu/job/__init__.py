"""ray_tpu.job: job submission — run driver scripts ON the cluster.

Reference: ``dashboard/modules/job/job_manager.py:525`` (JobManager spawning
a per-job JobSupervisor actor :140 that runs the entrypoint as a subprocess)
plus the SDK (``dashboard/modules/job/sdk.py``). TPU-first simplification:
no REST daemon — the submission API talks straight to the cluster (the same
control plane the dashboard head would use), and the supervisor actor owns
the subprocess: spawn, log capture, status transitions, stop.

If the cluster has a TCP listener, the entrypoint subprocess receives
``RAY_TPU_ADDRESS`` so it can ``ray_tpu.init(address=...)`` back into the
cluster that runs it (the reference sets RAY_ADDRESS the same way).
"""

from __future__ import annotations

import json
import os
import subprocess
import threading
import time
import uuid
from typing import Optional

import ray_tpu

_KV_PREFIX = "__jobs__/"

PENDING = "PENDING"
RUNNING = "RUNNING"
SUCCEEDED = "SUCCEEDED"
FAILED = "FAILED"
STOPPED = "STOPPED"


class JobSupervisor:
    """Actor owning one job's entrypoint subprocess (reference:
    ``job_manager.py:140`` JobSupervisor)."""

    def __init__(self, job_id: str, entrypoint: str, env_vars: dict, cwd: Optional[str]):
        self.job_id = job_id
        self.entrypoint = entrypoint
        self._status = PENDING
        self._log: list[str] = []
        self._proc: Optional[subprocess.Popen] = None
        self._lock = threading.Lock()
        self._env_vars = env_vars
        self._cwd = cwd
        threading.Thread(target=self._run, daemon=True).start()

    def _run(self):
        env = dict(os.environ)
        env.update(self._env_vars or {})
        try:
            from ray_tpu._private.runtime import get_ctx

            ctx = get_ctx()
            addr = ctx.call("tcp_address")
            if addr:
                env.setdefault("RAY_TPU_ADDRESS", f"{addr[0]}:{addr[1]}")
                env.setdefault("RAY_TPU_AUTHKEY", ctx.call("auth_info"))
        except Exception:
            pass
        try:
            self._proc = subprocess.Popen(
                self.entrypoint,
                shell=True,
                stdout=subprocess.PIPE,
                stderr=subprocess.STDOUT,
                text=True,
                env=env,
                cwd=self._cwd,
                start_new_session=True,  # stop() kills the whole group
            )
        except Exception as e:  # noqa: BLE001
            with self._lock:
                self._status = FAILED
                self._log.append(f"[supervisor] failed to spawn: {e!r}\n")
            return
        with self._lock:
            self._status = RUNNING
        for line in self._proc.stdout:
            with self._lock:
                self._log.append(line)
                if len(self._log) > 100_000:
                    del self._log[:50_000]
        rc = self._proc.wait()
        with self._lock:
            if self._status != STOPPED:
                self._status = SUCCEEDED if rc == 0 else FAILED
            self._log.append(f"[supervisor] exit code {rc}\n")

    def status(self) -> str:
        with self._lock:
            return self._status

    def logs(self) -> str:
        with self._lock:
            return "".join(self._log)

    def stop(self) -> bool:
        import signal

        with self._lock:
            if self._status not in (PENDING, RUNNING):
                return False
            self._status = STOPPED
            proc = self._proc
        if proc is not None and proc.poll() is None:
            try:
                os.killpg(os.getpgid(proc.pid), signal.SIGTERM)
            except Exception:
                proc.terminate()
        return True

    def ping(self) -> bool:
        return True


def _supervisor_name(job_id: str) -> str:
    return f"_job_supervisor:{job_id}"


def submit_job(
    entrypoint: str,
    *,
    submission_id: Optional[str] = None,
    env_vars: Optional[dict] = None,
    working_dir: Optional[str] = None,
) -> str:
    """Start ``entrypoint`` (a shell command) under a supervisor actor;
    returns the job id immediately (reference: ``JobSubmissionClient.submit_job``)."""
    from ray_tpu._private.runtime import get_ctx

    job_id = submission_id or f"job_{uuid.uuid4().hex[:10]}"
    cls = ray_tpu.remote(JobSupervisor)
    cls.options(
        name=_supervisor_name(job_id), lifetime="detached", max_concurrency=4,
        num_cpus=0,
    ).remote(job_id, entrypoint, env_vars or {}, working_dir)
    get_ctx().call(
        "kv_put",
        key=_KV_PREFIX + job_id,
        value=json.dumps(
            {"entrypoint": entrypoint, "submitted_at": time.time()}
        ).encode(),
    )
    return job_id


def _supervisor(job_id: str):
    return ray_tpu.get_actor(_supervisor_name(job_id))


def get_job_status(job_id: str) -> str:
    try:
        return ray_tpu.get(_supervisor(job_id).status.remote(), timeout=30)
    except ValueError:
        from ray_tpu._private.runtime import get_ctx

        if get_ctx().call("kv_get", key=_KV_PREFIX + job_id) is not None:
            return STOPPED  # supervisor gone (cluster restartish) — terminal
        raise


def get_job_logs(job_id: str) -> str:
    return ray_tpu.get(_supervisor(job_id).logs.remote(), timeout=30)


def stop_job(job_id: str) -> bool:
    stopped = ray_tpu.get(_supervisor(job_id).stop.remote(), timeout=30)
    return bool(stopped)


def wait_job(job_id: str, timeout: float = 300.0) -> str:
    """Block until the job reaches a terminal state; returns it."""
    deadline = time.time() + timeout
    while time.time() < deadline:
        st = get_job_status(job_id)
        if st in (SUCCEEDED, FAILED, STOPPED):
            return st
        time.sleep(0.2)
    raise TimeoutError(f"job {job_id} still {st!r} after {timeout}s")


def list_jobs() -> list[dict]:
    from ray_tpu._private.runtime import get_ctx

    ctx = get_ctx()
    out = []
    for key in ctx.call("kv_keys", prefix=_KV_PREFIX):
        job_id = key[len(_KV_PREFIX):]
        meta = json.loads(ctx.call("kv_get", key=key).decode())
        try:
            status = get_job_status(job_id)
        except Exception:
            status = "UNKNOWN"
        out.append({"job_id": job_id, "status": status, **meta})
    return sorted(out, key=lambda j: j.get("submitted_at", 0))
