"""Mutable shared-memory channels (reference: ``python/ray/experimental/
channel.py:49`` — the reusable plasma channels under compiled DAGs).

A Channel is one POSIX shm segment reused for every message between a fixed
writer and a fixed reader — after setup, sending a value is a serialize +
memcpy + counter bump with no task submission, no socket round-trip, and no
allocation. That makes actor-to-actor pipelines (compiled DAGs, pipeline
parallelism across hosts' driver processes) run at memory bandwidth instead
of control-plane latency.

Protocol: single-slot rendezvous (matching the reference's channel
semantics, where a write blocks until the previous value was read):

    [ wseq : 8 bytes ][ rack : 8 bytes ][ len : 8 bytes ][ payload ... ]

* writer: wait until ``wseq == rack`` (previous value consumed), write
  payload + len, then publish ``wseq += 1``;
* reader: wait until ``wseq > rack``, copy payload out, ack ``rack = wseq``.

One writer and one reader per channel (fan-out = one channel per edge).
Both sides poll with escalating sleeps — at pipeline rates the hot path
spins only microseconds.
"""

from __future__ import annotations

import struct
import time
from multiprocessing import resource_tracker, shared_memory
from typing import Any, Optional

from ray_tpu._private import serialization as ser

_HDR = 24  # wseq, rack, len


class ChannelClosed(Exception):
    pass


_CLOSED_LEN = (1 << 63) - 1  # len sentinel: channel torn down


def _untrack(shm) -> None:
    try:
        resource_tracker.unregister(shm._name, "shared_memory")  # type: ignore[attr-defined]
    except Exception:
        pass


class Channel:
    """One fixed-size, reusable message slot in shared memory."""

    def __init__(self, capacity: int = 1 << 20, _name: Optional[str] = None):
        if _name is None:
            shm = shared_memory.SharedMemory(create=True, size=_HDR + capacity)
            shm.buf[:_HDR] = b"\x00" * _HDR
            self._creator = True
        else:
            shm = shared_memory.SharedMemory(name=_name)
            self._creator = False
        _untrack(shm)
        self._shm = shm
        self.capacity = capacity
        self.name = shm.name

    # channels travel inside task args/plans; attach by name on arrival
    def __reduce__(self):
        return (Channel, (self.capacity, self.name))

    # -- counters ----------------------------------------------------------

    def _get(self, off: int) -> int:
        return struct.unpack_from("<q", self._shm.buf, off)[0]

    def _set(self, off: int, v: int) -> None:
        struct.pack_into("<q", self._shm.buf, off, v)

    @staticmethod
    def _spin(start: float, deadline: Optional[float]) -> None:
        if deadline is not None and time.monotonic() > deadline:
            raise TimeoutError("channel wait timed out")
        waited = time.monotonic() - start
        time.sleep(0.0 if waited < 0.001 else (0.0001 if waited < 0.1 else 0.001))

    # -- data path ---------------------------------------------------------

    def write(self, value: Any, timeout: Optional[float] = None) -> None:
        data = ser.serialize(value).to_bytes()
        if len(data) > self.capacity:
            raise ValueError(
                f"serialized value ({len(data)}B) exceeds channel capacity "
                f"({self.capacity}B); create the Channel with a larger capacity"
            )
        deadline = None if timeout is None else time.monotonic() + timeout
        start = time.monotonic()
        while self._get(0) != self._get(8):  # previous message unread
            if self._get(16) == _CLOSED_LEN:
                raise ChannelClosed()
            self._spin(start, deadline)
        self._shm.buf[_HDR : _HDR + len(data)] = data
        self._set(16, len(data))
        self._set(0, self._get(0) + 1)

    def read(self, timeout: Optional[float] = None) -> Any:
        deadline = None if timeout is None else time.monotonic() + timeout
        start = time.monotonic()
        while True:
            wseq, rack = self._get(0), self._get(8)
            if wseq > rack:
                break
            if self._get(16) == _CLOSED_LEN:
                raise ChannelClosed()
            self._spin(start, deadline)
        n = self._get(16)
        if n == _CLOSED_LEN:
            raise ChannelClosed()
        data = bytes(self._shm.buf[_HDR : _HDR + n])
        self._set(8, wseq)
        return ser.deserialize_value(ser.SerializedValue.from_bytes(data))

    # -- lifecycle ---------------------------------------------------------

    def close(self) -> None:
        """Mark closed: blocked/future readers and writers raise
        ChannelClosed (compiled-DAG teardown)."""
        try:
            self._set(16, _CLOSED_LEN)
            self._set(0, self._get(0) + 1)
        except Exception:
            pass

    def destroy(self) -> None:
        self.close()
        if self._creator:
            try:
                # creation untracked the segment (lifetime is ours, not the
                # resource_tracker's); re-register right before unlink so the
                # tracker's unregister message balances and stays quiet
                resource_tracker.register(self._shm._name, "shared_memory")  # type: ignore[attr-defined]
            except Exception:
                pass
            try:
                self._shm.unlink()
            except FileNotFoundError:
                pass
        try:
            self._shm.close()
        except BufferError:
            self._shm._buf = None  # type: ignore[attr-defined]
            self._shm._mmap = None  # type: ignore[attr-defined]
