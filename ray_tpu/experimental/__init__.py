"""Experimental runtime features (reference: ``python/ray/experimental/``)."""

from ray_tpu.experimental.channel import Channel  # noqa: F401
