"""Phase-ledger identity matrix (ISSUE 20: request latency attribution).

The contract under test: every finished request's engine phase ledger is
COMPLETE and NON-OVERLAPPING — the phases sum to the request's measured
end-to-end engine latency (finish − submit) — across every scheduling
regime the engine knows:

* greedy and seeded sampling;
* speculative decode (``spec_verify`` attributed, not lumped into
  ``decode``);
* preemption recompute (re-queue/re-admit/re-prefill charged to
  ``preempt``, so recompute cost is its own line);
* mid-stream failover resume (a fresh ledger for the second attempt —
  already-delivered token phases are never re-counted);
* prefix-cache hits (matched-prefix time lands in ``admit``; ``prefill``
  covers only the uncached suffix).

The identity is exact by construction (cursor model: every interval is
charged to exactly one phase) — the tolerance below only absorbs the
6-decimal rounding the fold applies per phase.

Plus the '—'-below-2-samples contract pins for the tables the loadgen
report reuses (``obs.hist_pcts_row``, the attribution per-phase table),
and the ≤2µs stamp budget.
"""

import time

import jax
import numpy as np
import pytest

from ray_tpu._private import events as ev
from ray_tpu.llm import EngineConfig, LLMEngine, SamplingParams
from ray_tpu.models.gptj import GPTJConfig, gptj_init
from ray_tpu.util import phases

TINY = GPTJConfig(
    vocab_size=128, seq_len=64, d_model=32, n_layers=2, n_heads=2,
    rotary_dim=8, dtype="float32", remat=False, attn_impl="xla",
    fused_loss=False,
)

#: per-phase durations are rounded to 1µs in the fold — 7 phases of
#: half-ulp each bounds the identity slack at a few µs
ROUND_SLACK = 1e-4


@pytest.fixture(scope="module")
def tiny_params():
    return gptj_init(jax.random.PRNGKey(0), TINY)


@pytest.fixture(autouse=True)
def fresh_ring():
    st = ev.stats()
    ev.clear()
    ev.set_enabled(True)
    yield
    ev.set_enabled(st["enabled"])
    ev.clear()


def _prompt(n, seed=1):
    return list(np.random.RandomState(seed).randint(0, TINY.vocab_size, n))


def _engine(params, **kw):
    defaults = dict(
        max_slots=3, num_blocks=32, block_size=4, max_blocks_per_seq=12,
        prefill_chunk=8,
    )
    defaults.update(kw)
    return LLMEngine(TINY, params, EngineConfig(**defaults))


def _drive(engine, reqs, timeout=120.0):
    deadline = time.monotonic() + timeout
    while not all(r.finished for r in reqs):
        engine.step()
        assert time.monotonic() < deadline, "engine did not finish in time"


def _ledgers():
    return [e for e in ev.snapshot() if e["type"] == "llm.phase.ledger"]


def _assert_identity(led):
    """One ledger event: known phase names, non-negative durations, and
    Σ phases == t_finish − t_submit (complete + non-overlapping)."""
    assert set(led["phases"]) <= set(phases.ENGINE_PHASES), led
    assert all(v >= 0.0 for v in led["phases"].values()), led
    e2e = led["t_finish"] - led["t_submit"]
    total = sum(led["phases"].values())
    assert abs(total - e2e) <= ROUND_SLACK + 1e-3 * e2e, (
        f"phase sum {total:.6f}s != e2e {e2e:.6f}s for {led['request_id']}: "
        f"{led['phases']}"
    )


# ---------------------------------------------------------------------------
# the matrix
# ---------------------------------------------------------------------------


def test_greedy_identity(tiny_params):
    eng = _engine(tiny_params)
    reqs = [
        eng.submit(_prompt(8, seed=s), SamplingParams(max_tokens=10))
        for s in (1, 2, 3)
    ]
    _drive(eng, reqs)
    leds = _ledgers()
    assert len(leds) == 3
    for led in leds:
        _assert_identity(led)
        assert led["phases"]["prefill"] > 0.0
        assert led["phases"]["decode"] > 0.0
        assert not led["resumed"]


def test_seeded_sampling_identity(tiny_params):
    eng = _engine(tiny_params)
    sp = dict(max_tokens=8, temperature=1.0, top_k=16)
    reqs = [
        eng.submit(_prompt(6, seed=s), SamplingParams(seed=s, **sp))
        for s in (4, 5)
    ]
    _drive(eng, reqs)
    leds = _ledgers()
    assert len(leds) == 2
    for led in leds:
        _assert_identity(led)


def test_spec_decode_attributes_verify_not_decode(tiny_params):
    # patterned prompt: the ngram drafter's home turf, so spec steps run
    eng = _engine(tiny_params, spec_k=2)
    prompt = [7, 8, 9] * 4
    reqs = [eng.submit(list(prompt), SamplingParams(max_tokens=12))]
    _drive(eng, reqs)
    (led,) = _ledgers()
    _assert_identity(led)
    # verified speculative steps are their own line, not lumped decode
    assert led["phases"]["spec_verify"] > 0.0


def test_preemption_recompute_charged_to_preempt(tiny_params):
    eng = _engine(
        tiny_params, max_slots=3, num_blocks=13, block_size=4,
        max_blocks_per_seq=10,
    )
    reqs = [
        eng.submit(_prompt(8, seed=s), SamplingParams(max_tokens=16))
        for s in (5, 6, 7)
    ]
    _drive(eng, reqs)
    assert eng.stats()["preemptions"] > 0, "pool was sized to force preemption"
    leds = _ledgers()
    assert len(leds) == 3
    for led in leds:
        _assert_identity(led)
    # at least one request's recompute (re-queue, re-admit, re-prefill)
    # is visible as its own phase — not smeared into queue/prefill
    assert any(led["phases"]["preempt"] > 0.0 for led in leds)


def test_failover_resume_fresh_ledger_no_recount(tiny_params):
    eng = _engine(tiny_params)
    prompt = _prompt(8, seed=9)
    full = eng.submit(prompt, SamplingParams(max_tokens=12))
    _drive(eng, [full])
    ev.clear()  # drop the first attempt's ledger: only the resume remains

    t_resume = time.time()
    resumed = eng.submit(
        prompt, SamplingParams(max_tokens=12),
        resume_tokens=tuple(full.out[:5]),
    )
    _drive(eng, [resumed])
    assert full.out[5:] == resumed.out[5:]  # token-identical continuation
    (led,) = _ledgers()
    _assert_identity(led)
    assert led["resumed"] == 5
    # the fresh ledger covers ONLY the second attempt: its submit anchor
    # postdates the resume call, so the 5 already-delivered tokens' phase
    # time (first attempt) cannot be re-counted here
    assert led["t_submit"] >= t_resume - ROUND_SLACK
    # and the resumed fold carries no dispatch leg — the gap back to any
    # proxy dispatch anchor spans the dead attempt (assembly reports it
    # as `failover`, never as engine time)
    assert "dispatch_s" not in led


def test_prefix_cache_hit_lands_in_admit_not_prefill(tiny_params):
    eng = _engine(
        tiny_params, num_blocks=64, max_blocks_per_seq=16, prefill_chunk=8,
    )
    shared = _prompt(48, seed=11)
    cold = eng.submit(list(shared), SamplingParams(max_tokens=4))
    _drive(eng, [cold])
    warm = eng.submit(list(shared), SamplingParams(max_tokens=4))
    _drive(eng, [warm])
    led_cold, led_warm = _ledgers()
    _assert_identity(led_cold)
    _assert_identity(led_warm)
    assert cold.out == warm.out
    # the warm request's radix match happened in admission; its prefill
    # covers only the uncached suffix (≤1 chunk of 8 vs the cold 6) —
    # the matched-prefix time must NOT reappear as prefill
    assert led_warm["phases"]["prefill"] < led_cold["phases"]["prefill"] / 2


def test_phases_disabled_costs_nothing(tiny_params):
    phases.set_enabled(False)
    try:
        eng = _engine(tiny_params)
        req = eng.submit(_prompt(6, seed=12), SamplingParams(max_tokens=4))
        _drive(eng, [req])
        assert req.phase_led is None
        assert not _ledgers()
    finally:
        phases.set_enabled(True)


# ---------------------------------------------------------------------------
# the '—'-below-2-samples contract (PR 5) on the tables loadgen reuses
# ---------------------------------------------------------------------------


def test_hist_pcts_row_dash_below_two_samples():
    from ray_tpu.obs import hist_pcts_row

    assert hist_pcts_row({"count": 0}) == "—"
    assert hist_pcts_row({"count": 1, "p50": 1.0, "p95": 1.0, "p99": 1.0}) == "—"
    row = hist_pcts_row({"count": 2, "p50": 0.5, "p95": 0.9, "p99": 0.99})
    assert row != "—" and "p50=500.0ms" in row


def test_attribution_table_dash_below_two_samples():
    from ray_tpu.obs import attribute_rows, attribution_report, render_attribution

    def ledger(rid, t0):
        return {
            "type": "llm.phase.ledger", "request_id": rid, "engine_req": 1,
            "reason": "complete", "t_submit": t0, "t_finish": t0 + 1.0,
            "resumed": 0,
            "phases": {"queue": 0.1, "prefill": 0.4, "decode": 0.5},
        }

    one = attribution_report(attribute_rows([ledger("r1", 100.0)]))
    txt = render_attribution(one)
    assert "—" in txt  # N=1 rows refuse to print fake percentiles
    two = attribution_report(
        attribute_rows([ledger("r1", 100.0), ledger("r2", 200.0)])
    )
    txt2 = render_attribution(two)
    assert "decode" in txt2 and "p99 budget" in txt2
    assert two["within_eps_frac"] == 1.0


# ---------------------------------------------------------------------------
# grafana / SLO derivations track the phase registry
# ---------------------------------------------------------------------------


def test_grafana_phases_row_tracks_registry():
    """The dashboard's request-phases row is GENERATED from
    ``phases.PHASES`` — every exported phase gets a panel, assembly-only
    phases (no series exists) get none, and the family lands in the
    skip-set so the dynamic fallback doesn't duplicate it."""
    from ray_tpu.util.grafana import _LLM_NAMES, _phases_panels

    doc = str(_phases_panels())
    for name, owner, _edges in phases.PHASES:
        if owner == "assembly":
            assert f'phase="{name}"' not in doc, name
        else:
            assert f'phase="{name}"' in doc, name
    assert "llm_request_phase_s" in doc
    assert "llm_request_phase_s" in _LLM_NAMES


def test_slo_queue_burn_rule_filters_phase_series(monkeypatch):
    from ray_tpu.util import slo

    monkeypatch.setenv("RAY_TPU_SLO_QUEUE_THRESHOLD_S", "0.5")
    rules = {r.name: r for r in slo.default_rules()}
    rule = rules["queue-time-burn"]
    assert rule.metric == "llm_request_phase_s"
    assert rule.tags == {"phase": "queue"}
    assert rule.threshold == 0.5

    # merged-series fixture: queue series burning hard, decode series
    # clean — the rule must read ONLY the queue series
    now = 1000.0
    bounds = (0.25, 0.5, 1.0)

    def hist_points(bad, good):
        # (ts, vector) points; vector = per-bucket counts (≤0.25, ≤0.5,
        # ≤1.0, +inf) + sum + count.  good lands in the ≤0.5 bucket, bad
        # beyond the 0.5 threshold; baseline point zeroes the delta.
        zero = [0.0] * 6
        vec = [0.0, good, bad / 2, bad / 2, 1.0, good + bad]
        return [(now - 200.0, zero), (now - 1.0, vec)]

    merged = {
        "llm_request_phase_s": {
            "kind": "histogram",
            "boundaries": bounds,
            "series": {
                '{"phase":"queue"}': hist_points(bad=50.0, good=50.0),
                '{"phase":"decode"}': hist_points(bad=0.0, good=1000.0),
            },
        }
    }
    res = slo.evaluate_rule(rule, merged, now=now)
    # 50% bad on a 1% budget = burn 50 — far above both factors; the
    # clean decode series would dilute this to ~4.5 if it leaked in
    assert res["breached"], res
    assert res["value"] > 14.4, res


def test_grafana_queue_burn_promql_carries_phase_selector():
    from ray_tpu.util.grafana import _slo_panels

    exprs = {title: expr for title, expr, _u, _d in _slo_panels()}
    q = exprs["queue-time-burn fast burn rate"]
    assert 'phase="queue"' in q
    assert "llm_request_phase_s_bucket" in q
    assert 'ray_tpu_llm_request_phase_s_count{phase="queue"}' in q


# ---------------------------------------------------------------------------
# the stamp budget
# ---------------------------------------------------------------------------


def test_charge_within_stamp_budget():
    """ISSUE 20 hot-path bar: ≤2µs per stamp. charge() is two float ops
    and two list stores — the generous bar catches a lock or an
    allocation creeping in (10-100x), not scheduler noise."""
    from ray_tpu.obs import measure_overhead

    res = measure_overhead(n=30_000)
    assert res["phase_charge_ns"] <= 2_000.0, res
