"""Lazy DAG tests (reference: ``python/ray/dag/tests`` themes: bind/execute,
InputNode substitution, diamond graphs, MultiOutputNode)."""

import pytest

import ray_tpu
from ray_tpu.dag import InputNode, MultiOutputNode


def test_bind_and_execute_chain(ray_start_regular):
    @ray_tpu.remote
    def add(a, b):
        return a + b

    @ray_tpu.remote
    def mul(a, b):
        return a * b

    dag = mul.bind(add.bind(1, 2), 10)
    assert ray_tpu.get(dag.execute(), timeout=120) == 30


def test_input_node_threads_runtime_value(ray_start_regular):
    @ray_tpu.remote
    def double(x):
        return 2 * x

    @ray_tpu.remote
    def inc(x):
        return x + 1

    with InputNode() as inp:
        dag = inc.bind(double.bind(inp))
    assert ray_tpu.get(dag.execute(5), timeout=120) == 11
    assert ray_tpu.get(dag.execute(100), timeout=120) == 201  # reusable


def test_diamond_executes_shared_node_once(ray_start_regular):
    calls = []

    @ray_tpu.remote
    class Tracker:
        def __init__(self):
            self.n = 0

        def hit(self):
            self.n += 1
            return self.n

        def count(self):
            return self.n

    t = Tracker.remote()

    @ray_tpu.remote
    def source(tracker):
        return ray_tpu.get(tracker.hit.remote())

    @ray_tpu.remote
    def left(x):
        return x + 1

    @ray_tpu.remote
    def right(x):
        return x + 2

    @ray_tpu.remote
    def join(a, b):
        return (a, b)

    s = source.bind(t)
    dag = join.bind(left.bind(s), right.bind(s))
    out = ray_tpu.get(dag.execute(), timeout=120)
    assert out == (2, 3)
    # the shared upstream ran exactly once
    assert ray_tpu.get(t.count.remote(), timeout=120) == 1


def test_multi_output_node(ray_start_regular):
    @ray_tpu.remote
    def f(x):
        return x * x

    with InputNode() as inp:
        dag = MultiOutputNode([f.bind(inp), f.bind(3)])
    refs = dag.execute(2)
    assert ray_tpu.get(refs, timeout=120) == [4, 9]


def test_executing_input_node_directly_errors(ray_start_regular):
    inp = InputNode()
    with pytest.raises(RuntimeError, match="InputNode has no value"):
        inp.execute()
