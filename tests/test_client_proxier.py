"""ray:// client sessions: namespace isolation, reconnect-with-resume,
dirty-disconnect cleanup (VERDICT r4 #7).

Reference: ``python/ray/util/client/server/proxier.py`` — the reference
multiplexes N concurrent ``ray://`` clients through per-client servers with
namespace isolation and reconnect grace. Here the head itself is the proxy
(``ClientSession`` in ``_private/head.py``): every client conn carries a
session token; named actors scope to the session's (anonymous by default)
namespace; a dropped connection resumes with every ref intact when the
client redials with its token, and a client that never comes back has its
refs/actors released after the grace.
"""

import os
import subprocess
import sys
import time

import pytest

import ray_tpu

HEAD_SCRIPT = (
    "import ray_tpu, time;"
    "info = ray_tpu.init(num_cpus=2);"
    "from ray_tpu._private.runtime import get_ctx;"
    "head = get_ctx().head;"
    "h, p = head.listen_tcp('127.0.0.1', 0);"
    "print(f'ADDR {h}:{p}', flush=True);"
    "time.sleep(120)"
)


@pytest.fixture
def tcp_head():
    key = os.urandom(16).hex()
    env = dict(
        os.environ,
        RAY_TPU_AUTHKEY=key,
        RAY_TPU_CLIENT_RECONNECT_GRACE_S="2",
        RAY_TPU_HEALTH_CHECK_INTERVAL_S="0.2",
    )
    proc = subprocess.Popen(
        [sys.executable, "-c", HEAD_SCRIPT], stdout=subprocess.PIPE, text=True, env=env
    )
    os.environ["RAY_TPU_AUTHKEY"] = key
    line = proc.stdout.readline()
    assert line.startswith("ADDR"), line
    addr = line.split()[1]
    try:
        yield addr
    finally:
        os.environ.pop("RAY_TPU_AUTHKEY", None)
        if ray_tpu.is_initialized():
            ray_tpu.shutdown()
        proc.terminate()
        proc.wait(timeout=10)


CLIENT_A = """
import os, ray_tpu
ray_tpu.init(address="ray://{addr}")

@ray_tpu.remote(num_cpus=0)
class Secret:
    def whoami(self): return "client-a"

s = Secret.options(name="secret").remote()
assert ray_tpu.get(s.whoami.remote(), timeout=60) == "client-a"
# visible to OURSELVES in our session namespace
assert ray_tpu.get(ray_tpu.get_actor("secret").whoami.remote(), timeout=30) == "client-a"
print("A-READY", flush=True)
import sys
for line in sys.stdin:
    if line.strip() == "exit":
        break
ray_tpu.shutdown()
"""


def test_two_clients_namespaces_isolated(tcp_head):
    """Client B must not see client A's named actor (each anonymous
    session gets its own namespace), while both share the cluster."""
    a = subprocess.Popen(
        [sys.executable, "-c", CLIENT_A.format(addr=tcp_head)],
        stdout=subprocess.PIPE,
        stdin=subprocess.PIPE,
        text=True,
        env=dict(os.environ),
    )
    try:
        assert a.stdout.readline().strip() == "A-READY"
        ray_tpu.init(address=f"ray://{tcp_head}")
        try:
            with pytest.raises(ValueError):
                ray_tpu.get_actor("secret")  # A's namespace, not ours

            # but the cluster itself is shared: plain tasks run fine
            @ray_tpu.remote
            def f(x):
                return x + 1

            assert ray_tpu.get(f.remote(1), timeout=60) == 2

            # same-name actor in OUR namespace does not collide with A's
            @ray_tpu.remote(num_cpus=0)
            class Secret:
                def whoami(self):
                    return "client-b"

            s = Secret.options(name="secret").remote()
            assert ray_tpu.get(s.whoami.remote(), timeout=60) == "client-b"
            assert (
                ray_tpu.get(ray_tpu.get_actor("secret").whoami.remote(), timeout=30)
                == "client-b"
            )
        finally:
            ray_tpu.shutdown()
    finally:
        try:
            a.stdin.write("exit\n")
            a.stdin.flush()
        except OSError:
            pass
        a.wait(timeout=15)


def test_explicit_shared_namespace(tcp_head):
    """Two clients that ASK for the same namespace share names (reference:
    ray.init(namespace=...))."""
    script = (
        "import ray_tpu;"
        f"ray_tpu.init(address='ray://{tcp_head}', namespace='team');"
        "\n@ray_tpu.remote(num_cpus=0)\n"
        "class P:\n"
        "    def ping(self): return 'shared'\n"
        "p = P.options(name='pact', lifetime='detached').remote()\n"
        "import ray_tpu as r\n"
        "assert r.get(p.ping.remote(), timeout=60) == 'shared'\n"
        "print('OK', flush=True)\n"
        "ray_tpu.shutdown()\n"
    )
    r = subprocess.run(
        [sys.executable, "-c", script],
        capture_output=True,
        text=True,
        timeout=120,
        env=dict(os.environ),
    )
    assert "OK" in r.stdout, r.stderr[-800:]
    ray_tpu.init(address=f"ray://{tcp_head}", namespace="team")
    try:
        # detached actor registered under "default" (cluster-scoped) —
        # visible from any session via the detached fallback
        h = ray_tpu.get_actor("pact")
        assert ray_tpu.get(h.ping.remote(), timeout=60) == "shared"
    finally:
        ray_tpu.shutdown()


def test_reconnect_resumes_refs(tcp_head):
    """Kill the client's TCP connection mid-session: the context redials
    with its session token and previously-created refs still resolve."""
    ray_tpu.init(address=f"ray://{tcp_head}")
    try:
        from ray_tpu._private.node_agent import shutdown_conn
        from ray_tpu._private.runtime import get_ctx

        ref = ray_tpu.put({"payload": list(range(100))})

        @ray_tpu.remote
        def g():
            return "alive"

        ctx = get_ctx()
        token = ctx.session_token
        assert token
        old_conn = ctx.conn
        shutdown_conn(old_conn)  # violent drop, no goodbye

        deadline = time.monotonic() + 30
        value = None
        while time.monotonic() < deadline:
            try:
                value = ray_tpu.get(ref, timeout=10)
                break
            except Exception:
                time.sleep(0.3)
        assert value == {"payload": list(range(100))}
        assert ctx.session_token == token  # same session, not a fresh one
        assert ray_tpu.get(g.remote(), timeout=60) == "alive"
    finally:
        ray_tpu.shutdown()


def test_dirty_disconnect_cleans_up_session(tcp_head):
    """A client that dies without shutdown loses its session after the
    grace: its named actor is killed and its namespace entry freed."""
    script = (
        "import os, ray_tpu;"
        f"ray_tpu.init(address='ray://{tcp_head}', namespace='dirty');"
        "\n@ray_tpu.remote(num_cpus=0)\n"
        "class D:\n"
        "    def ping(self): return 1\n"
        "d = D.options(name='doomed').remote()\n"
        "assert ray_tpu.get(d.ping.remote(), timeout=60) == 1\n"
        "print('UP', flush=True)\n"
        "os._exit(1)\n"  # dirty: no shutdown, no frees
    )
    r = subprocess.run(
        [sys.executable, "-c", script],
        capture_output=True,
        text=True,
        timeout=120,
        env=dict(os.environ),
    )
    assert "UP" in r.stdout, r.stderr[-800:]

    ray_tpu.init(address=f"ray://{tcp_head}", namespace="dirty")
    try:
        # same explicit namespace: the actor is visible until the grace
        # (2s in this fixture) expires, then the head kills it
        deadline = time.monotonic() + 30
        gone = False
        while time.monotonic() < deadline:
            try:
                h = ray_tpu.get_actor("doomed")
                ray_tpu.get(h.ping.remote(), timeout=5)
                time.sleep(0.5)
            except Exception:
                gone = True
                break
        assert gone, "dirty client's actor survived the reconnect grace"
    finally:
        ray_tpu.shutdown()


def test_worker_tasks_inherit_namespace():
    """A plain task submitted from a namespaced driver resolves named
    actors in the DRIVER's namespace (reference: job-scoped namespaces are
    inherited by workers)."""
    ray_tpu.init(num_cpus=2, namespace="teamspace")
    try:

        @ray_tpu.remote(num_cpus=0)
        class N:
            def who(self):
                return "ns-actor"

        keep = N.options(name="scoped").remote()  # noqa: F841 - a dropped
        # handle would GC the actor (num_handles -> 0) before lookup runs

        @ray_tpu.remote
        def lookup():
            return ray_tpu.get(
                ray_tpu.get_actor("scoped").who.remote(), timeout=30
            )

        assert ray_tpu.get(lookup.remote(), timeout=60) == "ns-actor"

        @ray_tpu.remote
        def create_inside():
            @ray_tpu.remote(num_cpus=0)
            class M:
                def who(self):
                    return "made-in-task"

            import ray_tpu as r

            h = M.options(name="task-made", lifetime="detached").remote()
            r.get(h.who.remote(), timeout=30)  # ensure ALIVE before return
            return True

        assert ray_tpu.get(create_inside.remote(), timeout=60)
        # a DETACHED actor created inside the task outlives the task and
        # registers cluster-scoped — visible from the driver
        assert (
            ray_tpu.get(ray_tpu.get_actor("task-made").who.remote(), timeout=30)
            == "made-in-task"
        )
    finally:
        ray_tpu.shutdown()
