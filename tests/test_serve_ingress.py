"""Serve ingress hardening (VERDICT r3 #7): asyncio+h11 proxy concurrency,
declarative config deploy, graceful replica drain on downscale.

Reference: ``serve/_private/proxy.py:759`` (uvicorn/ASGI ingress — the
asyncio proxy is its stdlib counterpart), ``serve/schema.py`` (declarative
deploy), ``deployment_state.py`` graceful_shutdown_timeout_s drain.
"""

import asyncio
import json
import sys
import textwrap
import time

import pytest

import ray_tpu
from ray_tpu import serve


@pytest.fixture
def serve_instance():
    ray_tpu.init(num_cpus=8)
    yield
    serve.shutdown()
    ray_tpu.shutdown()


def _proxy_port():
    controller = ray_tpu.get_actor("SERVE_CONTROLLER")
    return ray_tpu.get(controller.get_proxy_port.remote(), timeout=30)


async def _one_request(port: int, app: str, body: bytes) -> tuple[int, bytes]:
    reader, writer = await asyncio.open_connection("127.0.0.1", port)
    writer.write(
        f"POST /{app} HTTP/1.1\r\nhost: x\r\ncontent-type: application/json\r\n"
        f"content-length: {len(body)}\r\nconnection: close\r\n\r\n".encode() + body
    )
    await writer.drain()
    raw = await reader.read()
    writer.close()
    head, _, payload = raw.partition(b"\r\n\r\n")
    status = int(head.split(b" ")[1])
    return status, payload


def test_concurrent_load_500_inflight(serve_instance):
    """≥500 requests in flight at once: the asyncio proxy must hold them all
    concurrently (the old thread-per-request server pinned one OS thread
    each). Serial execution would take 500×0.5s≈250s; concurrent far less."""

    @serve.deployment(max_ongoing_requests=600)
    def slow(payload):
        time.sleep(0.5)
        return {"ok": payload["i"]}

    serve.run(slow.bind(), name="load", http=True, http_port=0)
    port = _proxy_port()

    async def fire():
        tasks = [
            _one_request(port, "load", json.dumps({"i": i}).encode())
            for i in range(500)
        ]
        return await asyncio.gather(*tasks)

    t0 = time.monotonic()
    results = asyncio.run(fire())
    wall = time.monotonic() - t0
    assert len(results) == 500
    assert all(status == 200 for status, _ in results), results[:3]
    got = sorted(json.loads(p)["ok"] for _, p in results)
    assert got == list(range(500))
    # generous bound for a 1-core CI box; serial would be ≥250s
    assert wall < 120, f"500 concurrent requests took {wall:.1f}s"


def test_keepalive_connection_reuse(serve_instance):
    """h11 cycle reuse: multiple requests over ONE connection."""

    @serve.deployment
    def echo(payload):
        return {"v": payload["v"]}

    serve.run(echo.bind(), name="ka", http=True, http_port=0)
    port = _proxy_port()

    async def run_two():
        reader, writer = await asyncio.open_connection("127.0.0.1", port)
        out = []
        for v in (1, 2):
            body = json.dumps({"v": v}).encode()
            writer.write(
                f"POST /ka HTTP/1.1\r\nhost: x\r\ncontent-type: application/json\r\n"
                f"content-length: {len(body)}\r\n\r\n".encode() + body
            )
            await writer.drain()
            head = await reader.readuntil(b"\r\n\r\n")
            length = int(
                [l for l in head.lower().split(b"\r\n") if b"content-length" in l][0]
                .split(b":")[1]
            )
            out.append(json.loads(await reader.readexactly(length)))
        writer.close()
        return out

    assert asyncio.run(run_two()) == [{"v": 1}, {"v": 2}]


def test_run_config_yaml_e2e(serve_instance, tmp_path):
    """Declarative deploy: yaml → run_config → overrides applied → HTTP."""
    mod_dir = tmp_path / "mods"
    mod_dir.mkdir()
    (mod_dir / "cfg_app.py").write_text(
        textwrap.dedent(
            """
            from ray_tpu import serve

            @serve.deployment
            def greeter(payload):
                return {"hello": (payload or {}).get("who", "world")}

            app = greeter.bind()
            """
        )
    )
    sys.path.insert(0, str(mod_dir))
    try:
        cfg = tmp_path / "serve.yaml"
        cfg.write_text(
            textwrap.dedent(
                """
                proxy:
                  port: 0
                applications:
                  - name: hello
                    import_path: cfg_app:app
                    deployments:
                      - name: greeter
                        num_replicas: 2
                        max_ongoing_requests: 32
                """
            )
        )
        handles = serve.run_config(str(cfg))
        assert handles == {"hello": "hello_greeter"}
        # override applied?
        controller = ray_tpu.get_actor("SERVE_CONTROLLER")
        st = ray_tpu.get(
            controller.get_deployment_status.remote("hello_greeter"), timeout=30
        )
        assert st["target_replicas"] == 2
        port = _proxy_port()
        status, payload = asyncio.run(
            _one_request(port, "hello", json.dumps({"who": "cfg"}).encode())
        )
        assert status == 200 and json.loads(payload) == {"hello": "cfg"}
    finally:
        sys.path.remove(str(mod_dir))


def test_graceful_drain_on_downscale(serve_instance):
    """In-flight requests on a downscale victim complete before the kill
    (the old path killed the actor immediately — mid-request errors)."""

    @serve.deployment(num_replicas=2, max_ongoing_requests=8,
                      graceful_shutdown_timeout_s=30)
    def slow(payload):
        time.sleep(3.0)
        return {"done": payload["i"]}

    handle = serve.run(slow.bind(), name="drain")
    # saturate BOTH replicas with in-flight work
    responses = [handle.remote({"i": i}) for i in range(4)]
    time.sleep(0.5)  # let them land on the replicas

    # downscale to 1 while those requests are running
    serve.run(slow.options(num_replicas=1).bind(), name="drain", _blocking=False)

    # every in-flight request must still complete
    results = sorted(r.result(timeout=60)["done"] for r in responses)
    assert results == [0, 1, 2, 3]

    # and the victim is eventually killed (drain completes)
    controller = ray_tpu.get_actor("SERVE_CONTROLLER")
    deadline = time.time() + 30
    while time.time() < deadline:
        st = ray_tpu.get(
            controller.get_deployment_status.remote("drain_slow"), timeout=30
        )
        if st["running_replicas"] == 1 and len(st["replica_ids"]) == 1:
            break
        time.sleep(0.25)
    else:
        raise AssertionError(f"victim replica never finished draining: {st}")

    # the survivor keeps serving
    assert handle.remote({"i": 9}).result(timeout=30) == {"done": 9}
