"""APPO: async PPO on the IMPALA machinery.

Reference: ``rllib/algorithms/appo`` (clipped surrogate + V-trace async).
Learning gate mirrors the IMPALA/PPO CartPole tests.
"""

import numpy as np
import pytest

from ray_tpu.rl.algorithms.appo import APPOConfig


# tier1-durations: ~19s on the CI box — the full suite overruns the
# 870s tier-1 budget (truncation, not failures; ROADMAP), so the heaviest
# non-LLM learning/scale tests run as @slow instead of being cut at random
@pytest.mark.slow
def test_appo_learns_cartpole(ray_start_regular):
    algo = (
        APPOConfig()
        .environment("CartPole-v1")
        .env_runners(num_env_runners=2, num_envs_per_env_runner=2,
                     rollout_fragment_length=50)
        .training(train_batch_size=1200, lr=5e-4, entropy_coeff=0.01)
        .debugging(seed=0)
        .build()
    )
    best = 0.0
    for _ in range(25):
        res = algo.train()
        ret = res.get("episode_return_mean")
        if ret is not None:
            best = max(best, ret)
        if best >= 150.0:
            break
    assert best >= 150.0, f"APPO failed to learn CartPole (best={best})"


def test_appo_kl_penalty_reported():
    algo = (
        APPOConfig()
        .environment("CartPole-v1")
        .training(train_batch_size=300, use_kl_loss=True)
        .debugging(seed=0)
        .build()
    )
    res = algo.train()
    assert "learner/kl" in res and np.isfinite(res["learner/kl"])
    assert res["learner/kl"] >= -1e-6  # k3 estimator is non-negative


def test_appo_registered():
    from ray_tpu.rl import get_algorithm_class

    assert get_algorithm_class("APPO") is not None
