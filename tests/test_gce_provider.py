"""Raw GCE / TPU-VM provider (autoscaler/gce.py) — VERDICT r4 missing #7:
bare-metal TPU pods without GKE.

Reference: ``python/ray/autoscaler/_private/gcp/node_provider.py`` (direct
instance management + TPU nodes). All tests run against fake transports —
no network, ever.
"""

import json

import pytest

from ray_tpu.autoscaler.gce import (
    GCEAsyncProvider,
    GCEClient,
    TPUNodeClient,
    _sanitize,
)
from ray_tpu.autoscaler.v2 import ALLOCATED, ALLOCATION_FAILED, REQUESTED, Instance


class FakeHTTP:
    """Record requests; script responses per (method, url-substring)."""

    def __init__(self):
        self.calls = []
        self.instances = {}  # name -> status dict
        self.tpu_nodes = {}

    def __call__(self, method, url, body):
        self.calls.append((method, url, body))
        if "tpu.googleapis.com" in url:
            return self._tpu(method, url, body)
        return self._gce(method, url, body)

    def _gce(self, method, url, body):
        if method == "POST" and url.endswith("/instances"):
            self.instances[body["name"]] = {"name": body["name"], "status": "PROVISIONING", "body": body}
            return {"name": "op-1"}
        name = url.rsplit("/", 1)[-1].split("?")[0]
        if method == "GET" and "/instances/" in url:
            if name not in self.instances:
                raise RuntimeError(f"GCP API GET {url} failed: 404 not found")
            return self.instances[name]
        if method == "DELETE":
            if name not in self.instances:
                raise RuntimeError(f"GCP API DELETE {url} failed: 404 not found")
            del self.instances[name]
            return {}
        if method == "GET" and url.endswith("/instances") or "?filter=" in url:
            return {"items": list(self.instances.values())}
        raise AssertionError((method, url))

    def _tpu(self, method, url, body):
        if method == "POST" and "nodeId=" in url:
            name = url.split("nodeId=")[-1]
            self.tpu_nodes[name] = {"name": name, "state": "CREATING", "body": body}
            return {"name": "op-tpu"}
        name = url.rsplit("/", 1)[-1]
        if method == "GET" and url.endswith("/nodes"):
            return {"nodes": list(self.tpu_nodes.values())}
        if method == "GET":
            if name not in self.tpu_nodes:
                raise RuntimeError(f"GCP API GET {url} failed: 404 not found")
            return self.tpu_nodes[name]
        if method == "DELETE":
            self.tpu_nodes.pop(name, None)
            return {}
        raise AssertionError((method, url))


@pytest.fixture
def fake():
    return FakeHTTP()


def _provider(fake, node_types):
    return GCEAsyncProvider(
        node_types=node_types,
        gce_client=GCEClient("proj", "us-central2-b", http=fake),
        tpu_client=TPUNodeClient("proj", "us-central2-b", http=fake),
    )


def test_sanitize():
    assert _sanitize("Ray_Worker.1") == "ray-worker-1"
    assert len(_sanitize("x" * 100)) == 63


def test_gce_instance_lifecycle(fake):
    p = _provider(fake, {"cpu": {"machine_type": "n2-standard-4",
                                 "startup_script": "join $RAY_TPU_NODE_ID"}})
    inst = Instance(node_type="cpu")
    p.request_create(inst, {"CPU": 4}, {"ray-cluster": "c1"})
    assert inst.provider_id.startswith("ray-cpu-")
    body = fake.instances[inst.provider_id]["body"]
    assert "n2-standard-4" in body["machineType"]
    assert body["labels"]["provider_node_id"] == inst.provider_id
    # the startup script got the node id substituted for exact pairing
    assert body["metadata"]["items"][0]["value"] == f"join {inst.provider_id}"

    assert p.poll(inst) == REQUESTED  # PROVISIONING
    fake.instances[inst.provider_id]["status"] = "RUNNING"
    assert p.poll(inst) == ALLOCATED
    p.terminate(inst)
    assert inst.provider_id not in fake.instances


def test_tpu_node_lifecycle(fake):
    p = _provider(fake, {"v5e": {"accelerator_type": "v5litepod-8"}})
    inst = Instance(node_type="v5e")
    p.request_create(inst, {"TPU": 8}, {})
    assert inst.provider_id in fake.tpu_nodes
    assert fake.tpu_nodes[inst.provider_id]["body"]["acceleratorType"] == "v5litepod-8"

    assert p.poll(inst) == REQUESTED  # CREATING
    fake.tpu_nodes[inst.provider_id]["state"] = "READY"
    assert p.poll(inst) == ALLOCATED
    fake.tpu_nodes[inst.provider_id]["state"] = "PREEMPTED"
    assert p.poll(inst) == ALLOCATION_FAILED
    p.terminate(inst)
    assert inst.provider_id not in fake.tpu_nodes


def test_transient_errors_keep_polling(fake):
    p = _provider(fake, {"cpu": {}})
    inst = Instance(node_type="cpu")
    p.request_create(inst, {}, {})

    def boom(method, url, body):
        raise RuntimeError("GCP API unreachable: 503")

    p.gce._http = boom
    assert p.poll(inst) == REQUESTED  # transient, not FAILED


def test_cluster_config_gce(fake):
    from ray_tpu.autoscaler.cluster_config import build_provider, validate_cluster_config

    cfg = {
        "cluster_name": "bare",
        "provider": {"type": "gce_tpu", "project": "proj", "zone": "us-central2-b"},
        "node_types": {
            "v5e": {
                "resources": {"TPU": 8},
                "accelerator_type": "v5litepod-8",
                "max_workers": 4,
            }
        },
    }
    validate_cluster_config(cfg)
    gce = GCEClient("proj", "us-central2-b", http=fake)
    tpu = TPUNodeClient("proj", "us-central2-b", http=fake)
    p = build_provider(cfg, client=(gce, tpu))
    inst = Instance(node_type="v5e")
    p.request_create(inst, {"TPU": 8}, {})
    assert inst.provider_id in fake.tpu_nodes

    with pytest.raises(ValueError):
        validate_cluster_config({**cfg, "provider": {"type": "gce_tpu", "project": "p"}})


def test_json_bodies_are_serializable(fake):
    """Every request body must survive the real urllib path's json.dumps."""
    p = _provider(fake, {"cpu": {"machine_type": "n2-standard-4"}})
    inst = Instance(node_type="cpu")
    p.request_create(inst, {}, {"a": "B!"})
    for _method, _url, body in fake.calls:
        if body is not None:
            json.dumps(body)


def test_teardown_sweeps_both_apis(fake):
    """'ray_tpu down' must find VMs AND tpu.googleapis.com nodes by the
    ray-cluster label the launch path stamps — TPU pods are the expensive
    leak."""
    from ray_tpu.autoscaler.cluster_config import build_provider, teardown_cluster

    cfg = {
        "cluster_name": "bare",
        "provider": {"type": "gce_tpu", "project": "proj", "zone": "z"},
        "node_types": {
            "v5e": {"resources": {"TPU": 8}, "accelerator_type": "v5litepod-8"},
            "cpu": {"resources": {"CPU": 8}},
        },
    }
    gce = GCEClient("proj", "z", http=fake)
    tpu = TPUNodeClient("proj", "z", http=fake)
    p = build_provider(cfg, client=(gce, tpu))
    i1, i2 = Instance(node_type="v5e"), Instance(node_type="cpu")
    p.request_create(i1, {"TPU": 8}, {})
    p.request_create(i2, {"CPU": 8}, {})
    # launch stamped the sweep label on both
    assert fake.tpu_nodes[i1.provider_id]["body"]["labels"]["ray-cluster"] == "bare"
    assert fake.instances[i2.provider_id]["body"]["labels"]["ray-cluster"] == "bare"
    # fake list: expose labels like the real APIs do
    for n in fake.tpu_nodes.values():
        n["labels"] = n["body"]["labels"]
    gone = teardown_cluster(cfg, client=(gce, tpu))
    assert sorted(gone) == sorted([i1.provider_id, i2.provider_id])
    assert not fake.tpu_nodes and not fake.instances
