"""raylint phase-1 (project index) unit suite.

The cross-module rules are only as good as the index underneath them, so
the index's resolution machinery is pinned directly: per-module symbol
tables (imports incl. relative), the jit registry across all wrapping
forms (decorator / ``partial`` decorator / assignment / inline call),
attribute mutability classification, attr→class resolution (constructor,
annotation, and cross-module constructor CALL SITES), owner-qualified
lock keys, transitive lock sets, daemon-thread reachability, and the
observability-name extraction RL012 consumes.
"""

import ast
import textwrap

from ray_tpu._lint.core import FileContext
from ray_tpu._lint.index import build_index, module_name_for


def make_index(tmp_path, files, display_root=None):
    """files: {relative_path: source} -> ProjectIndex over all of them."""
    ctxs = []
    for rel, src in files.items():
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        text = textwrap.dedent(src)
        p.write_text(text)
        ctxs.append(FileContext(p, rel, text, ast.parse(text)))
    return build_index(ctxs, display_root=display_root)


# ------------------------------------------------------------ module names


def test_module_name_for():
    assert module_name_for("ray_tpu/llm/engine.py") == "ray_tpu.llm.engine"
    assert module_name_for("ray_tpu/llm/__init__.py") == "ray_tpu.llm"
    assert module_name_for("pkg/mod.py") == "pkg.mod"


def test_relative_import_resolution(tmp_path):
    idx = make_index(
        tmp_path,
        {
            "pkg/__init__.py": "from .engine import Engine\n",
            "pkg/engine.py": "from .cache import Pool\n\nclass Engine:\n    pass\n",
            "pkg/cache.py": "class Pool:\n    pass\n",
        },
    )
    assert idx.modules["pkg.engine"].imports["Pool"] == "pkg.cache.Pool"
    # package __init__ anchors at the package itself, not its parent
    assert idx.modules["pkg"].imports["Engine"] == "pkg.engine.Engine"


# ------------------------------------------------------------ jit registry


def test_jit_registry_all_forms(tmp_path):
    idx = make_index(
        tmp_path,
        {
            "m.py": """
                import functools

                import jax
                from functools import partial

                @jax.jit
                def decorated(x):
                    return x

                @partial(jax.jit, static_argnums=(1,))
                def partial_decorated(x, n):
                    return x

                def plain(x):
                    return x

                module_level = jax.jit(plain, static_argnames=("n",))
                via_partial = jax.jit(functools.partial(plain, 1))

                class Runner:
                    def __init__(self):
                        self._step = jax.jit(self._impl, donate_argnums=(0,))

                    def _impl(self, pool):
                        return pool
            """,
        },
    )
    resolved = {}
    for site, owner in idx.jit_sites:
        target = idx.resolve_jit_target(site, owner)
        if target is not None:
            resolved[target.qualname] = site
    assert "decorated" in resolved
    assert "partial_decorated" in resolved
    assert resolved["partial_decorated"].static_argnums == (1,)
    assert "plain" in resolved  # assignment AND partial form both hit it
    assert "Runner._impl" in resolved
    module_site = next(
        s for s, _ in idx.jit_sites if s.target_chain == ("plain",)
        and s.static_argnames
    )
    assert module_site.static_argnames == ("n",)


# ------------------------------------------------- attribute classification


ATTR_SRC = {
    "m.py": """
        import numpy as np

        class Runner:
            def __init__(self, params: dict, block_size: int, arch="gpt",
                         table=None):
                self.params = params
                self.block_size = block_size
                self.arch = arch
                self.table = table
                self.buf = np.zeros(4)
                self.mode = "fast"
                self.counter = 0

            def tweak(self):
                self.counter = 1
    """,
}


def test_attr_kinds(tmp_path):
    idx = make_index(tmp_path, ATTR_SRC)
    cls = idx.classes[("m", "Runner")]
    assert cls.attr_kind("params") == "mutable"      # name + dict annotation
    assert cls.attr_kind("block_size") == "static"   # int annotation
    assert cls.attr_kind("arch") == "static"         # str default
    assert cls.attr_kind("buf") == "mutable"         # array constructor
    assert cls.attr_kind("mode") == "static"         # literal
    assert cls.attr_kind("counter") == "mutable"     # reassigned after init
    assert cls.attr_kind("table") == "unknown"       # no evidence: no fire


def test_cross_module_mutation_marks_mutable(tmp_path):
    idx = make_index(
        tmp_path,
        {
            "runner.py": """
                class Runner:
                    def __init__(self, weights_in):
                        self.store = weights_in
            """,
            "engine.py": """
                from runner import Runner

                class Engine:
                    def __init__(self):
                        self.runner = Runner({})

                    def swap(self, new):
                        self.runner.store = new
            """,
        },
    )
    cls = idx.classes[("runner", "Runner")]
    assert cls.attr_kind("store") == "mutable"


# ------------------------------------------------------- class resolution


def test_attr_class_from_ctor_and_callsite(tmp_path):
    idx = make_index(
        tmp_path,
        {
            "cache.py": """
                import threading

                class Pool:
                    def __init__(self):
                        self._lock = threading.Lock()

                    def free(self):
                        with self._lock:
                            return 1
            """,
            "engine.py": """
                import threading

                from cache import Pool
                from watch import Watchdog

                class Engine:
                    def __init__(self):
                        self._lock = threading.Lock()
                        self.pool = Pool()
                        self.watchdog = Watchdog(self)
            """,
            "watch.py": """
                class Watchdog:
                    def __init__(self, engine):
                        self.engine = engine
            """,
        },
    )
    eng = idx.classes[("engine", "Engine")]
    assert eng.attr_classes["pool"] == ("cache", "Pool")
    # ctor CALL SITE inference: Watchdog(self) binds engine -> Engine
    wd = idx.classes[("watch", "Watchdog")]
    assert wd.attr_classes["engine"] == ("engine", "Engine")


def test_lock_key_resolution(tmp_path):
    idx = make_index(
        tmp_path,
        {
            "cache.py": """
                import threading

                _GLOBAL_LOCK = threading.Lock()

                class Pool:
                    def __init__(self):
                        self._lock = threading.Lock()

                    def free(self):
                        with self._lock:
                            with _GLOBAL_LOCK:
                                return 1
            """,
            "engine.py": """
                import threading

                from cache import Pool

                class Engine:
                    def __init__(self):
                        self._lock = threading.Lock()
                        self.pool = Pool()

                    def step(self):
                        with self._lock:
                            with self.pool._lock:
                                return 1
            """,
        },
    )
    eng = idx.classes[("engine", "Engine")]
    step = eng.methods["step"]
    keys = [idx.lock_key(a.chain, step) for a in step.acquisitions]
    assert keys == ["Engine._lock", "Pool._lock"]
    pool_free = idx.classes[("cache", "Pool")].methods["free"]
    keys = [idx.lock_key(a.chain, pool_free) for a in pool_free.acquisitions]
    assert keys == ["Pool._lock", "cache._GLOBAL_LOCK"]


def test_local_attr_alias_resolves(tmp_path):
    # `sched = self.scheduler; sched.admit()` must resolve like the
    # spelled-out chain — the engine step loop is written in this style
    idx = make_index(
        tmp_path,
        {
            "s.py": """
                import threading

                class Sched:
                    def __init__(self):
                        self._lock = threading.Lock()

                    def admit(self):
                        with self._lock:
                            return 1

                class Engine:
                    def __init__(self):
                        self.scheduler = Sched()

                    def step(self):
                        sched = self.scheduler
                        return sched.admit()
            """,
        },
    )
    eng = idx.classes[("s", "Engine")]
    step = eng.methods["step"]
    locks = idx.trans_lock_acqs(step)
    assert any(k == "Sched._lock" for k, _b, _f, _l in locks)


def test_trans_locks_cross_module_and_bounded(tmp_path):
    idx = make_index(
        tmp_path,
        {
            "a.py": """
                import threading

                class A:
                    def __init__(self):
                        self._lock = threading.Lock()

                    def locked(self):
                        with self._lock:
                            return 1

                    def bounded(self):
                        got = self._lock.acquire(timeout=0.1)
                        if got:
                            self._lock.release()
            """,
            "b.py": """
                from a import A

                class B:
                    def __init__(self):
                        self.a = A()

                    def call_locked(self):
                        return self.a.locked()

                    def call_bounded(self):
                        return self.a.bounded()
            """,
        },
    )
    b = idx.classes[("b", "B")]
    via_locked = idx.trans_lock_acqs(b.methods["call_locked"])
    assert ("A._lock", False) in {(k, bd) for k, bd, _f, _l in via_locked}
    via_bounded = idx.trans_lock_acqs(b.methods["call_bounded"])
    assert all(bd for _k, bd, _f, _l in via_bounded)  # bounded only


def test_daemon_reachability(tmp_path):
    idx = make_index(
        tmp_path,
        {
            "w.py": """
                import threading

                class W:
                    def start(self):
                        self._t = threading.Thread(target=self._run, daemon=True)
                        self._j = threading.Thread(target=self._joined)

                    def _run(self):
                        self._tick()

                    def _tick(self):
                        return 1

                    def _joined(self):
                        return 3

                    def not_a_thread(self):
                        return 2
            """,
        },
    )
    reach = idx.daemon_reachable()
    assert "w:W._run" in reach
    assert "w:W._tick" in reach      # transitively
    assert "w:W.not_a_thread" not in reach
    # a non-daemon (join()ed, short-lived) thread is not a monitor: RL011's
    # contract is about daemon/watchdog threads only
    assert "w:W._joined" not in reach


def test_trans_locks_complete_despite_call_cycle(tmp_path):
    # memo regression: a traversal truncated by a call cycle must not be
    # cached as final — with early() scanned first (poisoning the memo for
    # g via the truncated f<->g recursion), a later top-level query for
    # late()'s locks must still see CV through f -> g
    idx = make_index(
        tmp_path,
        {
            "c.py": """
                import threading

                OUTER_LOCK = threading.Lock()
                OTHER_LOCK = threading.Lock()
                CV = threading.Lock()

                def early():
                    with OTHER_LOCK:
                        f()

                def f():
                    g()

                def g():
                    with CV:
                        f()

                def late():
                    with OUTER_LOCK:
                        f()
            """,
        },
    )
    mi = idx.modules["c"]
    # query in scan order so the cycle-truncated path runs first
    idx.trans_lock_acqs(mi.functions["early"])
    late_locks = {k for k, _b, _f, _l in idx.trans_lock_acqs(mi.functions["late"])}
    assert "c.CV" in late_locks


# ------------------------------------------------- observability extraction


def test_emit_and_registry_extraction(tmp_path):
    md = tmp_path / "OBSERVABILITY.md"
    md.write_text("| `llm.*` | `submit`, `finish` |\n`llm_documented_metric`\n")
    idx = make_index(
        tmp_path,
        {
            "m.py": """
                from collections import Counter as CollectionsCounter

                from ray_tpu._private import events as _events
                from ray_tpu.util.metrics import Counter, Gauge

                METRIC_NAMES = ("m_one", "m_two")
                EVENT_NAMES = ("sys.boot",)
                LOCK_ORDER = ("Engine._lock", "Pool._lock")

                c = Counter("m_one", "doc")
                g = Gauge("m_two", "doc")
                histo = CollectionsCounter(["not", "a", "metric"])
                _events.record("sys.boot", n=1)
                panel = "rate(ray_tpu_m_one[1m])"
            """,
        },
        display_root=tmp_path,
    )
    metric_names = {s.name for s, _f in idx.emits if s.kind == "metric"}
    event_names = {s.name for s, _f in idx.emits if s.kind == "event"}
    assert metric_names == {"m_one", "m_two"}  # collections.Counter excluded
    assert event_names == {"sys.boot"}
    regs = idx.registries("METRIC_NAMES")
    assert regs and regs[0][1] == ["m_one", "m_two"]
    orders = idx.lock_orders()
    assert orders and orders[0][1] == ["Engine._lock", "Pool._lock"]
    assert ("m_one") in {n for n, _node, _mi in idx.prom_refs()}
    # doc names parsed from the markdown at display_root
    assert "llm.*" in idx.doc_names and "submit" in idx.doc_names
    assert "llm_documented_metric" in idx.doc_names


# ---------------------------------------------- thread roots & accesses (v4)


def test_thread_target_lambda_and_executor_submit(tmp_path):
    idx = make_index(
        tmp_path,
        {
            "mod.py": """
                import threading
                from concurrent.futures import ThreadPoolExecutor

                class Beat:
                    def __init__(self):
                        self.pool = ThreadPoolExecutor(2)
                        threading.Thread(target=lambda: self._run(), daemon=True).start()

                    def kick(self, k):
                        self.pool.submit(self._work, k)

                    def shove(self, loop, k):
                        loop.run_in_executor(None, self._bg, k)

                    def _run(self):
                        pass

                    def _work(self, k):
                        pass

                    def _bg(self, k):
                        pass
            """,
        },
    )
    init = idx.functions["mod:Beat.__init__"]
    # the lambda body's call chain is the recorded target
    assert [t for t, _d in init.thread_targets] == [("self", "_run")]
    kick = idx.functions["mod:Beat.kick"]
    assert kick.exec_submits == [("self", "_work")]
    shove = idx.functions["mod:Beat.shove"]
    assert shove.exec_submits == [("self", "_bg")]


def test_attr_accesses_record_kind_and_held(tmp_path):
    idx = make_index(
        tmp_path,
        {
            "mod.py": """
                import threading

                class Box:
                    def __init__(self):
                        self._lock = threading.Lock()
                        self.items = []
                        self.n = 0
                        self.flag = False

                    def locked_put(self, x):
                        with self._lock:
                            self.items.append(x)

                    def bare_bump(self):
                        self.n += 1

                    def publish(self):
                        self.flag = True

                    def bracketed(self):
                        self._lock.acquire()
                        try:
                            self.n += 1
                        finally:
                            self._lock.release()
            """,
        },
    )
    f = idx.functions["mod:Box.locked_put"]
    mutates = [a for a in f.attr_accesses if a.kind == "mutate"]
    assert mutates and mutates[0].chain == ("self", "items")
    assert mutates[0].held == (("self", "_lock"),)
    g = idx.functions["mod:Box.bare_bump"]
    augs = [a for a in g.attr_accesses if a.kind == "aug"]
    assert augs and augs[0].chain == ("self", "n") and augs[0].held == ()
    p = idx.functions["mod:Box.publish"]
    stores = [a for a in p.attr_accesses if a.kind == "store"]
    assert stores and stores[0].const_store  # literal flag publish
    b = idx.functions["mod:Box.bracketed"]
    augs_b = [a for a in b.attr_accesses if a.kind == "aug"]
    # linear .acquire()/.release() bracketing counts as held
    assert augs_b and augs_b[0].held == (("self", "_lock"),)


def test_param_annotation_resolves_class_and_lock(tmp_path):
    idx = make_index(
        tmp_path,
        {
            "mod.py": """
                import threading

                class State:
                    def __init__(self):
                        self.reply_lock = threading.Lock()
                        self.reply_buf = []

                    def bump(self):
                        pass

                def flush(state: State):
                    with state.reply_lock:
                        state.reply_buf.append(1)
                    state.bump()
            """,
        },
    )
    f = idx.functions["mod:flush"]
    assert f.param_classes["state"] == ("mod", "State")
    # param-rooted lock chains key to the owning class
    assert idx.lock_key(("state", "reply_lock"), f) == "State.reply_lock"
    # and param-rooted calls resolve to methods
    callee = idx.resolve_call(f, ("state", "bump"))
    assert callee is not None and callee.key == "mod:State.bump"


def test_ctor_typed_lock_with_unlockish_name(tmp_path):
    # PR 14 named its serializer `_submit_send`: lock-typed by ctor, so
    # `with self._submit_send:` must still enter the acquisition graph
    idx = make_index(
        tmp_path,
        {
            "mod.py": """
                import threading

                class Ctx:
                    def __init__(self):
                        self._submit_send = threading.Lock()

                    def flush(self):
                        with self._submit_send:
                            pass
            """,
        },
    )
    f = idx.functions["mod:Ctx.flush"]
    assert [a.chain for a in f.acquisitions] == [("self", "_submit_send")]
    assert idx.lock_key(("self", "_submit_send"), f) == "Ctx._submit_send"


# ------------------------------------------------- wire-protocol sites (v4)


def test_msg_send_extraction_forms(tmp_path):
    idx = make_index(
        tmp_path,
        {
            "mod.py": """
                from x import ser

                def direct(conn):
                    conn.send(("ping", 1))

                def via_conn_send(conn, payload):
                    ser.conn_send(conn, ("submit_batch", payload))

                def via_local(conn, batch):
                    msg = ("one", batch[0]) if len(batch) == 1 else ("many", batch)
                    conn.send(msg)

                def parametric(conn, msg_kind, payload):
                    conn.send((msg_kind, payload))
            """,
        },
    )
    kinds = lambda key: sorted(k for k, _n in idx.functions[key].msg_sends)
    assert kinds("mod:direct") == ["ping"]
    assert kinds("mod:via_conn_send") == ["submit_batch"]
    assert kinds("mod:via_local") == ["many", "one"]
    assert kinds("mod:parametric") == []
    assert [p for p, _n in idx.functions["mod:parametric"].msg_param_sends] == [
        "msg_kind"
    ]


def test_msg_compare_extraction_recv_rooted_only(tmp_path):
    idx = make_index(
        tmp_path,
        {
            "mod.py": """
                def serve(conn, reader):
                    msg = conn.recv()
                    kind = msg[0]
                    if kind == "a":
                        pass
                    if msg[0] != "b":
                        pass
                    for m in reader.read_available():
                        if m[0] == "c":
                            pass

                def unpack(conn):
                    kind, info = conn.recv()
                    assert kind == "ack"

                def helper(msg):
                    if msg[0] == "promoted":
                        pass

                def not_wire(locator, spec):
                    if locator[0] == "inline":
                        pass
                    if spec["kind"] == "task":
                        pass
            """,
        },
    )
    serve = idx.functions["mod:serve"]
    assert sorted(m.kind for m in serve.msg_compares) == ["a", "b", "c"]
    assert all(m.root == "recv" for m in serve.msg_compares)
    unpack = idx.functions["mod:unpack"]
    assert [m.kind for m in unpack.msg_compares] == ["ack"]
    helper = idx.functions["mod:helper"]
    assert [(m.kind, m.root) for m in helper.msg_compares] == [
        ("promoted", ("msg", "msg"))
    ]
    # `locator[0] == "inline"` is recorded only as a DORMANT param
    # compare (promoted solely by a recv-rooted caller — none exists);
    # the string-key spec compare is not recorded at all
    nw = idx.functions["mod:not_wire"]
    assert [(m.kind, m.root) for m in nw.msg_compares] == [
        ("inline", ("msg", "locator"))
    ]


def test_lockfree_registry_collected(tmp_path):
    idx = make_index(
        tmp_path,
        {
            "mod.py": """
                LOCKFREE = ("Owner._attr: atomic", "_global",)
            """,
        },
    )
    decls = idx.lockfree_decls()
    assert len(decls) == 1
    module, entries, _node, _ctx = decls[0]
    assert module == "mod"
    assert entries == ["Owner._attr: atomic", "_global"]


def test_tuple_kind_local_invalidated_on_rebind(tmp_path):
    # a local rebound to a non-kind value must not keep reporting the
    # old kind at later sends (phantom RL019 sends)
    idx = make_index(
        tmp_path,
        {
            "mod.py": """
                def relay(conn):
                    msg = ("hello", 1)
                    conn.send(msg)
                    msg = conn.recv()
                    conn.send(msg)
            """,
        },
    )
    f = idx.functions["mod:relay"]
    assert [k for k, _n in f.msg_sends] == ["hello"]


def test_ctor_typed_lock_seen_from_method_above_init(tmp_path):
    # __init__ scans first regardless of source position, so the ctor
    # evidence reaches a lexically-earlier method's with-block
    idx = make_index(
        tmp_path,
        {
            "mod.py": """
                import threading

                class Ctx:
                    def flush(self):
                        with self._submit_send:
                            pass

                    def __init__(self):
                        self._submit_send = threading.Lock()
            """,
        },
    )
    f = idx.functions["mod:Ctx.flush"]
    assert [a.chain for a in f.acquisitions] == [("self", "_submit_send")]


# ------------------------------------------------------- mesh/SPMD extraction


def test_mesh_axes_resolution_chain(tmp_path):
    """The RL020/RL021 axis universe: Mesh positional/kwarg literals,
    tuple(NAME) unwrapping, module string-tuple globals with one
    import-following hop, make_*mesh factory kwonly defaults resolved
    cross-module, and parameter meshes staying opaque (ANY)."""
    from ray_tpu._lint import spmd

    idx = make_index(
        tmp_path,
        {
            "pkg/__init__.py": "",
            "pkg/meshlib.py": """
                AXES = ("dp", "tp")

                def make_mesh(config, *, devices=None, axis_names=AXES):
                    from jax.sharding import Mesh
                    return Mesh(devices, axis_names=tuple(axis_names))
            """,
            "pkg/use.py": """
                import jax
                import numpy as np
                from jax.sharding import Mesh
                from jax.experimental.shard_map import shard_map
                from pkg.meshlib import make_mesh, AXES

                def body_a(x):
                    return x

                def body_b(x):
                    return x

                def body_c(x):
                    return x

                def body_d(x):
                    return x

                def use_positional(x):
                    mesh = Mesh(np.array(jax.devices()), ("data",))
                    return shard_map(body_a, mesh=mesh, in_specs=None, out_specs=None)(x)

                def use_import_table(x):
                    mesh = Mesh(np.array(jax.devices()), AXES)
                    return shard_map(body_b, mesh=mesh, in_specs=None, out_specs=None)(x)

                def use_factory(cfg, x):
                    mesh = make_mesh(cfg)
                    return shard_map(body_c, mesh=mesh, in_specs=None, out_specs=None)(x)

                def use_param(mesh, x):
                    return shard_map(body_d, mesh=mesh, in_specs=None, out_specs=None)(x)
            """,
        },
    )
    model = spmd.get_model(idx)
    assert model.envs["pkg.use:body_a"] == {"data"}
    assert model.envs["pkg.use:body_b"] == {"dp", "tp"}
    # factory call resolves to the kwonly default, itself a module global
    assert model.envs["pkg.use:body_c"] == {"dp", "tp"}
    # parameter mesh: opaque — suppresses, never fires
    assert model.envs["pkg.use:body_d"] is spmd.ANY
    # the owner scopes got the same envs (nested-body folding support)
    assert model.envs["pkg.use:use_positional"] == {"data"}
    assert model.envs["pkg.use:use_param"] is spmd.ANY


def test_collective_extraction_forms(tmp_path):
    idx = make_index(
        tmp_path,
        {
            "m.py": """
                import jax
                from ray_tpu.jax_compat import axis_size

                def f(x, axis_name="sp"):
                    a = jax.lax.psum(x, "dp")
                    b = jax.lax.pmean(x, ("dp", "fsdp"))
                    c = jax.lax.ppermute(x, axis_name, [(0, 1)])
                    d = axis_size(axis_name)
                    e = jax.lax.psum(x, pick_axis())   # dynamic: not recorded
                    return a + b + c + d + e
            """,
        },
    )
    cs = idx.functions["m:f"].collectives
    got = {(c.op, c.axes, c.axis_param) for c in cs}
    assert ("psum", ("dp",), None) in got
    assert ("pmean", ("dp", "fsdp"), None) in got
    assert ("ppermute", (), "axis_name") in got
    assert ("axis_size", (), "axis_name") in got
    assert len(cs) == 4  # the dynamic-axis psum was not invented


def test_spec_literal_extraction(tmp_path):
    idx = make_index(
        tmp_path,
        {
            "m.py": """
                from jax.sharding import PartitionSpec as P

                def f(batch_axes):
                    spec = P(("dp", "fsdp"), "tp", None)
                    splat = P(*batch_axes)
                    dyn = P(batch_axes[0])
                    return spec, splat, dyn
            """,
        },
    )
    info = idx.functions["m:f"]
    entries = {s.entries for s in info.spec_sites}
    assert (("dp", "fsdp"), "tp", None) in entries
    assert ("*",) in entries
    assert ("?",) in entries
    assert "spec" in info.spec_locals  # name -> P(...) bind for in_specs use


def test_pallas_site_extraction_inline_and_gridspec_local(tmp_path):
    idx = make_index(
        tmp_path,
        {
            "m.py": """
                import functools
                import jax
                from jax.experimental import pallas as pl
                from jax.experimental.pallas import tpu as pltpu

                def _interp():
                    return True

                def _kernel(x_ref, o_ref):
                    o_ref[...] = x_ref[...]

                def inline(x, bq):
                    grid = (4, 8)
                    return pl.pallas_call(
                        functools.partial(_kernel, bq),
                        grid=grid,
                        in_specs=[pl.BlockSpec((8, 128), lambda i, j: (i, j))],
                        out_specs=pl.BlockSpec((8, 128), lambda i, j: (i, j)),
                        out_shape=jax.ShapeDtypeStruct((32, 1024), "float32"),
                        interpret=_interp(),
                    )(x)

                def prefetched(x):
                    grid_spec = pltpu.PrefetchScalarGridSpec(
                        num_scalar_prefetch=2,
                        grid=(4,),
                        in_specs=[pl.BlockSpec((1, 8), lambda s, t, i: (i, 0))],
                        out_specs=pl.BlockSpec((1, 8), lambda s, t, i: (i, 0)),
                    )
                    return pl.pallas_call(_kernel, grid_spec=grid_spec)(x)
            """,
        },
    )
    (site,) = idx.functions["m:inline"].pallas_sites
    assert site.kernel_chain == ("_kernel",)        # partial-unwrapped
    assert site.grid_rank == 2                      # grid=grid local tuple
    assert site.interpret == "dynamic"
    assert site.interpret_chain == ("_interp",)
    assert site.out_shape_dims == (32, 1024)
    assert {(b.role, b.block_shape, b.index_map_arity) for b in site.block_specs} == {
        ("in", (8, 128), 2),
        ("out", (8, 128), 2),
    }
    (psite,) = idx.functions["m:prefetched"].pallas_sites
    assert psite.scalar_grid and psite.num_scalar_prefetch == 2
    assert psite.grid_rank == 1                     # via the grid_spec local
    assert psite.interpret == "absent"
    assert {b.index_map_arity for b in psite.block_specs} == {3}


def test_dma_handle_binds_recorded(tmp_path):
    idx = make_index(
        tmp_path,
        {
            "m.py": """
                from jax.experimental.pallas import tpu as pltpu

                def kernel(src, dst, send, recv):
                    rdma = pltpu.make_async_remote_copy(
                        src_ref=src, dst_ref=dst, send_sem=send,
                        recv_sem=recv, device_id=1,
                    )
                    rdma.start()
                    rdma.wait()
            """,
        },
    )
    binds = idx.functions["m:kernel"].dma_binds
    assert [name for name, _ in binds] == ["rdma"]


def test_jit_shard_map_composition_forms(tmp_path):
    """Satellite: the jit registry sees THROUGH composition so RL013/RL014
    keep working on multi-chip code — jit(shard_map(f, ...)) and
    shard_map(jax.jit(f), ...) both resolve to f with merged fields."""
    idx = make_index(
        tmp_path,
        {
            "m.py": """
                import jax
                from jax.experimental.shard_map import shard_map

                def step(p, b):
                    return p

                def outer_jit(p, b, mesh):
                    f = jax.jit(
                        shard_map(step, mesh=mesh, in_specs=None, out_specs=None),
                        donate_argnums=(0,),
                    )
                    return f(p, b)

                def inner_jit(p, b, mesh):
                    g = shard_map(
                        jax.jit(step, static_argnames=("b",)),
                        mesh=mesh, in_specs=None, out_specs=None,
                    )
                    return g(p, b)
            """,
        },
    )
    sites = {
        (s.wrapper, s.composed_with): s
        for s, owner in idx.jit_sites
        if s.composed_with is not None
    }
    outer = sites[("jit", "shard_map")]
    assert outer.target_chain == ("step",)
    assert outer.donate_argnums == (0,)
    assert outer.mesh_expr is not None          # specs lifted from the inner
    assert outer.wrappers() == {"jit", "shard_map"}
    inner = sites[("shard_map", "jit")]
    assert inner.target_chain == ("step",)
    assert inner.static_argnames == ("b",)      # statics lifted from the inner
    assert inner.mesh_expr is not None


def test_placement_extraction_kinds(tmp_path):
    idx = make_index(
        tmp_path,
        {
            "m.py": """
                import jax
                import numpy as np
                from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

                def f(x, mesh, dev):
                    a = jax.device_put(np.zeros((4, 2)))
                    s = NamedSharding(mesh, P("dp", None))
                    b = jax.device_put(x, s)
                    c = jax.device_put(x, NamedSharding(mesh, P("dp")))
                    d = jax.device_put(np.zeros((4,)), jax.sharding.SingleDeviceSharding(dev))
                    return a, b, c, d
            """,
        },
    )
    by_name = {
        p.bound_names[0]: p for p in idx.functions["m:f"].placements
    }
    assert by_name["a"].sharding == "absent"
    assert by_name["a"].operand_rank == 2
    assert by_name["b"].sharding == "named"     # via the NamedSharding local
    assert by_name["c"].sharding == "named"
    assert by_name["c"].spec_rank == 1
    assert by_name["d"].sharding == "single"
    assert by_name["d"].operand_rank == 1


def test_str_tuples_and_interpret_only_registry(tmp_path):
    idx = make_index(
        tmp_path,
        {
            "m.py": """
                AXES = ("dp", "fsdp", "tp")
                NOT_STRS = (1, 2)

                INTERPRET_ONLY = (
                    "_decode_pallas: compiled path unvalidated off-TPU",
                )
            """,
        },
    )
    mi = idx.modules["m"]
    assert mi.str_tuples["AXES"] == ("dp", "fsdp", "tp")
    assert "NOT_STRS" not in mi.str_tuples
    decls = idx.interpret_only_decls()
    assert len(decls) == 1
    module, entries, _anchor, _ctx = decls[0]
    assert module == "m" and entries[0].startswith("_decode_pallas:")
