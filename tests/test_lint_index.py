"""raylint phase-1 (project index) unit suite.

The cross-module rules are only as good as the index underneath them, so
the index's resolution machinery is pinned directly: per-module symbol
tables (imports incl. relative), the jit registry across all wrapping
forms (decorator / ``partial`` decorator / assignment / inline call),
attribute mutability classification, attr→class resolution (constructor,
annotation, and cross-module constructor CALL SITES), owner-qualified
lock keys, transitive lock sets, daemon-thread reachability, and the
observability-name extraction RL012 consumes.
"""

import ast
import textwrap

from ray_tpu._lint.core import FileContext
from ray_tpu._lint.index import build_index, module_name_for


def make_index(tmp_path, files, display_root=None):
    """files: {relative_path: source} -> ProjectIndex over all of them."""
    ctxs = []
    for rel, src in files.items():
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        text = textwrap.dedent(src)
        p.write_text(text)
        ctxs.append(FileContext(p, rel, text, ast.parse(text)))
    return build_index(ctxs, display_root=display_root)


# ------------------------------------------------------------ module names


def test_module_name_for():
    assert module_name_for("ray_tpu/llm/engine.py") == "ray_tpu.llm.engine"
    assert module_name_for("ray_tpu/llm/__init__.py") == "ray_tpu.llm"
    assert module_name_for("pkg/mod.py") == "pkg.mod"


def test_relative_import_resolution(tmp_path):
    idx = make_index(
        tmp_path,
        {
            "pkg/__init__.py": "from .engine import Engine\n",
            "pkg/engine.py": "from .cache import Pool\n\nclass Engine:\n    pass\n",
            "pkg/cache.py": "class Pool:\n    pass\n",
        },
    )
    assert idx.modules["pkg.engine"].imports["Pool"] == "pkg.cache.Pool"
    # package __init__ anchors at the package itself, not its parent
    assert idx.modules["pkg"].imports["Engine"] == "pkg.engine.Engine"


# ------------------------------------------------------------ jit registry


def test_jit_registry_all_forms(tmp_path):
    idx = make_index(
        tmp_path,
        {
            "m.py": """
                import functools

                import jax
                from functools import partial

                @jax.jit
                def decorated(x):
                    return x

                @partial(jax.jit, static_argnums=(1,))
                def partial_decorated(x, n):
                    return x

                def plain(x):
                    return x

                module_level = jax.jit(plain, static_argnames=("n",))
                via_partial = jax.jit(functools.partial(plain, 1))

                class Runner:
                    def __init__(self):
                        self._step = jax.jit(self._impl, donate_argnums=(0,))

                    def _impl(self, pool):
                        return pool
            """,
        },
    )
    resolved = {}
    for site, owner in idx.jit_sites:
        target = idx.resolve_jit_target(site, owner)
        if target is not None:
            resolved[target.qualname] = site
    assert "decorated" in resolved
    assert "partial_decorated" in resolved
    assert resolved["partial_decorated"].static_argnums == (1,)
    assert "plain" in resolved  # assignment AND partial form both hit it
    assert "Runner._impl" in resolved
    module_site = next(
        s for s, _ in idx.jit_sites if s.target_chain == ("plain",)
        and s.static_argnames
    )
    assert module_site.static_argnames == ("n",)


# ------------------------------------------------- attribute classification


ATTR_SRC = {
    "m.py": """
        import numpy as np

        class Runner:
            def __init__(self, params: dict, block_size: int, arch="gpt",
                         table=None):
                self.params = params
                self.block_size = block_size
                self.arch = arch
                self.table = table
                self.buf = np.zeros(4)
                self.mode = "fast"
                self.counter = 0

            def tweak(self):
                self.counter = 1
    """,
}


def test_attr_kinds(tmp_path):
    idx = make_index(tmp_path, ATTR_SRC)
    cls = idx.classes[("m", "Runner")]
    assert cls.attr_kind("params") == "mutable"      # name + dict annotation
    assert cls.attr_kind("block_size") == "static"   # int annotation
    assert cls.attr_kind("arch") == "static"         # str default
    assert cls.attr_kind("buf") == "mutable"         # array constructor
    assert cls.attr_kind("mode") == "static"         # literal
    assert cls.attr_kind("counter") == "mutable"     # reassigned after init
    assert cls.attr_kind("table") == "unknown"       # no evidence: no fire


def test_cross_module_mutation_marks_mutable(tmp_path):
    idx = make_index(
        tmp_path,
        {
            "runner.py": """
                class Runner:
                    def __init__(self, weights_in):
                        self.store = weights_in
            """,
            "engine.py": """
                from runner import Runner

                class Engine:
                    def __init__(self):
                        self.runner = Runner({})

                    def swap(self, new):
                        self.runner.store = new
            """,
        },
    )
    cls = idx.classes[("runner", "Runner")]
    assert cls.attr_kind("store") == "mutable"


# ------------------------------------------------------- class resolution


def test_attr_class_from_ctor_and_callsite(tmp_path):
    idx = make_index(
        tmp_path,
        {
            "cache.py": """
                import threading

                class Pool:
                    def __init__(self):
                        self._lock = threading.Lock()

                    def free(self):
                        with self._lock:
                            return 1
            """,
            "engine.py": """
                import threading

                from cache import Pool
                from watch import Watchdog

                class Engine:
                    def __init__(self):
                        self._lock = threading.Lock()
                        self.pool = Pool()
                        self.watchdog = Watchdog(self)
            """,
            "watch.py": """
                class Watchdog:
                    def __init__(self, engine):
                        self.engine = engine
            """,
        },
    )
    eng = idx.classes[("engine", "Engine")]
    assert eng.attr_classes["pool"] == ("cache", "Pool")
    # ctor CALL SITE inference: Watchdog(self) binds engine -> Engine
    wd = idx.classes[("watch", "Watchdog")]
    assert wd.attr_classes["engine"] == ("engine", "Engine")


def test_lock_key_resolution(tmp_path):
    idx = make_index(
        tmp_path,
        {
            "cache.py": """
                import threading

                _GLOBAL_LOCK = threading.Lock()

                class Pool:
                    def __init__(self):
                        self._lock = threading.Lock()

                    def free(self):
                        with self._lock:
                            with _GLOBAL_LOCK:
                                return 1
            """,
            "engine.py": """
                import threading

                from cache import Pool

                class Engine:
                    def __init__(self):
                        self._lock = threading.Lock()
                        self.pool = Pool()

                    def step(self):
                        with self._lock:
                            with self.pool._lock:
                                return 1
            """,
        },
    )
    eng = idx.classes[("engine", "Engine")]
    step = eng.methods["step"]
    keys = [idx.lock_key(a.chain, step) for a in step.acquisitions]
    assert keys == ["Engine._lock", "Pool._lock"]
    pool_free = idx.classes[("cache", "Pool")].methods["free"]
    keys = [idx.lock_key(a.chain, pool_free) for a in pool_free.acquisitions]
    assert keys == ["Pool._lock", "cache._GLOBAL_LOCK"]


def test_local_attr_alias_resolves(tmp_path):
    # `sched = self.scheduler; sched.admit()` must resolve like the
    # spelled-out chain — the engine step loop is written in this style
    idx = make_index(
        tmp_path,
        {
            "s.py": """
                import threading

                class Sched:
                    def __init__(self):
                        self._lock = threading.Lock()

                    def admit(self):
                        with self._lock:
                            return 1

                class Engine:
                    def __init__(self):
                        self.scheduler = Sched()

                    def step(self):
                        sched = self.scheduler
                        return sched.admit()
            """,
        },
    )
    eng = idx.classes[("s", "Engine")]
    step = eng.methods["step"]
    locks = idx.trans_lock_acqs(step)
    assert any(k == "Sched._lock" for k, _b, _f, _l in locks)


def test_trans_locks_cross_module_and_bounded(tmp_path):
    idx = make_index(
        tmp_path,
        {
            "a.py": """
                import threading

                class A:
                    def __init__(self):
                        self._lock = threading.Lock()

                    def locked(self):
                        with self._lock:
                            return 1

                    def bounded(self):
                        got = self._lock.acquire(timeout=0.1)
                        if got:
                            self._lock.release()
            """,
            "b.py": """
                from a import A

                class B:
                    def __init__(self):
                        self.a = A()

                    def call_locked(self):
                        return self.a.locked()

                    def call_bounded(self):
                        return self.a.bounded()
            """,
        },
    )
    b = idx.classes[("b", "B")]
    via_locked = idx.trans_lock_acqs(b.methods["call_locked"])
    assert ("A._lock", False) in {(k, bd) for k, bd, _f, _l in via_locked}
    via_bounded = idx.trans_lock_acqs(b.methods["call_bounded"])
    assert all(bd for _k, bd, _f, _l in via_bounded)  # bounded only


def test_daemon_reachability(tmp_path):
    idx = make_index(
        tmp_path,
        {
            "w.py": """
                import threading

                class W:
                    def start(self):
                        self._t = threading.Thread(target=self._run, daemon=True)
                        self._j = threading.Thread(target=self._joined)

                    def _run(self):
                        self._tick()

                    def _tick(self):
                        return 1

                    def _joined(self):
                        return 3

                    def not_a_thread(self):
                        return 2
            """,
        },
    )
    reach = idx.daemon_reachable()
    assert "w:W._run" in reach
    assert "w:W._tick" in reach      # transitively
    assert "w:W.not_a_thread" not in reach
    # a non-daemon (join()ed, short-lived) thread is not a monitor: RL011's
    # contract is about daemon/watchdog threads only
    assert "w:W._joined" not in reach


def test_trans_locks_complete_despite_call_cycle(tmp_path):
    # memo regression: a traversal truncated by a call cycle must not be
    # cached as final — with early() scanned first (poisoning the memo for
    # g via the truncated f<->g recursion), a later top-level query for
    # late()'s locks must still see CV through f -> g
    idx = make_index(
        tmp_path,
        {
            "c.py": """
                import threading

                OUTER_LOCK = threading.Lock()
                OTHER_LOCK = threading.Lock()
                CV = threading.Lock()

                def early():
                    with OTHER_LOCK:
                        f()

                def f():
                    g()

                def g():
                    with CV:
                        f()

                def late():
                    with OUTER_LOCK:
                        f()
            """,
        },
    )
    mi = idx.modules["c"]
    # query in scan order so the cycle-truncated path runs first
    idx.trans_lock_acqs(mi.functions["early"])
    late_locks = {k for k, _b, _f, _l in idx.trans_lock_acqs(mi.functions["late"])}
    assert "c.CV" in late_locks


# ------------------------------------------------- observability extraction


def test_emit_and_registry_extraction(tmp_path):
    md = tmp_path / "OBSERVABILITY.md"
    md.write_text("| `llm.*` | `submit`, `finish` |\n`llm_documented_metric`\n")
    idx = make_index(
        tmp_path,
        {
            "m.py": """
                from collections import Counter as CollectionsCounter

                from ray_tpu._private import events as _events
                from ray_tpu.util.metrics import Counter, Gauge

                METRIC_NAMES = ("m_one", "m_two")
                EVENT_NAMES = ("sys.boot",)
                LOCK_ORDER = ("Engine._lock", "Pool._lock")

                c = Counter("m_one", "doc")
                g = Gauge("m_two", "doc")
                histo = CollectionsCounter(["not", "a", "metric"])
                _events.record("sys.boot", n=1)
                panel = "rate(ray_tpu_m_one[1m])"
            """,
        },
        display_root=tmp_path,
    )
    metric_names = {s.name for s, _f in idx.emits if s.kind == "metric"}
    event_names = {s.name for s, _f in idx.emits if s.kind == "event"}
    assert metric_names == {"m_one", "m_two"}  # collections.Counter excluded
    assert event_names == {"sys.boot"}
    regs = idx.registries("METRIC_NAMES")
    assert regs and regs[0][1] == ["m_one", "m_two"]
    orders = idx.lock_orders()
    assert orders and orders[0][1] == ["Engine._lock", "Pool._lock"]
    assert ("m_one") in {n for n, _node, _mi in idx.prom_refs()}
    # doc names parsed from the markdown at display_root
    assert "llm.*" in idx.doc_names and "submit" in idx.doc_names
    assert "llm_documented_metric" in idx.doc_names
