"""Arrival-curve + client-summary unit tests for the open-loop load
harness (``llm.loadgen``).  The full served-path run is exercised by the
``loadgen-smoke`` CI job (it boots a serve cluster); these pin the pure
parts — the schedule math that makes the harness open-loop, and the
client-side summary the LOADGEN artifact reports.  No jax, no cluster.
"""

from ray_tpu.llm import loadgen


def test_constant_curve_spacing():
    offs = loadgen.arrivals("constant", rate=10.0, duration_s=2.0)
    assert len(offs) == 20
    gaps = [b - a for a, b in zip(offs, offs[1:])]
    assert all(abs(g - 0.1) < 1e-9 for g in gaps)


def test_poisson_curve_seeded_and_bounded():
    a = loadgen.arrivals("poisson", rate=50.0, duration_s=4.0, seed=7)
    b = loadgen.arrivals("poisson", rate=50.0, duration_s=4.0, seed=7)
    c = loadgen.arrivals("poisson", rate=50.0, duration_s=4.0, seed=8)
    assert a == b  # reproducible schedules: same run is the same run
    assert a != c
    assert all(0.0 <= t < 4.0 for t in a)
    assert a == sorted(a)
    # law of large numbers, generous: ~200 expected
    assert 120 < len(a) < 300


def test_ramp_curve_densifies():
    offs = loadgen.arrivals("ramp", rate=5.0, duration_s=10.0, ramp_to=50.0)
    assert offs == sorted(offs)
    assert all(0.0 <= t <= 10.0 for t in offs)
    first_half = sum(1 for t in offs if t < 5.0)
    second_half = len(offs) - first_half
    # the rate grows: the back half must carry well more arrivals
    assert second_half > first_half * 1.5


def test_burst_curve_clump():
    offs = loadgen.arrivals("burst", rate=2.0, duration_s=4.0, burst_n=30)
    assert offs == sorted(offs)
    assert sum(1 for t in offs if t == 2.0) >= 30  # the clump, together


def test_unknown_curve_rejected():
    import pytest

    with pytest.raises(ValueError):
        loadgen.arrivals("sawtooth", rate=1.0, duration_s=1.0)


def test_empty_curves():
    assert loadgen.arrivals("constant", rate=0.0, duration_s=5.0) == []
    assert loadgen.arrivals("poisson", rate=10.0, duration_s=0.0) == []


def test_summarize_client_status_mix():
    recs = (
        [{"status": 200, "e2e_s": 0.1 * i, "ttft_s": 0.01} for i in range(1, 5)]
        + [{"status": 429, "e2e_s": 0.01} for _ in range(4)]
        + [{"status": 0, "error": "ConnectionError", "e2e_s": 0.0}]
    )
    s = loadgen.summarize_client(recs, duration_s=2.0)
    assert s["requests"] == 9 and s["ok"] == 4 and s["errors"] == 1
    assert s["shed_429"] == 4
    assert abs(s["shed_rate"] - 4 / 9) < 1e-3  # rounded to 4 decimals
    assert s["offered_rate_rps"] == 4.5
    # percentiles come from the SUCCESSFUL streams only — shed 429s must
    # not dilute the latency distribution they were shed to protect
    assert s["e2e_s"]["count"] == 4
    assert s["e2e_s"]["p50"] in (0.2, 0.3)
    assert s["ttft_s"]["count"] == 4
