"""RL library tests: unit coverage for GAE/replay/vector-env semantics plus
learning-threshold tests (the reference gates algorithms on reaching a target
reward — ``rllib/tuned_examples/``, ``release/rllib_tests/README.rst``)."""

import numpy as np
import pytest

import ray_tpu
from ray_tpu.rl import (
    DQNConfig,
    IMPALAConfig,
    PPOConfig,
    PrioritizedReplayBuffer,
    ReplayBuffer,
    SampleBatch,
    SyncVectorEnv,
    compute_gae,
)
from ray_tpu.rl import sample_batch as sb


# ---------------------------------------------------------------------------
# unit: GAE
# ---------------------------------------------------------------------------


def test_gae_terminated_zeroes_bootstrap():
    T, N = 3, 1
    rewards = np.ones((T, N), np.float32)
    values = np.zeros((T, N), np.float32)
    term = np.zeros((T, N), bool)
    term[-1] = True
    trunc = np.zeros((T, N), bool)
    last_values = np.full((N,), 100.0, np.float32)  # must be ignored: terminated
    adv, targets = compute_gae(rewards, values, term, trunc, last_values, gamma=1.0, lam=1.0)
    # terminal step: delta = r = 1; no bootstrap of the 100
    assert adv[-1, 0] == pytest.approx(1.0)
    assert adv[0, 0] == pytest.approx(3.0)  # 1+1+1, undiscounted


def test_gae_truncated_bootstraps_true_final_value():
    T, N = 2, 1
    rewards = np.zeros((T, N), np.float32)
    values = np.zeros((T, N), np.float32)
    term = np.zeros((T, N), bool)
    trunc = np.zeros((T, N), bool)
    trunc[0] = True  # episode cut at t=0
    last_values = np.zeros((N,), np.float32)
    # Without truncation_values the recursion would bootstrap values[1] (the
    # RESET state's value, = 0 here). With it, the true final value (5.0).
    tv = np.zeros((T, N), np.float32)
    tv[0] = 5.0
    adv, _ = compute_gae(
        rewards, values, term, trunc, last_values, gamma=0.5, lam=1.0, truncation_values=tv
    )
    assert adv[0, 0] == pytest.approx(0.5 * 5.0)
    # and the recursion is CUT at the boundary: t=0 advantage excludes t=1
    adv2, _ = compute_gae(
        rewards + 1.0, values, term, trunc, last_values, gamma=1.0, lam=1.0, truncation_values=tv
    )
    assert adv2[0, 0] == pytest.approx(1.0 + 5.0)


# ---------------------------------------------------------------------------
# unit: replay buffers
# ---------------------------------------------------------------------------


def _batch(n, base=0):
    return SampleBatch(
        {
            sb.OBS: np.arange(base, base + n, dtype=np.float32)[:, None],
            sb.ACTIONS: np.zeros(n, np.int64),
        }
    )


def test_replay_buffer_ring_overwrites_oldest():
    buf = ReplayBuffer(capacity=4, seed=0)
    buf.add(_batch(3))          # 0,1,2
    assert len(buf) == 3
    buf.add(_batch(3, base=10))  # 10,11,12 -> wraps, overwrites 0,1
    assert len(buf) == 4
    live = set(buf._store[sb.OBS][:, 0].tolist())
    assert live == {2.0, 10.0, 11.0, 12.0}


def test_prioritized_replay_uses_per_sample_priorities():
    buf = PrioritizedReplayBuffer(capacity=16, alpha=1.0, beta=1.0, seed=0)
    buf.add(_batch(8))
    # crank one index's priority way up
    buf.update_priorities(np.array([3]), np.array([1000.0]))
    counts = np.zeros(8)
    for _ in range(50):
        out = buf.sample(4)
        for i in out["batch_indexes"]:
            counts[i] += 1
    assert counts[3] == counts.max() and counts[3] > counts.sum() * 0.8
    # IS weights: the hot sample must get the SMALLEST weight
    out = buf.sample(8)
    w = {int(i): float(x) for i, x in zip(out["batch_indexes"], out["weights"])}
    if 3 in w and len(w) > 1:
        assert w[3] == min(w.values())


# ---------------------------------------------------------------------------
# unit: vector env final-obs semantics
# ---------------------------------------------------------------------------


def test_vector_env_returns_pre_reset_final_obs():
    from ray_tpu.rl.env import GridWorldEnv

    vec = SyncVectorEnv(lambda: GridWorldEnv(n=3), 1, seed=0)
    vec.reset()
    # two rights reach the goal (pos 2 = n-1): terminated
    obs, rew, term, trunc, final = vec.step(np.array([1]))
    assert not term[0]
    assert final[0, 0] == obs[0, 0] == 1.0
    obs, rew, term, trunc, final = vec.step(np.array([1]))
    assert term[0]
    assert final[0, 0] == 2.0      # the TRUE terminal obs
    assert obs[0, 0] == 0.0        # auto-reset obs the policy acts on next


def test_dqn_transitions_store_true_next_obs():
    from ray_tpu.rl.env import GridWorldEnv
    from ray_tpu.rl.env_runner import EnvRunner
    from ray_tpu.rl.rl_module import QModule

    r = EnvRunner(lambda: GridWorldEnv(n=3), num_envs=1, seed=0, module_cls=QModule)
    r.set_epsilon(0.5)  # explore so some episodes actually terminate at goal
    batch = r.sample_transitions(200)
    term = batch[sb.TERMINATEDS]
    # every TERMINATED transition's next_obs must be the goal state (pos 2),
    # never the auto-reset obs (pos 0)
    assert term.any()
    assert (batch[sb.NEXT_OBS][term][:, 0] == 2.0).all()
    assert sb.TRUNCATEDS in batch


# ---------------------------------------------------------------------------
# smoke: one training_step per algorithm (local mode)
# ---------------------------------------------------------------------------


def test_ppo_training_step_smoke():
    algo = (
        PPOConfig()
        .environment("CartPole-v1")
        .env_runners(num_env_runners=0, num_envs_per_env_runner=4, rollout_fragment_length=32)
        .training(train_batch_size=128, minibatch_size=64, num_epochs=2)
        .build()
    )
    try:
        result = algo.train()
        assert result["training_iteration"] == 1
        assert result["timesteps_total"] >= 128
        assert "learner/policy_loss" in result
    finally:
        algo.stop()


def test_dqn_training_step_smoke_prioritized():
    algo = (
        DQNConfig()
        .environment("CartPole-v1")
        .env_runners(num_env_runners=0, num_envs_per_env_runner=4, rollout_fragment_length=32)
        .training(
            train_batch_size=32,
            prioritized_replay=True,
            learning_starts=64,
            sample_steps_per_iter=128,
            updates_per_iter=4,
        )
        .build()
    )
    try:
        algo.train()
        result = algo.train()
        assert "learner/td_error_mean" in result
        # per-sample priorities: after updates the priority table must hold
        # MANY distinct values, not one batch-mean scalar
        prio = algo.buffer._prio[: len(algo.buffer)]
        touched = prio[prio != 1.0]
        assert len(np.unique(touched)) > 4
        # td_abs must not leak into reported metrics
        assert "learner/td_abs" not in result
    finally:
        algo.stop()


def test_impala_training_step_smoke_local():
    algo = (
        IMPALAConfig()
        .environment("CartPole-v1")
        .env_runners(num_env_runners=0, num_envs_per_env_runner=4, rollout_fragment_length=16)
        .training(train_batch_size=64)
        .build()
    )
    try:
        result = algo.train()
        assert "learner/policy_loss" in result
        assert result["timesteps_total"] >= 64
    finally:
        algo.stop()


def test_vtrace_reduces_to_discounted_returns_on_policy():
    """With target==behavior logp (rho=1) and exact values=0, vs must equal
    discounted returns — the standard V-trace sanity identity."""
    import jax.numpy as jnp

    from ray_tpu.rl.algorithms.impala import vtrace

    N, T = 1, 4
    logp = jnp.zeros((N, T))
    rewards = jnp.ones((N, T))
    dones = jnp.zeros((N, T))
    values = jnp.zeros((N, T))
    boot = jnp.zeros((N,))
    vs, pg_adv = vtrace(logp, logp, rewards, dones, values, boot, 0.5, 1.0, 1.0)
    expect = [1 + 0.5 * (1 + 0.5 * (1 + 0.5 * 1)), 1 + 0.5 * (1 + 0.5 * 1), 1.5, 1.0]
    np.testing.assert_allclose(np.asarray(vs)[0], expect, rtol=1e-5)


# ---------------------------------------------------------------------------
# learning tests (reference: rllib/tuned_examples — reward threshold gates)
# ---------------------------------------------------------------------------


def _run_until(algo, key, threshold, max_iters):
    best = -np.inf
    for _ in range(max_iters):
        result = algo.train()
        v = result.get(key)
        if v is not None:
            best = max(best, v)
            if v >= threshold:
                return v, result["timesteps_total"]
    return best, None


# tier-1 budget (ISSUE 20): 10.3s measured — suite growth pushed the 870s
# command past its wall clock, so the heaviest learning (convergence) tests
# ride the slow tier; test_ppo_training_step_smoke keeps PPO mechanics in
# tier-1
@pytest.mark.slow
def test_ppo_learns_cartpole():
    """PPO must reach mean episode return >= 200 on CartPole-v1 (random play
    scores ~20) within a bounded budget — mirrors
    ``rllib/tuned_examples/ppo/cartpole-ppo.yaml`` (threshold scaled down to
    keep CI wall-clock bounded)."""
    algo = (
        PPOConfig()
        .environment("CartPole-v1")
        .env_runners(num_env_runners=0, num_envs_per_env_runner=8, rollout_fragment_length=128)
        .training(
            train_batch_size=2048,
            minibatch_size=256,
            num_epochs=6,
            lr=3e-4,
            entropy_coeff=0.0,
        )
        .debugging(seed=0)
        .build()
    )
    try:
        best, _ = _run_until(algo, "episode_return_mean", 200.0, max_iters=25)
        assert best >= 200.0, f"PPO failed to learn CartPole: best return {best}"
    finally:
        algo.stop()


# tier-1 budget (ISSUE 20): 8.4s measured — convergence rides slow;
# test_dqn_training_step_smoke_prioritized keeps DQN mechanics in tier-1
@pytest.mark.slow
def test_dqn_learns_cartpole():
    """DQN (double-Q + prioritized replay) must clearly beat random play on
    CartPole within a small budget."""
    algo = (
        DQNConfig()
        .environment("CartPole-v1")
        .env_runners(num_env_runners=0, num_envs_per_env_runner=8, rollout_fragment_length=64)
        .training(
            train_batch_size=64,
            prioritized_replay=True,
            learning_starts=500,
            sample_steps_per_iter=512,
            updates_per_iter=64,
            target_update_freq=1000,
            epsilon_decay_steps=10000,
            lr=5e-4,
        )
        .debugging(seed=0)
        .build()
    )
    try:
        best, _ = _run_until(algo, "episode_return_mean", 100.0, max_iters=40)
        assert best >= 100.0, f"DQN failed to learn CartPole: best return {best}"
    finally:
        algo.stop()


# ---------------------------------------------------------------------------
# distributed: async IMPALA + env-runner fault tolerance
# ---------------------------------------------------------------------------


# tier-1 budget (ISSUE 20): 11.2s measured — convergence rides slow;
# test_impala_training_step_smoke_local keeps IMPALA mechanics in tier-1 and
# test_env_runner_fault_tolerance keeps the async-runner plumbing gated
@pytest.mark.slow
def test_impala_async_runners_learn(ray_start_regular):
    """IMPALA with 2 remote env-runner actors: async futures pipeline works
    and the policy improves (loose threshold — the point is the plumbing)."""
    algo = (
        IMPALAConfig()
        .environment("CartPole-v1")
        .env_runners(num_env_runners=2, num_envs_per_env_runner=4, rollout_fragment_length=64)
        .training(train_batch_size=1024, lr=5e-4, entropy_coeff=0.005)
        .debugging(seed=0)
        .build()
    )
    try:
        best, _ = _run_until(algo, "episode_return_mean", 100.0, max_iters=25)
        assert best >= 100.0, f"IMPALA failed to improve on CartPole: best {best}"
    finally:
        algo.stop()


def test_env_runner_fault_tolerance(ray_start_regular):
    """Kill an env-runner actor mid-training: training continues and the
    runner pool is healed (reference: restart_failed_env_runners)."""
    algo = (
        PPOConfig()
        .environment("CartPole-v1")
        .env_runners(num_env_runners=2, num_envs_per_env_runner=2, rollout_fragment_length=32)
        .training(train_batch_size=128, minibatch_size=64, num_epochs=1)
        .build()
    )
    try:
        algo.train()
        victim = algo._runner_actors[0]
        ray_tpu.kill(victim)
        result = algo.train()  # must not raise; dead runner replaced
        assert result["training_iteration"] == 2
        assert algo._runner_actors[0] is not victim
        # healed pool responds
        assert all(algo.foreach_runner("ping"))
    finally:
        algo.stop()


# ---------------------------------------------------------------------------
# SAC (continuous control) + multi-agent env API
# ---------------------------------------------------------------------------


def test_sac_training_step_smoke():
    from ray_tpu.rl.algorithms.sac import SACConfig

    algo = (
        SACConfig()
        .environment("Pendulum-v1")
        .env_runners(num_env_runners=0, num_envs_per_env_runner=2, rollout_fragment_length=32)
        .training(
            learning_starts=64, sample_steps_per_iter=128, updates_per_iter=4,
            train_batch_size=64,
        )
        .build()
    )
    try:
        algo.train()
        result = algo.train()
        assert "learner/q_loss" in result
        assert result["learner/alpha"] > 0
    finally:
        algo.stop()


# tier1-durations: ~186s on the CI box — the full suite overruns the
# 870s tier-1 budget (truncation, not failures; ROADMAP), so the heaviest
# non-LLM learning/scale tests run as @slow instead of being cut at random
@pytest.mark.slow
def test_sac_learns_pendulum():
    """SAC must clearly improve on Pendulum-v1 (random play averages about
    -1200; threshold mirrors rllib/tuned_examples/sac scaled to CI budget)."""
    from ray_tpu.rl.algorithms.sac import SACConfig

    algo = (
        SACConfig()
        .environment("Pendulum-v1")
        .env_runners(num_env_runners=0, num_envs_per_env_runner=8)
        .training(
            learning_starts=800, sample_steps_per_iter=400, updates_per_iter=400,
            train_batch_size=256, lr=3e-4,
        )
        .debugging(seed=0)
        .build()
    )
    try:
        best, _ = _run_until(algo, "episode_return_mean", -350.0, max_iters=40)
        assert best >= -350.0, f"SAC failed to learn Pendulum: best return {best}"
    finally:
        algo.stop()


def test_multi_agent_vector_env_slots():
    from ray_tpu.rl.env import make_vector_env
    from ray_tpu.rl.multi_agent import EchoCoopEnv

    vec = make_vector_env(lambda: EchoCoopEnv(episode_len=4), 3, seed=0)
    assert vec.n == 6  # 3 envs x 2 agents
    obs = vec.reset()
    assert obs.shape == (6, 2)
    # both agents of one env see the same observation
    np.testing.assert_array_equal(obs[0], obs[1])
    # perfect play: action = argmax(obs) (the bit is obs[0])
    acts = obs[:, 0].astype(np.int64) ^ 0  # action == bit
    obs2, rew, term, trunc, final = vec.step(1 - np.argmax(obs, -1))
    np.testing.assert_allclose(rew, 1.5)  # both correct -> 1 + 0.5 each
    # episodes truncate after 4 steps and auto-reset
    for _ in range(3):
        obs2, rew, term, trunc, final = vec.step(np.zeros(6, np.int64))
    assert trunc.all()


# tier-1 budget (ISSUE 20): ~7s measured — convergence rides slow;
# test_multi_agent_vector_env_slots keeps the multi-agent plumbing in tier-1
@pytest.mark.slow
def test_shared_policy_ppo_learns_multi_agent():
    """PPO trains ONE shared policy over all agents of a MultiAgentEnv via
    the slot-flattened vector view; coordination reward improves toward the
    1.5/step optimum."""
    from ray_tpu.rl.multi_agent import EchoCoopEnv

    algo = (
        PPOConfig()
        .environment(lambda: EchoCoopEnv(episode_len=16))
        .env_runners(num_env_runners=0, num_envs_per_env_runner=4, rollout_fragment_length=64)
        .training(train_batch_size=1024, minibatch_size=256, num_epochs=4, lr=1e-3)
        .debugging(seed=0)
        .build()
    )
    try:
        # per-slot episode return: optimum 16*1.5=24; random ~12
        best, _ = _run_until(algo, "episode_return_mean", 20.0, max_iters=25)
        assert best >= 20.0, f"shared-policy PPO failed: best {best}"
    finally:
        algo.stop()
