"""Runtime twins for raylint's mesh/SPMD phase (RL020, RL024).

Per the test_core_races.py precedent: the static rule flags a bug shape,
and the twin PROVES the same shape actually fails (or silently retraces)
on a real multi-device mesh — static and runtime pointing at the same
line. RL020's shape (a collective axis no enclosing shard_map binds)
raises ``NameError: unbound axis name`` at TRACE time; RL024's shape (a
single-device placement flowing into a mesh-jitted call) produces no
exception at all — only a second compile-cache entry, which is exactly
why it needed a lint rule (the PR 13 bug ran for a whole session at 2x
step time before anyone noticed).
"""

import numpy as np
import pytest


def _multi_device_cpu() -> bool:
    """Capability probe: the twins need a >=2-device CPU mesh. The
    suite's conftest forces 8 in-process CPU devices via
    ``XLA_FLAGS=--xla_force_host_platform_device_count`` before jax
    initializes; it cannot use ``jax.config.update("jax_num_cpu_devices",
    8)`` because this jax 0.4.37 build lacks that config option (the
    documented pre-existing environmental failure since PR 9 — see
    ``tests/test_multislice.py::_worker_can_size_cpu_devices``). The
    probe checks the devices actually materialized, without mutating
    anything."""
    import jax

    return len(jax.devices("cpu")) >= 2


pytestmark = pytest.mark.skipif(
    not _multi_device_cpu(),
    reason="needs a >=2-device CPU mesh "
    "(XLA_FLAGS=--xla_force_host_platform_device_count, set by conftest)",
)


def _mesh(n=2):
    import jax
    from jax.sharding import Mesh

    return Mesh(np.array(jax.devices("cpu")[:n]), ("data",))


# --------------------------------------------------------------------- RL020


def test_rl020_unbound_axis_raises_at_trace_time():
    """The RL020 bug shape: ``psum(x, "dp")`` with no enclosing shard_map
    binding "dp" dies the FIRST time the function is traced — i.e. in
    whatever multi-chip path first exercises it, not where the collective
    was written. The static rule moves the diagnostic to the source."""
    import jax
    import jax.numpy as jnp

    def body(x):
        return jax.lax.psum(x, "dp")

    with pytest.raises(NameError, match="unbound axis name"):
        jax.jit(body)(jnp.ones((4,)))


def test_rl020_bound_axis_traces_clean():
    """Positive control: the identical collective under a shard_map whose
    mesh binds the axis traces and runs — it is the BINDING the rule
    checks, not the collective."""
    import jax
    import jax.numpy as jnp
    from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P

    mesh = _mesh(2)

    def body(x):
        return jax.lax.psum(x.sum(), "data")  # local sum, then cross-device

    f = shard_map(body, mesh=mesh, in_specs=P("data"), out_specs=P())
    out = f(jnp.arange(4, dtype=jnp.float32))
    assert float(out) == pytest.approx(0.0 + 1.0 + 2.0 + 3.0)


# --------------------------------------------------------------------- RL024


def test_rl024_single_device_placement_bumps_compile_cache():
    """The RL024 bug shape, live: a jitted fn first called with a
    mesh-placed (NamedSharding) operand, then with the same shape/dtype
    committed to a single device. No error, no warning — just a second
    entry in ``PjitFunction._cache_size``: the committed sharding is part
    of the compile-cache key, so the drifting placement retraces and
    recompiles on call 2. In the PR 13 incident this fired EVERY step."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    mesh = _mesh(2)
    f = jax.jit(lambda b: b * 2.0)
    arr = np.ones((4, 2), np.float32)

    good = jax.device_put(arr, NamedSharding(mesh, P("data")))
    f(good)
    assert f._cache_size() == 1

    bad = jax.device_put(arr, jax.devices("cpu")[0])  # the RL024 placement
    f(bad)
    assert f._cache_size() == 2  # silent recompile — the whole bug


def test_rl024_consistent_placement_reuses_cache():
    """The fixed shape (what shard_train_state does since PR 13): every
    call placed with the same NamedSharding — fresh values, one cache
    entry forever."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    mesh = _mesh(2)
    sharding = NamedSharding(mesh, P("data"))
    g = jax.jit(lambda b: b * 2.0)
    arr = np.ones((4, 2), np.float32)

    g(jax.device_put(arr, sharding))
    g(jax.device_put(arr + 1.0, sharding))
    assert g._cache_size() == 1
