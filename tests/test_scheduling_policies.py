"""Scheduling-policy residue (reference: raylet/scheduling/policy/): node
labels (hard + soft), label_selector, and the deep-queue envelope the
signature-bucketed scheduler is built for."""

import time

import pytest

import ray_tpu
from ray_tpu._private.runtime import get_ctx
from ray_tpu.util.scheduling_strategies import NodeLabelSchedulingStrategy


def test_hard_label_selection(ray_start_regular):
    head = get_ctx().head
    gpuish = head.add_node({"CPU": 2.0}, labels={"accel": "v5e", "zone": "a"})
    head.add_node({"CPU": 2.0}, labels={"accel": "cpu", "zone": "b"})

    @ray_tpu.remote(num_cpus=1)
    def where():
        import ray_tpu as rt

        return rt.get_runtime_context().get_node_id()

    strat = NodeLabelSchedulingStrategy(hard={"accel": "v5e"})
    nodes = set(
        ray_tpu.get(
            [where.options(scheduling_strategy=strat).remote() for _ in range(4)],
            timeout=60,
        )
    )
    assert nodes == {gpuish.hex()}


def test_label_selector_option(ray_start_regular):
    head = get_ctx().head
    target = head.add_node({"CPU": 2.0}, labels={"pool": "inference"})

    @ray_tpu.remote(num_cpus=1)
    def where():
        import ray_tpu as rt

        return rt.get_runtime_context().get_node_id()

    got = ray_tpu.get(
        where.options(label_selector={"pool": "inference"}).remote(), timeout=60
    )
    assert got == target.hex()


def test_soft_labels_prefer_but_fall_back(ray_start_regular):
    head = get_ctx().head
    preferred = head.add_node({"CPU": 1.0}, labels={"tier": "fast"})
    head.add_node({"CPU": 8.0}, labels={"tier": "slow"})

    @ray_tpu.remote(num_cpus=1)
    def where():
        import ray_tpu as rt

        return rt.get_runtime_context().get_node_id()

    strat = NodeLabelSchedulingStrategy(soft={"tier": "fast"})
    # first task lands on the preferred node...
    assert ray_tpu.get(
        where.options(scheduling_strategy=strat).remote(), timeout=60
    ) == preferred.hex()
    # ...and an infeasible-preference task still runs somewhere (soft)
    strat2 = NodeLabelSchedulingStrategy(soft={"tier": "nonexistent"})
    assert ray_tpu.get(
        where.options(scheduling_strategy=strat2).remote(), timeout=60
    )


def test_unsatisfiable_hard_labels_stay_pending(ray_start_regular):
    @ray_tpu.remote
    def nope():
        return 1

    strat = NodeLabelSchedulingStrategy(hard={"planet": "mars"})
    ref = nope.options(scheduling_strategy=strat).remote()
    with pytest.raises(Exception):
        ray_tpu.get(ref, timeout=1.5)  # GetTimeoutError: pending forever
    ray_tpu.cancel(ref)


@pytest.mark.slow
def test_deep_queue_envelope(ray_start_regular):
    """The queued-tasks envelope (SURVEY §3.2 family): a deep backlog of
    infeasible tasks must not degrade scheduling of runnable work — the
    signature-bucketed queue makes the backlog O(1) per scheduling event."""

    @ray_tpu.remote(resources={"never": 1.0})
    def blocked():
        return None

    @ray_tpu.remote
    def runnable(x):
        return x * 2

    t0 = time.perf_counter()
    backlog = [blocked.remote() for _ in range(50_000)]
    submit_rate = 50_000 / (time.perf_counter() - t0)
    assert submit_rate > 5_000, f"submit rate collapsed: {submit_rate:.0f}/s"

    # runnable work schedules promptly THROUGH the backlog
    t0 = time.perf_counter()
    assert ray_tpu.get([runnable.remote(i) for i in range(50)], timeout=60) == [
        2 * i for i in range(50)
    ]
    dt = time.perf_counter() - t0
    assert dt < 10.0, f"runnable tasks starved behind the backlog ({dt:.1f}s)"

    t0 = time.perf_counter()
    for ref in backlog[:1000]:
        ray_tpu.cancel(ref)
    assert time.perf_counter() - t0 < 10.0
    del backlog
