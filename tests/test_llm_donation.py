"""RL013's runtime twin: the use-after-donation bug class is REAL.

``PagedModelRunner`` jits its decode/prefill/verify/fork steps with
``donate_argnums`` on the KV pool buffers (model_runner.py) — each step
scatters into the pool in place instead of copying the biggest array in
inference. The price is the RL013 contract: the moment a step call
dispatches, XLA invalidates the INPUT buffers; any read of the old
``pool.k``/``pool.v`` reference before the engine reassigns them is a
deleted-buffer error (or, on backends that alias without deleting,
silently garbled data).

This module drives the real jitted paged-decode path and pins both
directions, exactly like ``tests/test_llm_weight_swap.py`` twins RL009:

* the pre-call buffer object IS deleted after the call — reading it
  raises — which is the poisoned state RL013's dataflow models;
* the engine's reassign-immediately idiom (``self.pool.k, self.pool.v =
  k, v``) keeps the pool usable and decoding deterministic across
  repeated donated steps, which is the fix the rule's message demands.

Backends may legally ignore donation (older CPU runtimes warn and copy);
a probe skips the strict deletion asserts there so the suite stays
honest about what it proved.
"""

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from ray_tpu.llm.cache import CacheConfig, KVBlockPool  # noqa: E402
from ray_tpu.llm.model_runner import PagedModelRunner  # noqa: E402
from ray_tpu.models.gpt import GPTConfig, gpt_init  # noqa: E402


def _donation_effective() -> bool:
    """Does this backend actually invalidate donated buffers?"""
    x = jnp.arange(4.0)
    jax.jit(lambda a: a + 1, donate_argnums=(0,))(x)
    return x.is_deleted()


DONATION_EFFECTIVE = _donation_effective()

needs_donation = pytest.mark.skipif(
    not DONATION_EFFECTIVE,
    reason="backend ignores buffer donation (copies instead); the "
    "use-after-donation failure mode cannot manifest here",
)

CFG = GPTConfig(
    vocab_size=64, d_model=32, n_layers=2, n_heads=2, seq_len=64,
    dtype="float32",
)


def _runner_and_pool(num_blocks=8, block_size=4, tmax=4):
    params = gpt_init(jax.random.PRNGKey(0), CFG)
    runner = PagedModelRunner(CFG, params, block_size)
    pool = KVBlockPool(
        CacheConfig(
            num_blocks=num_blocks, block_size=block_size,
            max_blocks_per_seq=tmax,
        ),
        n_layers=CFG.n_layers, n_heads=CFG.n_heads, head_dim=CFG.head_dim,
    )
    return runner, pool


def _decode_args(pool, slots=2):
    """(tokens, positions, tables, temp, top_k, top_p, seeds, counters)
    for a greedy decode step with one live block per slot."""
    tables = np.zeros((slots, pool.cfg.max_blocks_per_seq), np.int32)
    tables[:, 0] = 1
    return (
        np.array([3, 5][:slots], np.int32),        # tokens
        np.zeros(slots, np.int32),                 # positions
        tables,
        np.zeros(slots, np.float32),               # temp (greedy)
        np.zeros(slots, np.int32),                 # top_k
        np.ones(slots, np.float32),                # top_p
        np.zeros(slots, np.uint32),                # seeds
        np.zeros(slots, np.int32),                 # counters
    )


@needs_donation
def test_decode_step_invalidates_donated_pool_buffers():
    """The fixture RL013 mirrors (test_raylint.RL013_ENGINE_BAD), run for
    real: keep the old pool.k reference across a decode_step and the read
    blows up with a deleted-buffer error."""
    runner, pool = _runner_and_pool()
    stale_k, stale_v = pool.k, pool.v
    k, v, nxt, logp = runner.decode_step(pool.k, pool.v, *_decode_args(pool))
    assert stale_k.is_deleted() and stale_v.is_deleted()
    with pytest.raises(RuntimeError, match="deleted"):
        np.asarray(stale_k)  # the poisoned read RL013 flags statically
    # the reassign-immediately idiom restores a usable pool
    pool.k, pool.v = k, v
    assert np.asarray(pool.k).shape == stale_k.shape
    assert int(nxt[0]) >= 0


@needs_donation
def test_prefill_and_fork_paths_also_donate():
    """Every jitted pool path donates, not just decode — the rule's
    summary machinery covers prefill_chunk and fork_blocks callers too."""
    runner, pool = _runner_and_pool()
    table = pool.table_row(None)
    table[0] = 1
    old_k = pool.k
    k, v, logits = runner.prefill_chunk(
        pool.k, pool.v, np.array([1, 2, 3, 0], np.int32), 0, 3, table
    )
    assert old_k.is_deleted()
    pool.k, pool.v = k, v
    old_k = pool.k
    z = np.zeros(2, np.int32)
    pool.k, pool.v = runner.fork_blocks(pool.k, pool.v, z, z)
    assert old_k.is_deleted()
    assert logits.shape == (CFG.vocab_size,)


def test_reassigned_pool_decodes_deterministically():
    """Donation with immediate reassignment (the pattern RL013 enforces)
    is semantically clean: two identical fresh runs produce identical
    tokens and logprobs across repeated donated steps. Runs on every
    backend — donating or copying, the OUTPUT contract holds."""

    def run():
        runner, pool = _runner_and_pool()
        out = []
        for step in range(3):
            tokens, positions, tables, temp, tk, tp, seeds, counters = (
                _decode_args(pool)
            )
            positions[:] = step
            counters[:] = step
            k, v, nxt, logp = runner.decode_step(
                pool.k, pool.v, tokens, positions, tables,
                temp, tk, tp, seeds, counters,
            )
            pool.k, pool.v = k, v
            out.append((np.asarray(nxt).copy(), np.asarray(logp).copy()))
        return out

    a, b = run(), run()
    for (ta, la), (tb, lb) in zip(a, b):
        np.testing.assert_array_equal(ta, tb)
        np.testing.assert_allclose(la, lb, rtol=1e-6)


def test_donation_probe_matches_platform_expectation():
    """The probe itself is pinned so a jax upgrade that changes donation
    semantics surfaces here, not as silent skips: on current CPU jax
    (>= 0.4.3x) donation IS effective, and the skip branch above should
    be dead in CI."""
    assert isinstance(DONATION_EFFECTIVE, bool)
    if jax.default_backend() == "cpu" and jax.__version__ >= "0.4.30":
        assert DONATION_EFFECTIVE, (
            "CPU jax stopped honoring donate_argnums — the donated paged "
            "paths (model_runner.py) silently became copies; re-measure "
            "the pool-update cost before trusting this"
        )
